"""Serving benchmark: synthetic Poisson arrivals through the
continuous-batching engine (``distributed_ml_pytorch_tpu/serving/``).

An open-loop load generator: request inter-arrival times are exponential
(rate ``--rate`` req/s), prompt and generation lengths are uniform in the
given ranges, and a fraction of requests sample with temperature/top-k
(the rest decode greedily) — all from one seed, so a run is reproducible.
The driver submits each request when its arrival time passes and spins the
engine's scheduling loop in between; TTFT therefore includes real queueing
delay under load, not just prefill time.

Prints exactly ONE JSON line on stdout (BENCH convention, like
``bench.py``); narration goes to stderr. Runs on whatever the default jax
platform is — CPU in the test rig, the TPU chip under the driver.

    python bench_serving.py --requests 32 --rate 8 --slots 4
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--rate", type=float, default=8.0,
                   help="mean arrival rate, requests/sec (Poisson)")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--cache-size", type=int, default=160)
    p.add_argument("--decode-block", type=int, default=8)
    p.add_argument("--kv-quant", action="store_true")
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--prefill-bucket", type=int, default=16)
    p.add_argument("--prompt-len", type=int, nargs=2, default=(4, 16),
                   metavar=("LO", "HI"))
    p.add_argument("--new-tokens", type=int, nargs=2, default=(8, 48),
                   metavar=("LO", "HI"))
    p.add_argument("--sampled-frac", type=float, default=0.5,
                   help="fraction of requests using temperature sampling")
    # tiny-LM shape: serving overhead is the subject, not model FLOPs
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--d-ff", type=int, default=128)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json-out", type=str, default="",
                   help="also write the result JSON to this file")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.models import TransformerLM
    from distributed_ml_pytorch_tpu.serving.engine import ServingEngine

    lm = TransformerLM(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff,
        max_len=max(args.cache_size, 256))
    params = lm.init(jax.random.key(args.seed),
                     jnp.zeros((1, 8), jnp.int32))["params"]
    engine = ServingEngine(
        lm, params, slots=args.slots, cache_size=args.cache_size,
        decode_block=args.decode_block, kv_quant=args.kv_quant,
        max_queue=args.max_queue, prefill_bucket=args.prefill_bucket)

    rng = np.random.default_rng(args.seed)
    plo, phi = args.prompt_len
    nlo, nhi = args.new_tokens
    arrivals = np.cumsum(rng.exponential(1.0 / args.rate, args.requests))
    plan = [
        dict(
            prompt=rng.integers(0, args.vocab, size=int(rng.integers(plo, phi + 1))),
            max_new_tokens=int(rng.integers(nlo, nhi + 1)),
            **({"temperature": 0.8, "top_k": 16, "seed": int(i)}
               if rng.random() < args.sampled_frac else {}),
        )
        for i in range(args.requests)
    ]

    # warmup: compile EVERY prefill bucket the prompt-length range can hit
    # plus the decode block, outside the timed window (bench.py's
    # traced-call discipline) — a mid-range bucket compiling inside the
    # loop would land XLA compile time in the TTFT percentiles
    log("warmup: compiling prefill buckets + decode block ...")
    for bucket_len in sorted({
            max(2, -(-int(L) // args.prefill_bucket) * args.prefill_bucket)
            for L in range(plo, phi + 1)}):
        # a bucket-length prompt maps exactly to its own bucket (a shorter
        # one can fall into a smaller bucket at --prefill-bucket 1)
        w = engine.submit(np.zeros(bucket_len, np.int32),
                          args.decode_block + 2)
        engine.run_until_idle()
        assert w.done
    engine.reset_metrics()  # warmup must not pollute the SLO samples

    log(f"offered load: {args.requests} requests at {args.rate}/s "
        f"(prompts {plo}-{phi}, {nlo}-{nhi} new tokens, "
        f"{args.slots} slots, block {args.decode_block}"
        + (", int8 kv" if args.kv_quant else "") + ")")
    handles = []
    next_i = 0
    t0 = time.perf_counter()
    while len(handles) < args.requests or not all(h.done for h in handles):
        now = time.perf_counter() - t0
        while next_i < args.requests and arrivals[next_i] <= now:
            handles.append(engine.submit(**plan[next_i]))
            next_i += 1
        if not engine.step():
            if next_i < args.requests:
                time.sleep(min(0.002, max(0.0, arrivals[next_i] - now)))
    wall = time.perf_counter() - t0

    total_tokens = sum(len(h.tokens) for h in handles)
    summary = engine.slo_summary()
    throughput = total_tokens / wall
    log(f"served {args.requests} requests / {total_tokens} tokens "
        f"in {wall:.2f}s -> {throughput:.1f} tok/s on "
        f"{jax.devices()[0].platform}")

    result = {
        "metric": "serving_decode_throughput",
        "value": round(throughput, 2),
        "unit": "tokens/sec",
        "requests": args.requests,
        "offered_rate_rps": args.rate,
        "wall_s": round(wall, 3),
        "ttft_ms": summary["ttft_ms"],
        "tpot_ms": summary["tpot_ms"],
        "queue_depth": summary["queue_depth"],
        "slot_occupancy": round(summary["slot_occupancy"], 4),
        "slots": args.slots,
        "decode_block": args.decode_block,
        "kv_quant": bool(args.kv_quant),
        "platform": jax.devices()[0].platform,
    }
    line = json.dumps(result)
    print(line)
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            fh.write(line + "\n")
        log(f"wrote {args.json_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
