"""Serving benchmark: synthetic arrivals through the continuous-batching
engine (``distributed_ml_pytorch_tpu/serving/``), fleet mode included.

An open-loop load generator with four arrival mixes (``--arrival``):

- ``poisson``  — exponential inter-arrivals at ``--rate`` (the original);
- ``diurnal``  — a sinusoidally-modulated Poisson process (mean ``--rate``,
  peak/trough ±``--diurnal-amp``, one full "day" per ``--diurnal-period``
  seconds of bench time) via thinning;
- ``bursty``   — a two-state Markov-modulated Poisson process: ON windows
  at ``burst_factor × rate`` alternating with near-idle OFF windows;
- ``herd``     — thundering herd: ``--herd-frac`` of all requests arrive in
  one instant at the front, the rest Poisson behind them.

Goodput is measured **under SLO, not just throughput** (ISSUE 6): every
request carries ``--deadline-ms`` (0 = off) and a priority from
``--priority-levels``; the JSON reports ``goodput_slo_tok_s`` (tokens of
requests that completed within their deadline / wall), ``shed_rate``
(explicitly rejected / offered) and, in fleet mode, the migration MTTR.

``--engines N`` (N >= 2) runs the FULL fleet path — N engine replicas
behind a :class:`~distributed_ml_pytorch_tpu.serving.fleet.FleetRouter`,
an in-process transport, and a real client — and ``--kill-engine-at T``
crashes one replica T seconds into the run, so the JSON's MTTR and
goodput price an engine death, not a happy path.

Prints exactly ONE JSON line on stdout (BENCH convention, like
``bench.py``); narration goes to stderr. Runs on whatever the default jax
platform is — CPU in the test rig, the TPU chip under the driver.

    python bench_serving.py --requests 32 --rate 8 --slots 4
    python bench_serving.py --engines 3 --kill-engine-at 2 --deadline-ms 4000
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--rate", type=float, default=8.0,
                   help="mean arrival rate, requests/sec")
    p.add_argument("--arrival", default="poisson",
                   choices=["poisson", "diurnal", "bursty", "herd"])
    p.add_argument("--diurnal-amp", type=float, default=0.8,
                   help="diurnal modulation depth in [0,1)")
    p.add_argument("--diurnal-period", type=float, default=8.0,
                   help="seconds per synthetic 'day'")
    p.add_argument("--burst-factor", type=float, default=6.0,
                   help="ON-state rate multiplier (bursty)")
    p.add_argument("--burst-on", type=float, default=0.5,
                   help="mean ON-window seconds (bursty)")
    p.add_argument("--burst-off", type=float, default=1.5,
                   help="mean OFF-window seconds (bursty)")
    p.add_argument("--herd-frac", type=float, default=0.5,
                   help="fraction of requests arriving at t=0 (herd)")
    p.add_argument("--deadline-ms", type=int, default=0,
                   help="per-request completion deadline (0 = no SLO; "
                        "goodput then equals throughput)")
    p.add_argument("--priority-levels", type=int, default=1,
                   help="requests draw priority uniformly from [0, L) — "
                        "the overload plane sheds lowest first")
    # fleet mode
    p.add_argument("--engines", type=int, default=1,
                   help=">= 2 runs the FleetRouter path (full transport + "
                        "client); 1 drives one engine directly")
    p.add_argument("--kill-engine-at", type=float, default=0.0,
                   help="crash one replica this many seconds into the "
                        "fleet run (0 = no kill) — prices migration")
    # autoscale mode (ISSUE 16): the coordinator's check_engine_scaling
    # advisory drives a REAL spawn/retire loop (FleetAutoscaler) instead
    # of just logging advice; the JSON reports the scale-up MTTR
    p.add_argument("--autoscale", action="store_true",
                   help="run a coordinator whose scaling advice actually "
                        "spawns/retires replicas (implies the fleet path)")
    p.add_argument("--autoscale-max", type=int, default=4,
                   help="replica ceiling for the autoscaler")
    p.add_argument("--scale-occ-high", type=float, default=0.85,
                   help="mean engine occupancy that advises scale-UP")
    p.add_argument("--scale-occ-low", type=float, default=0.15,
                   help="mean engine occupancy that advises scale-DOWN")
    p.add_argument("--scale-cooldown", type=float, default=1.0,
                   help="seconds between scaling decisions")
    p.add_argument("--shed-occupancy", type=float, default=0.0)
    p.add_argument("--brownout-occupancy", type=float, default=0.0)
    p.add_argument("--brownout-max-new", type=int, default=0)
    p.add_argument("--slo-ttft-ms", type=float, default=0.0)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--cache-size", type=int, default=160)
    p.add_argument("--decode-block", type=int, default=8)
    p.add_argument("--kv-quant", action="store_true")
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--prefill-bucket", type=int, default=16)
    p.add_argument("--prompt-len", type=int, nargs=2, default=(4, 16),
                   metavar=("LO", "HI"))
    p.add_argument("--new-tokens", type=int, nargs=2, default=(8, 48),
                   metavar=("LO", "HI"))
    p.add_argument("--sampled-frac", type=float, default=0.5,
                   help="fraction of requests using temperature sampling")
    # tiny-LM shape: serving overhead is the subject, not model FLOPs
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--d-ff", type=int, default=128)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json-out", type=str, default="",
                   help="also write the result JSON to this file")
    return p


def make_arrivals(args, rng: np.random.Generator) -> np.ndarray:
    """Sorted arrival times (seconds from bench start) for ``--requests``
    requests under the chosen mix. Pure function of (args, rng) so a run
    is reproducible from its seed."""
    n, rate = args.requests, args.rate
    if args.arrival == "poisson":
        return np.cumsum(rng.exponential(1.0 / rate, n))
    if args.arrival == "diurnal":
        # thinning: candidates at the peak rate, kept w.p. rate(t)/peak
        amp = min(max(args.diurnal_amp, 0.0), 0.99)
        peak = rate * (1.0 + amp)
        out, t = [], 0.0
        while len(out) < n:
            t += rng.exponential(1.0 / peak)
            lam = rate * (1.0 + amp * np.sin(
                2.0 * np.pi * t / args.diurnal_period))
            if rng.uniform() * peak < lam:
                out.append(t)
        return np.asarray(out)
    if args.arrival == "bursty":
        # MMPP-2: exponential ON/OFF sojourns, Poisson within each state
        out, t, on = [], 0.0, True
        while len(out) < n:
            dwell = rng.exponential(args.burst_on if on else args.burst_off)
            lam = rate * (args.burst_factor if on else 0.1)
            tt = t + rng.exponential(1.0 / lam) if lam > 0 else t + dwell
            while tt < t + dwell and len(out) < n:
                out.append(tt)
                tt += rng.exponential(1.0 / lam)
            t += dwell
            on = not on
        return np.asarray(out)
    if args.arrival == "herd":
        k = int(round(n * min(max(args.herd_frac, 0.0), 1.0)))
        herd = np.zeros(k)  # everyone at once: the adversarial front
        tail = np.cumsum(rng.exponential(1.0 / rate, n - k)) if n > k else []
        return np.sort(np.concatenate([herd, np.asarray(tail)]))
    raise ValueError(f"unknown arrival mix {args.arrival!r}")


def make_plan(args, rng: np.random.Generator):
    plo, phi = args.prompt_len
    nlo, nhi = args.new_tokens
    return [
        dict(
            prompt=rng.integers(
                0, args.vocab, size=int(rng.integers(plo, phi + 1))),
            max_new_tokens=int(rng.integers(nlo, nhi + 1)),
            priority=int(rng.integers(0, max(1, args.priority_levels))),
            **({"temperature": 0.8, "top_k": 16, "seed": int(i)}
               if rng.random() < args.sampled_frac else {}),
        )
        for i in range(args.requests)
    ]


def _build_engine(args):
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.models import TransformerLM
    from distributed_ml_pytorch_tpu.serving.engine import ServingEngine

    lm = TransformerLM(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff,
        max_len=max(args.cache_size, 256))
    params = lm.init(jax.random.key(args.seed),
                     jnp.zeros((1, 8), jnp.int32))["params"]

    def make():
        return ServingEngine(
            lm, params, slots=args.slots, cache_size=args.cache_size,
            decode_block=args.decode_block, kv_quant=args.kv_quant,
            max_queue=args.max_queue, prefill_bucket=args.prefill_bucket)

    return make


def _warmup(args, engine) -> None:
    # warmup: compile EVERY prefill bucket the prompt-length range can hit
    # plus the decode block, outside the timed window (bench.py's
    # traced-call discipline) — a mid-range bucket compiling inside the
    # loop would land XLA compile time in the TTFT percentiles
    plo, phi = args.prompt_len
    for bucket_len in sorted({
            max(2, -(-int(L) // args.prefill_bucket) * args.prefill_bucket)
            for L in range(plo, phi + 1)}):
        # a bucket-length prompt maps exactly to its own bucket (a shorter
        # one can fall into a smaller bucket at --prefill-bucket 1)
        w = engine.submit(np.zeros(bucket_len, np.int32),
                          args.decode_block + 2)
        engine.run_until_idle()
        assert w.done
    engine.reset_metrics()  # warmup must not pollute the SLO samples


def run_single(args) -> dict:
    """One engine driven directly (the original path + SLO accounting)."""
    rng = np.random.default_rng(args.seed)
    engine = _build_engine(args)()
    _warmup(args, engine)
    arrivals = make_arrivals(args, rng)
    plan = make_plan(args, rng)
    for spec in plan:
        spec.pop("priority", None)  # engine API has no overload plane
    log(f"offered load: {args.requests} requests, {args.arrival} arrivals "
        f"at {args.rate}/s mean")
    handles, deadlines = [], []
    next_i = 0
    t0 = time.perf_counter()
    while len(handles) < args.requests or not all(h.done for h in handles):
        now = time.perf_counter() - t0
        while next_i < args.requests and arrivals[next_i] <= now:
            handles.append(engine.submit(**plan[next_i]))
            deadlines.append(
                now + args.deadline_ms / 1e3 if args.deadline_ms else None)
            next_i += 1
        if not engine.step():
            if next_i < args.requests:
                time.sleep(min(0.002, max(0.0, arrivals[next_i] - now)))
    wall = time.perf_counter() - t0
    good_tokens = total_tokens = 0
    met = 0
    for h, dl in zip(handles, deadlines):
        total_tokens += len(h.tokens)
        done_at = h.t_done - t0
        within = dl is None or done_at <= dl
        if within:
            met += 1
            good_tokens += len(h.tokens)
    return {
        "engine": engine, "wall": wall, "total_tokens": total_tokens,
        "good_tokens": good_tokens, "completed_in_slo": met,
        "shed": 0, "rejected_client_side": 0, "mttr_s": None,
        "migrations": 0, "summary": engine.slo_summary(),
    }


def run_fleet(args) -> dict:
    """N replicas behind a FleetRouter over a real in-process transport;
    optional mid-run engine kill to price migration."""
    import threading

    from distributed_ml_pytorch_tpu.serving.fleet import (
        EngineMember,
        FleetRouter,
    )
    from distributed_ml_pytorch_tpu.serving.frontend import ServingClient
    from distributed_ml_pytorch_tpu.utils.messaging import InProcessTransport

    rng = np.random.default_rng(args.seed)
    make = _build_engine(args)
    engines = [make() for _ in range(args.engines)]
    for e in engines:
        _warmup(args, e)
    coord = coord_thread = autoscaler = None
    if args.autoscale:
        # the full advisory->actuator loop: engine members lease into a
        # real coordinator, renewals carry occupancy/TTFT, and the
        # coordinator's check_engine_scaling advice lands on a
        # FleetAutoscaler that spawns/retires replicas on the router
        from distributed_ml_pytorch_tpu.coord.coordinator import Coordinator
        from distributed_ml_pytorch_tpu.coord.member import CoordClient
        from distributed_ml_pytorch_tpu.serving.fleet import FleetAutoscaler

        cap = max(args.autoscale_max, args.engines)
        coord_world = InProcessTransport.create_world(1 + cap)
        coord = Coordinator(
            coord_world[0], 1, lease=2.0, speculation=False,
            engine_occ_high=args.scale_occ_high,
            engine_occ_low=args.scale_occ_low,
            scale_cooldown=args.scale_cooldown)
        coord_thread = threading.Thread(
            target=coord.run, name="bench-coord", daemon=True)
        coord_thread.start()

        def _member(eid: int, engine) -> EngineMember:
            client = CoordClient(coord_world[1 + eid], "engine",
                                 renew_interval=0.1)
            return EngineMember(eid, engine, coord=client,
                                report_interval=0.1)

        members = [_member(i, e).start() for i, e in enumerate(engines)]
    else:
        members = [EngineMember(i, e).start() for i, e in enumerate(engines)]
    world = InProcessTransport.create_world(2)
    router = FleetRouter(
        world[0], members, probe_timeout=0.5,
        # the raw frame collector below never sends StreamAck, so the
        # silent-client reaper must stay out of the way — a reaped stream
        # would be counted as a (truncated) completion
        client_deadline=3600.0,
        slo_ttft_ms=args.slo_ttft_ms, shed_occupancy=args.shed_occupancy,
        brownout_occupancy=args.brownout_occupancy,
        brownout_max_new=args.brownout_max_new)
    if args.autoscale:
        def member_factory() -> EngineMember:
            used = set(router.members.keys())
            eid = next(i for i in range(cap) if i not in used)
            engine = make()
            _warmup(args, engine)
            engines.append(engine)
            log(f"autoscaler: spawning engine {eid}")
            return _member(eid, engine)

        autoscaler = FleetAutoscaler(
            router, member_factory, min_engines=1, max_engines=cap)
        coord.on_scale = autoscaler.on_scale
    server = threading.Thread(target=router.serve_forever, daemon=True)
    server.start()
    client = ServingClient(world[1])
    arrivals = make_arrivals(args, rng)
    plan = make_plan(args, rng)
    log(f"fleet: {args.engines} engines, {args.requests} requests, "
        f"{args.arrival} arrivals at {args.rate}/s mean"
        + (f", kill at {args.kill_engine_at}s" if args.kill_engine_at
           else ""))
    # collector state: rid -> [tokens, done_at, rejected]
    state = {}
    t0 = time.perf_counter()
    next_i, killed = 0, False
    submitted = []
    while True:
        now = time.perf_counter() - t0
        if (args.kill_engine_at and not killed
                and now >= args.kill_engine_at):
            members[0].crash()  # silent death; the router's probe detects
            killed = True
            log(f"killed engine 0 at {now:.2f}s")
        while next_i < args.requests and arrivals[next_i] <= now:
            spec = dict(plan[next_i])
            rid = client.submit(
                spec.pop("prompt"), spec.pop("max_new_tokens"),
                priority=spec.pop("priority", 0),
                deadline_ms=args.deadline_ms, **spec)
            state[rid] = [[], None, False]
            submitted.append(rid)
            next_i += 1
        # drain frames without the generator machinery (lossless wire)
        msg = world[1].recv(timeout=0.002)
        if msg is not None:
            _s, code, payload = msg
            if payload.size >= 1:
                rid = int(payload[0])
                entry = state.get(rid)
                if entry is not None:
                    from distributed_ml_pytorch_tpu.utils.messaging import (
                        MessageCode,
                    )

                    if code == MessageCode.ServeReject:
                        entry[2] = True
                        entry[1] = time.perf_counter() - t0
                    elif code == MessageCode.StreamTokens \
                            and payload.size >= 3:
                        start = int(payload[2])
                        toks = payload[3:].astype(np.int32).tolist()
                        have = entry[0]
                        fresh = toks[max(0, len(have) - start):]
                        if start <= len(have) and fresh:
                            have.extend(fresh)
                        if payload[1] and entry[1] is None \
                                and start + len(toks) <= len(have):
                            entry[1] = time.perf_counter() - t0
        if next_i >= args.requests and all(
                s[1] is not None for s in state.values()):
            break
        if time.perf_counter() - t0 > 600:
            log("bench safety timeout: giving up on stragglers")
            break
    wall = time.perf_counter() - t0
    router.stop()
    server.join(timeout=5)
    for t in world.values():
        t.close()
    autoscale_info = None
    if autoscaler is not None:
        autoscaler.quiesce()
        autoscale_info = autoscaler.summary()
        coord.stop()
        coord_thread.join(timeout=5)
        for t in coord_world.values():
            t.close()
        log(f"autoscaler: {autoscale_info}")
    good_tokens = total_tokens = met = shed = 0
    for i, rid in enumerate(submitted):
        toks, done_at, rejected = state[rid]
        total_tokens += len(toks)
        if rejected:
            shed += 1
            continue
        if done_at is None:
            continue
        dl = (arrivals[i] + args.deadline_ms / 1e3
              if args.deadline_ms else None)
        if dl is None or done_at <= dl:
            met += 1
            good_tokens += len(toks)
    return {
        "engine": engines[-1], "wall": wall, "total_tokens": total_tokens,
        "good_tokens": good_tokens, "completed_in_slo": met,
        "shed": router.shed + router.migration_failures,
        "rejected_client_side": shed, "mttr_s": router.mttr_s(),
        "migrations": router.migrations,
        "summary": engines[-1].slo_summary(),
        "autoscale": autoscale_info,
    }


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    import jax

    r = (run_fleet(args) if args.engines >= 2 or args.autoscale
         else run_single(args))
    wall, total = r["wall"], r["total_tokens"]
    throughput = total / wall if wall > 0 else 0.0
    goodput = r["good_tokens"] / wall if wall > 0 else 0.0
    summary = r["summary"]
    log(f"served {args.requests} requests / {total} tokens in {wall:.2f}s "
        f"-> {throughput:.1f} tok/s ({goodput:.1f} goodput-under-SLO) on "
        f"{jax.devices()[0].platform}")

    result = {
        "metric": "serving_decode_throughput",
        "value": round(throughput, 2),
        "unit": "tokens/sec",
        "requests": args.requests,
        "offered_rate_rps": args.rate,
        "arrival": args.arrival,
        "wall_s": round(wall, 3),
        # --- goodput under SLO, not just throughput (ISSUE 6) ---
        "deadline_ms": args.deadline_ms,
        "goodput_slo_tok_s": round(goodput, 2),
        "completed_in_slo": r["completed_in_slo"],
        "shed": r["shed"],
        "shed_rate": round(r["rejected_client_side"] / args.requests, 4),
        "migrations": r["migrations"],
        "migration_mttr_s": (round(r["mttr_s"], 4)
                             if r["mttr_s"] is not None else None),
        "engines": args.engines,
        # --- autoscale loop (ISSUE 16): advice -> actual spawn/retire ---
        "autoscaled": bool(r.get("autoscale")),
        "scaled_up": (r["autoscale"]["scaled_up"]
                      if r.get("autoscale") else 0),
        "scaled_down": (r["autoscale"]["scaled_down"]
                        if r.get("autoscale") else 0),
        "scale_up_mttr_s": (
            round(float(np.mean(r["autoscale"]["scale_up_mttr_s"])), 4)
            if r.get("autoscale") and r["autoscale"]["scale_up_mttr_s"]
            else None),
        "ttft_ms": summary["ttft_ms"],
        "tpot_ms": summary["tpot_ms"],
        "queue_depth": summary["queue_depth"],
        "slot_occupancy": round(summary["slot_occupancy"], 4),
        "slots": args.slots,
        "decode_block": args.decode_block,
        "kv_quant": bool(args.kv_quant),
        "platform": jax.devices()[0].platform,
    }
    line = json.dumps(result)
    print(line)
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            fh.write(line + "\n")
        log(f"wrote {args.json_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
