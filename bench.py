"""Benchmark harness (BASELINE.md config #1, the reference's headline workload).

Measures steady-state training throughput (images/sec/chip) of the flagship
AlexNet on CIFAR-10-shaped data with the reference training recipe — batch 64,
SGD lr 0.008 (reference ``example/main.py:142,144-145``) — on the default jax
device (the TPU chip under the driver; CPU elsewhere).

``vs_baseline`` is measured, not assumed: the same workload (same architecture,
same batch, same optimizer) is timed in torch on CPU — the reference's own
``make single`` configuration (reference ``Makefile:23``; the reference
publishes no numbers, BASELINE.md, so its baseline must be produced). The
printed ratio is TPU-images/sec over torch-CPU-images/sec.

Prints exactly ONE JSON line on stdout; all narration goes to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BATCH = 64
LR = 0.008
WARMUP = 10
STEPS = 100
BASELINE_STEPS = 12


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_batch(batch: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    images = rng.normal(size=(batch, 32, 32, 3)).astype(np.float32)
    labels = (np.arange(batch) % 10).astype(np.int32)
    return images, labels


def bench_jax(batch: int = BATCH, steps: int = STEPS, warmup: int = WARMUP) -> float:
    """images/sec of the jitted AlexNet train step on the default device."""
    import jax

    from distributed_ml_pytorch_tpu.models import AlexNet
    from distributed_ml_pytorch_tpu.training.trainer import (
        create_train_state,
        make_train_step,
    )

    model = AlexNet(num_classes=10)
    state, tx = create_train_state(model, jax.random.key(0), lr=LR)
    train_step = make_train_step(model, tx)
    images, labels = make_batch(batch)
    images = jax.device_put(images)
    labels = jax.device_put(labels)
    rng = jax.random.key(1)

    for _ in range(warmup):
        state, loss = train_step(state, images, labels, rng)
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = train_step(state, images, labels, rng)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0
    dev = jax.devices()[0]
    log(f"jax [{dev.platform}]: {steps} steps of batch {batch} in {dt:.3f}s "
        f"→ {steps * batch / dt:.1f} img/s, final loss {float(loss):.4f}")
    return steps * batch / dt


def bench_torch_cpu(batch: int = BATCH, steps: int = BASELINE_STEPS) -> float | None:
    """images/sec of the reference workload (torch CPU, same recipe).

    The model is the reference's CIFAR AlexNet re-stated from its architecture
    spec (SURVEY.md C7: five convs 3→64 k11 s4 p5 / 64→192 k5 p2 / 192→384 k3
    p1 / 384→256 k3 p1 / 256→256 k3 p1, three 2×2 maxpools, Linear(256, 10)).
    """
    try:
        import torch
        import torch.nn as tnn
        import torch.nn.functional as F
    except Exception as e:  # torch unavailable: no measured baseline
        log(f"torch baseline unavailable: {e}")
        return None

    torch.manual_seed(0)

    model = tnn.Sequential(
        tnn.Conv2d(3, 64, 11, stride=4, padding=5), tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Conv2d(64, 192, 5, padding=2), tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Conv2d(192, 384, 3, padding=1), tnn.ReLU(),
        tnn.Conv2d(384, 256, 3, padding=1), tnn.ReLU(),
        tnn.Conv2d(256, 256, 3, padding=1), tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Flatten(),
        tnn.Linear(256, 10),
    )
    opt = torch.optim.SGD(model.parameters(), lr=LR, momentum=0.0)
    images_np, labels_np = make_batch(batch)
    images = torch.from_numpy(images_np.transpose(0, 3, 1, 2).copy())  # NCHW
    labels = torch.from_numpy(labels_np.astype(np.int64))

    def step():
        opt.zero_grad()
        loss = F.cross_entropy(model(images), labels)
        loss.backward()
        opt.step()
        return loss.detach()

    for _ in range(2):
        step()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step()
    dt = time.perf_counter() - t0
    log(f"torch [cpu]: {steps} steps of batch {batch} in {dt:.3f}s "
        f"→ {steps * batch / dt:.1f} img/s, final loss {float(loss):.4f}")
    return steps * batch / dt


def main() -> None:
    ips = bench_jax()
    base = bench_torch_cpu()
    vs = round(ips / base, 2) if base else None  # null = baseline not measurable here
    print(json.dumps({
        "metric": "alexnet_cifar10_train_throughput_per_chip",
        "value": round(ips, 1),
        "unit": "images/sec/chip",
        "vs_baseline": vs,
    }), flush=True)


if __name__ == "__main__":
    main()
