"""Benchmark harness (BASELINE.md config #1, the reference's headline workload).

Measures steady-state training throughput (images/sec/chip) of the flagship
AlexNet on CIFAR-10-shaped data on the default jax device (the TPU chip
under the driver; CPU elsewhere), as THREE first-class legs reported side
by side in one JSON record (round 9 — the ceiling the round-5 audit
measured is now the shipped number):

- ``parity_b64`` — the reference training recipe exactly (batch 64, SGD
  lr 0.008, reference ``example/main.py:142,144-145``): the parity leg
  every trajectory/steps-to-accuracy comparison anchors to.
- ``large_batch_b1024`` — the identical architecture at batch 1024 with
  Pallas-fused conv epilogues (``ops/fused_conv.py``): the throughput
  leg, and the record's headline ``value``.
- ``grad_accum_b1024`` — batch 1024 as a microbatch-256 accumulation
  scan whose applied update is scaled to the SUM of the sixteen
  batch-64 mean-gradient updates at frozen params
  (``make_accum_train_step(effective_update_batch=64)``): large-batch
  geometry, batch-64-recipe effective update (first-order).

Every leg records its ``mfu_floor`` from ``bench_floors.json``; ``--gate``
re-checks measured MFU against the floors and exits non-zero on a breach
(``--json FILE`` gates a canned/previous record with no device run — the
``make bench-gate`` tier-1 smoke), so the headline can never silently
regress below its recorded floor again.

``vs_baseline`` is measured, not assumed: the reference's own workload
(``make single`` configuration, batch 64) is timed in torch on CPU (the
reference publishes no numbers, BASELINE.md). The printed ratio is the
headline leg's images/sec over torch-CPU-images/sec; the baseline keeps
the reference's fixed recipe because that IS the baseline.

Prints exactly ONE JSON line on stdout; all narration goes to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BATCH = 64
LR = 0.008
SCAN_K = 100       # steps fused into one compiled program (lax.scan)
LARGE_BATCH = 1024       # the throughput legs' batch (audited plateau zone)
LARGE_SCAN_K = 20        # updates per compiled program for the large legs
ACCUM_MICROBATCH = 256   # grad-accum leg: 4 microbatches per update
EFFECTIVE_UPDATE = 64    # ...whose update preserves the batch-64 recipe
N_SHORT, N_LONG = 1, 41  # dispatch counts for the differenced measurement
                         # (long leg ≈ 4000 steps so RTT jitter is small
                         # relative to the compute being measured)
TRIALS = 5         # report the median differenced estimate
BASELINE_STEPS = 12
FLOORS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bench_floors.json")
HEADLINE_LEG = "large_batch_b1024"


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class Rate(float):
    """images/sec (or tokens/sec) that also carries the leg's FLOPs story:
    ``.flops_per_step`` (XLA's count for one step), ``.tflops`` (achieved),
    ``.mfu`` (fraction of the chip's bf16 peak) — any may be None when the
    backend doesn't report flops or the device kind has no peak entry."""

    flops_per_step: float | None = None
    tflops: float | None = None
    mfu: float | None = None

    @staticmethod
    def make(value: float, flops_per_step, step_seconds) -> "Rate":
        from distributed_ml_pytorch_tpu.utils.flops import utilization

        r = Rate(value)
        r.flops_per_step = flops_per_step
        r.tflops, r.mfu = utilization(flops_per_step, step_seconds)
        return r

    def mfu_note(self) -> str:
        """Human fragment for BASELINE notes: '12.3 TFLOP/s, 6.2% MFU'."""
        if self.tflops is None:
            return "flops not reported by backend"
        if self.mfu is None:
            return f"{self.tflops:.1f} TFLOP/s (no peak table for device)"
        return f"{self.tflops:.1f} TFLOP/s, {self.mfu:.1%} MFU"

    def record_fields(self) -> dict:
        """The FLOPs story as JSON record fields — the single serialization
        used by bench.py's headline and bench_all's emit."""
        rec = {}
        if self.tflops is not None:
            rec["flops_per_step"] = self.flops_per_step
            rec["tflops"] = round(self.tflops, 2)
            if self.mfu is not None:
                rec["mfu"] = round(self.mfu, 4)
        return rec


def make_batch(batch: int, seed: int = 0, k: int = 0,
               shape: tuple = (32, 32, 3), n_classes: int = 10):
    """Synthetic image batch (CIFAR-shaped by default); ``k > 0`` stacks k
    distinct microbatches on a leading axis (for the scanned trainer)."""
    rng = np.random.default_rng(seed)
    n = (k or 1) * batch
    images = rng.normal(size=(n, *shape)).astype(np.float32)
    labels = (np.arange(n) % n_classes).astype(np.int32)
    if k:
        return images.reshape(k, batch, *shape), labels.reshape(k, batch)
    return images, labels


def bench_jax(batch: int = BATCH, k: int | None = None, model=None,
              input_shape: tuple = (32, 32, 3), n_classes: int = 10,
              n_long: int | None = None, trials: int | None = None,
              step_builder=None, flops_override=None) -> float:
    """Steady-state images/sec of the scanned AlexNet trainer on the default
    device.

    Measurement boundary — stated precisely because naive timing lies twice
    on this setup: (a) K distinct microbatches train inside ONE compiled
    program (``make_scan_train_step``'s ``lax.scan``), so host dispatch is
    amortized — the framework's idiomatic execution for small models; (b) on
    a tunneled device, ``block_until_ready`` can return before the device
    finishes and a device→host fetch costs a large fixed RTT, so the number
    reported is the **differenced steady state**: time(N_LONG dispatches) −
    time(N_SHORT dispatches), each ended by fetching the final scalar loss
    (a true data dependency), divided by the extra steps. The fixed RTT
    cancels; what remains is per-step device time.

    ``step_builder(model, tx)`` overrides the compiled program (default
    ``make_scan_train_step``; the grad-accum leg passes
    ``make_scan_accum_train_step``) — it must keep the
    ``(state, images [k,B,...], labels [k,B], rng) → (state, losses [k])``
    contract so the timing/flops machinery applies unchanged.
    ``flops_override`` replaces XLA's per-dispatch flop count for legs
    whose program nests a scan (cost_analysis counts each scan body ONCE,
    so a microbatch scan inside the update body under-reports by the
    microbatch count; the caller passes the equivalent plain-step count).
    """
    import jax

    from distributed_ml_pytorch_tpu.models import AlexNet
    from distributed_ml_pytorch_tpu.training.trainer import (
        create_train_state,
        make_scan_train_step,
    )

    # the RTT-differencing machinery exists for the tunneled TPU; on a local
    # CPU/GPU device a fraction of the workload measures the same thing in
    # seconds instead of tens of minutes
    n_short = N_SHORT
    if jax.devices()[0].platform != "tpu":
        if k is None:  # shrink only the default workload, not a caller's k
            k = 10
        n_long, trials = n_long or 3, trials or 2
    else:
        k = SCAN_K if k is None else k
        n_long, trials = n_long or N_LONG, trials or TRIALS

    model = model if model is not None else AlexNet(num_classes=10)
    state, tx = create_train_state(
        model, jax.random.key(0), lr=LR, sample_shape=(1, *input_shape)
    )
    train_scan = (step_builder or make_scan_train_step)(model, tx)
    images, labels = make_batch(batch, k=k, shape=input_shape, n_classes=n_classes)
    images = jax.device_put(images)
    labels = jax.device_put(labels)
    rng = jax.random.key(1)

    losses = None
    for _ in range(2):  # compile + cache warmup
        state, losses = train_scan(state, images, labels, rng)
    float(losses[-1])

    dev = jax.devices()[0]
    if dev.platform == "tpu":
        # device-true timing (round 3): the profiler's device spans are
        # deterministic to the microsecond where host-differenced timing
        # through the tunnel swings 2-3x run to run (utils/devtime).
        # ``trials`` sets the traced call count; n_short/n_long belong to
        # the off-TPU differencing fallback below
        from distributed_ml_pytorch_tpu.utils.devtime import device_time

        holder = {"s": state, "l": losses}

        def one_call():
            holder["s"], holder["l"] = train_scan(
                holder["s"], images, labels, rng)
            return holder["l"]

        t = device_time(one_call, calls=max(2, trials), warmup=1)
        per_step = t.per_call_s / k
        state, losses = holder["s"], holder["l"]
        log(f"  device-true: {t.per_call_ms:.2f} ms per {k}-step scan "
            f"({t.calls} traced calls)")
    else:
        def timed(n_dispatches: int) -> float:
            nonlocal state, losses
            t0 = time.perf_counter()
            for _ in range(n_dispatches):
                state, losses = train_scan(state, images, labels, rng)
            float(losses[-1])  # forces completion of the whole chain
            return time.perf_counter() - t0

        shorts, longs = [], []
        for trial in range(trials):
            shorts.append(timed(n_short))
            longs.append(timed(n_long))
            log(f"  trial {trial}: T({n_short})={shorts[-1] * 1e3:.0f}ms "
                f"T({n_long})={longs[-1] * 1e3:.0f}ms")
        # min-min differencing: each leg's minimum is its fixed RTT + true
        # compute with the least noise; their difference cancels the RTT
        # without a single trial's jitter polluting both terms
        extra_steps = (n_long - n_short) * k
        per_step = (min(longs) - min(shorts)) / extra_steps
    from distributed_ml_pytorch_tpu.utils.flops import compiled_flops

    # XLA's cost_analysis counts a lax.scan body ONCE (not x trip count —
    # verified against a bare scanned matmul), so the k-step scan program's
    # reported flops ARE the per-step flops (+ negligible outside-body ops)
    if flops_override is not None:
        scan_flops = flops_override
    else:
        scan_flops = compiled_flops(train_scan, state, images, labels, rng)
    rate = Rate.make(batch / per_step, scan_flops, per_step)
    method = ("device-true trace" if dev.platform == "tpu"
              else f"min-min differenced over {trials} trials")
    log(f"jax [{dev.platform}]: {method}, batch {batch}, {k}-step scans → "
        f"{per_step * 1e6:.1f} us/step, "
        f"{rate:.1f} img/s ({rate.mfu_note()}), final loss {float(losses[-1]):.4f}")
    return rate


def make_torch_alexnet():
    """The reference's CIFAR AlexNet as one torch Sequential (SURVEY.md C7) —
    the single spec shared by the throughput baseline here and the
    steps-to-accuracy comparison in ``bench_all.py``."""
    import torch.nn as tnn

    return tnn.Sequential(
        tnn.Conv2d(3, 64, 11, stride=4, padding=5), tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Conv2d(64, 192, 5, padding=2), tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Conv2d(192, 384, 3, padding=1), tnn.ReLU(),
        tnn.Conv2d(384, 256, 3, padding=1), tnn.ReLU(),
        tnn.Conv2d(256, 256, 3, padding=1), tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Flatten(),
        tnn.Linear(256, 10),
    )


def bench_torch_cpu(batch: int = BATCH, steps: int = BASELINE_STEPS) -> float | None:
    """images/sec of the reference workload (torch CPU, same recipe).

    The model is the reference's CIFAR AlexNet re-stated from its architecture
    spec (SURVEY.md C7: five convs 3→64 k11 s4 p5 / 64→192 k5 p2 / 192→384 k3
    p1 / 384→256 k3 p1 / 256→256 k3 p1, three 2×2 maxpools, Linear(256, 10)).
    """
    try:
        import torch
        import torch.nn.functional as F
    except Exception as e:  # torch unavailable: no measured baseline
        log(f"torch baseline unavailable: {e}")
        return None

    torch.manual_seed(0)
    model = make_torch_alexnet()
    opt = torch.optim.SGD(model.parameters(), lr=LR, momentum=0.0)
    images_np, labels_np = make_batch(batch)
    images = torch.from_numpy(images_np.transpose(0, 3, 1, 2).copy())  # NCHW
    labels = torch.from_numpy(labels_np.astype(np.int64))

    def step():
        opt.zero_grad()
        loss = F.cross_entropy(model(images), labels)
        loss.backward()
        opt.step()
        return loss.detach()

    for _ in range(2):
        step()
    rates = []
    for _ in range(3):  # the CPU is shared; median out scheduler noise
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step()
        dt = time.perf_counter() - t0
        rates.append(steps * batch / dt)
    med = float(np.median(rates))
    log(f"torch [cpu]: median of 3x{steps}-step windows, batch {batch} "
        f"→ {med:.1f} img/s, final loss {float(loss):.4f}")
    return med


def run_headline_legs() -> dict:
    """Measure the three config-1 legs; ``{leg_name: Rate}``.

    The grad-accum leg's MFU numerator reuses the large-batch leg's XLA
    flop count: its program nests the microbatch scan inside the update
    body and ``cost_analysis`` counts scan bodies once (under-reporting by
    the microbatch count), while the real work per update — conv
    forward/backward over the same 1024 images plus one full-size
    optimizer apply — matches the plain batch-1024 step's count.
    """
    import jax

    from distributed_ml_pytorch_tpu.models import AlexNet
    from distributed_ml_pytorch_tpu.training.trainer import (
        make_scan_accum_train_step,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        big, micro, large_kw = LARGE_BATCH, ACCUM_MICROBATCH, \
            dict(k=LARGE_SCAN_K)
    else:
        # a 1-core CPU host runs the large legs to validate the record
        # shape and the program paths, not to produce a number (it has no
        # MFU table anyway); batch 1024 would take ~an hour there
        big, micro, large_kw = 256, 64, dict(k=2, n_long=2, trials=1)
    legs: dict = {}
    log("--- leg parity_b64 (reference recipe)")
    legs["parity_b64"] = bench_jax()
    legs["parity_b64"].leg_batch = BATCH
    fused = AlexNet(num_classes=10, fused_epilogue=True)
    fused_ok = True
    log("--- leg large_batch_b1024 (fused epilogues)")
    try:
        large = bench_jax(batch=big, model=fused, **large_kw)
    except Exception as e:
        # the audited plateau (~1.64M img/s) was measured on the UNFUSED
        # architecture, so a Mosaic/runtime rejection of the epilogue
        # kernel must not take the headline leg down with it — fall back
        # and say so in the record (fused_epilogue: false)
        log(f"fused-epilogue program failed on this runtime ({e!r}); "
            "re-running the large-batch legs unfused")
        fused, fused_ok = AlexNet(num_classes=10), False
        large = bench_jax(batch=big, model=fused, **large_kw)
    large.fused_epilogue = fused_ok
    large.leg_batch = big
    legs["large_batch_b1024"] = large
    log("--- leg grad_accum_b1024 (microbatch scan, batch-64 effective update)")
    accum_kw = dict(
        batch=big, **large_kw,
        step_builder=lambda m, tx: make_scan_accum_train_step(
            m, tx, micro, effective_update_batch=EFFECTIVE_UPDATE),
        flops_override=large.flops_per_step,
    )
    accum_fused_ok = fused_ok
    try:
        accum = bench_jax(model=fused, **accum_kw)
    except Exception as e:
        # the accum program nests the microbatch scan in the update body —
        # different block geometry, so the epilogue kernel can be rejected
        # here even after the plain batch-1024 program compiled; same
        # fall-back-and-say-so contract as the large leg
        if not accum_fused_ok:
            raise  # already unfused: a failure here is a real bug
        log(f"fused-epilogue accum program failed on this runtime ({e!r}); "
            "re-running the grad-accum leg unfused")
        accum_fused_ok = False
        accum = bench_jax(model=AlexNet(num_classes=10), **accum_kw)
    accum.fused_epilogue = accum_fused_ok
    accum.leg_batch = big
    legs["grad_accum_b1024"] = accum
    return legs


#: per-leg honesty notes for the headline record
LEG_NOTES = {
    "parity_b64": (
        "reference recipe (batch 64, SGD lr 0.008) — the trajectory-parity "
        "leg; conv-geometry-bound (per-fusion audit, BASELINE.md #1)"),
    "large_batch_b1024": (
        "identical architecture, batch 1024, Pallas-fused conv epilogues "
        "(ops/fused_conv.py) — the audited ~35%-MFU plateau as the shipped "
        "headline"),
    "grad_accum_b1024": (
        "batch 1024 as a microbatch-256 accumulation scan; applied update "
        "= sum of the 16 batch-64 mean-grad updates at frozen params "
        "(first-order equal to 16 recipe steps); flops numerator = the "
        "plain batch-1024 program's XLA count (nested-scan bodies are "
        "counted once by cost_analysis)"),
}


def load_floors(path: str | None = None) -> dict:
    """The checked-in MFU floors: ``{"tolerance": f, "legs": {name: floor}}``."""
    with open(path or FLOORS_PATH) as fh:
        floors = json.load(fh)
    return floors


def build_record(legs: dict, torch_base: float | None,
                 floors: dict | None = None) -> dict:
    """The one-line headline JSON: headline value = the large-batch leg,
    every leg reported side by side with its recorded ``mfu_floor``."""
    headline = legs[HEADLINE_LEG]
    rec = {
        "metric": "alexnet_cifar10_train_throughput_per_chip",
        "value": round(float(headline), 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(float(headline) / torch_base, 2) if torch_base else None,
        "headline_leg": HEADLINE_LEG,
    }
    if isinstance(headline, Rate):
        rec.update(headline.record_fields())
    floor_legs = (floors or {}).get("legs", {})
    # the TPU leg batches; a CPU validation run records what it actually
    # ran (the shrunk shapes) via the Rate's leg_batch attribute
    batches = {"parity_b64": BATCH, "large_batch_b1024": LARGE_BATCH,
               "grad_accum_b1024": LARGE_BATCH}
    rec["legs"] = {}
    for name, rate in legs.items():
        leg = {"img_per_s": round(float(rate), 1),
               "batch": getattr(rate, "leg_batch", None) or batches.get(name)}
        if isinstance(rate, Rate):
            leg.update(rate.record_fields())
        if getattr(rate, "fused_epilogue", None) is not None:
            leg["fused_epilogue"] = rate.fused_epilogue
        if name in floor_legs:
            leg["mfu_floor"] = floor_legs[name]
        if name in LEG_NOTES:
            leg["note"] = LEG_NOTES[name]
        rec["legs"][name] = leg
    rec["recipe_note"] = (
        "round 9: the round-5 audit's measured batch-256-1024 plateau "
        "(~35% MFU / 1.64M img/s) is now the shipped headline leg; the "
        "batch-64 reference recipe stays first-class as the parity leg, "
        "and the grad-accum leg carries the batch-64 effective update at "
        "large-batch geometry. --gate enforces the recorded mfu_floor "
        "per leg (bench_floors.json)")
    return rec


def check_mfu_floors(record: dict, floors: dict) -> tuple[list, list]:
    """Gate logic, pure on (record, floors): ``(breaches, skips)``.

    A leg listed in the floors but missing from the record is a breach
    (a silently dropped leg must fail the gate, not pass it); a leg
    without a measured MFU (CPU hosts have no peak-flops table) is a
    skip, reported but not failing.
    """
    tol = float(floors.get("tolerance", 0.0))
    legs = record.get("legs", {})
    breaches, skips = [], []
    for name, floor in sorted(floors.get("legs", {}).items()):
        leg = legs.get(name)
        if leg is None:
            breaches.append(f"{name}: leg missing from the bench record "
                            f"(floor {floor:.3f})")
            continue
        mfu = leg.get("mfu")
        if mfu is None:
            skips.append(f"{name}: no measured MFU on this backend "
                         f"(floor {floor:.3f} not checkable)")
            continue
        if mfu < floor - tol:
            breaches.append(
                f"{name}: MFU {mfu:.4f} < floor {floor:.3f} - tol {tol:.3f}")
    return breaches, skips


def gate(record: dict, floors: dict, require_mfu: bool = False) -> int:
    breaches, skips = check_mfu_floors(record, floors)
    for line in skips:
        log(f"gate: SKIP {line}")
    for line in breaches:
        log(f"gate: FAIL {line}")
    if require_mfu and skips:
        log("gate: FAIL unmeasured legs with --require-mfu")
        return 1
    if breaches:
        log(f"gate: {len(breaches)} MFU floor breach(es)")
        return 1
    log(f"gate: ok ({len(floors.get('legs', {})) - len(skips)} leg(s) "
        "at or above floor)")
    return 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--gate", action="store_true",
                    help="check measured MFU per leg against the recorded "
                         "floors (bench_floors.json); exit non-zero on a "
                         "breach")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="with --gate: gate this previously-emitted record "
                         "(no device run) — the `make bench-gate` smoke; "
                         "accepts the raw record or a driver wrapper with "
                         "a 'parsed' field")
    ap.add_argument("--floors", metavar="FILE", default=None,
                    help="floors file (default: bench_floors.json beside "
                         "this script)")
    ap.add_argument("--require-mfu", action="store_true",
                    help="with --gate: unmeasured legs fail instead of skip")
    args = ap.parse_args(argv)

    floors = load_floors(args.floors)
    if args.json:
        if not args.gate:
            ap.error("--json only makes sense with --gate")
        with open(args.json) as fh:
            record = json.load(fh)
        if "parsed" in record and "legs" not in record:
            record = record["parsed"]
        return gate(record, floors, args.require_mfu)

    legs = run_headline_legs()
    base = bench_torch_cpu()
    rec = build_record(legs, base, floors)
    print(json.dumps(rec), flush=True)
    if args.gate:
        return gate(rec, floors, args.require_mfu)
    return 0


if __name__ == "__main__":
    sys.exit(main())
