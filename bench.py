"""Benchmark harness (BASELINE.md config #1, the reference's headline workload).

Measures steady-state training throughput (images/sec/chip) of the flagship
AlexNet on CIFAR-10-shaped data with the reference training recipe — batch 64,
SGD lr 0.008 (reference ``example/main.py:142,144-145``) — on the default jax
device (the TPU chip under the driver; CPU elsewhere).

``vs_baseline`` is measured, not assumed: the same workload (same architecture,
same batch, same optimizer) is timed in torch on CPU — the reference's own
``make single`` configuration (reference ``Makefile:23``; the reference
publishes no numbers, BASELINE.md, so its baseline must be produced). The
printed ratio is TPU-images/sec over torch-CPU-images/sec.

Prints exactly ONE JSON line on stdout; all narration goes to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BATCH = 64
LR = 0.008
SCAN_K = 100       # steps fused into one compiled program (lax.scan)
N_SHORT, N_LONG = 1, 41  # dispatch counts for the differenced measurement
                         # (long leg ≈ 4000 steps so RTT jitter is small
                         # relative to the compute being measured)
TRIALS = 5         # report the median differenced estimate
BASELINE_STEPS = 12


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class Rate(float):
    """images/sec (or tokens/sec) that also carries the leg's FLOPs story:
    ``.flops_per_step`` (XLA's count for one step), ``.tflops`` (achieved),
    ``.mfu`` (fraction of the chip's bf16 peak) — any may be None when the
    backend doesn't report flops or the device kind has no peak entry."""

    flops_per_step: float | None = None
    tflops: float | None = None
    mfu: float | None = None

    @staticmethod
    def make(value: float, flops_per_step, step_seconds) -> "Rate":
        from distributed_ml_pytorch_tpu.utils.flops import utilization

        r = Rate(value)
        r.flops_per_step = flops_per_step
        r.tflops, r.mfu = utilization(flops_per_step, step_seconds)
        return r

    def mfu_note(self) -> str:
        """Human fragment for BASELINE notes: '12.3 TFLOP/s, 6.2% MFU'."""
        if self.tflops is None:
            return "flops not reported by backend"
        if self.mfu is None:
            return f"{self.tflops:.1f} TFLOP/s (no peak table for device)"
        return f"{self.tflops:.1f} TFLOP/s, {self.mfu:.1%} MFU"

    def record_fields(self) -> dict:
        """The FLOPs story as JSON record fields — the single serialization
        used by bench.py's headline and bench_all's emit."""
        rec = {}
        if self.tflops is not None:
            rec["flops_per_step"] = self.flops_per_step
            rec["tflops"] = round(self.tflops, 2)
            if self.mfu is not None:
                rec["mfu"] = round(self.mfu, 4)
        return rec


def make_batch(batch: int, seed: int = 0, k: int = 0,
               shape: tuple = (32, 32, 3), n_classes: int = 10):
    """Synthetic image batch (CIFAR-shaped by default); ``k > 0`` stacks k
    distinct microbatches on a leading axis (for the scanned trainer)."""
    rng = np.random.default_rng(seed)
    n = (k or 1) * batch
    images = rng.normal(size=(n, *shape)).astype(np.float32)
    labels = (np.arange(n) % n_classes).astype(np.int32)
    if k:
        return images.reshape(k, batch, *shape), labels.reshape(k, batch)
    return images, labels


def bench_jax(batch: int = BATCH, k: int | None = None, model=None,
              input_shape: tuple = (32, 32, 3), n_classes: int = 10,
              n_long: int | None = None, trials: int | None = None) -> float:
    """Steady-state images/sec of the scanned AlexNet trainer on the default
    device.

    Measurement boundary — stated precisely because naive timing lies twice
    on this setup: (a) K distinct microbatches train inside ONE compiled
    program (``make_scan_train_step``'s ``lax.scan``), so host dispatch is
    amortized — the framework's idiomatic execution for small models; (b) on
    a tunneled device, ``block_until_ready`` can return before the device
    finishes and a device→host fetch costs a large fixed RTT, so the number
    reported is the **differenced steady state**: time(N_LONG dispatches) −
    time(N_SHORT dispatches), each ended by fetching the final scalar loss
    (a true data dependency), divided by the extra steps. The fixed RTT
    cancels; what remains is per-step device time.
    """
    import jax

    from distributed_ml_pytorch_tpu.models import AlexNet
    from distributed_ml_pytorch_tpu.training.trainer import (
        create_train_state,
        make_scan_train_step,
    )

    # the RTT-differencing machinery exists for the tunneled TPU; on a local
    # CPU/GPU device a fraction of the workload measures the same thing in
    # seconds instead of tens of minutes
    n_short = N_SHORT
    if jax.devices()[0].platform != "tpu":
        if k is None:  # shrink only the default workload, not a caller's k
            k = 10
        n_long, trials = n_long or 3, trials or 2
    else:
        k = SCAN_K if k is None else k
        n_long, trials = n_long or N_LONG, trials or TRIALS

    model = model if model is not None else AlexNet(num_classes=10)
    state, tx = create_train_state(
        model, jax.random.key(0), lr=LR, sample_shape=(1, *input_shape)
    )
    train_scan = make_scan_train_step(model, tx)
    images, labels = make_batch(batch, k=k, shape=input_shape, n_classes=n_classes)
    images = jax.device_put(images)
    labels = jax.device_put(labels)
    rng = jax.random.key(1)

    losses = None
    for _ in range(2):  # compile + cache warmup
        state, losses = train_scan(state, images, labels, rng)
    float(losses[-1])

    dev = jax.devices()[0]
    if dev.platform == "tpu":
        # device-true timing (round 3): the profiler's device spans are
        # deterministic to the microsecond where host-differenced timing
        # through the tunnel swings 2-3x run to run (utils/devtime).
        # ``trials`` sets the traced call count; n_short/n_long belong to
        # the off-TPU differencing fallback below
        from distributed_ml_pytorch_tpu.utils.devtime import device_time

        holder = {"s": state, "l": losses}

        def one_call():
            holder["s"], holder["l"] = train_scan(
                holder["s"], images, labels, rng)
            return holder["l"]

        t = device_time(one_call, calls=max(2, trials), warmup=1)
        per_step = t.per_call_s / k
        state, losses = holder["s"], holder["l"]
        log(f"  device-true: {t.per_call_ms:.2f} ms per {k}-step scan "
            f"({t.calls} traced calls)")
    else:
        def timed(n_dispatches: int) -> float:
            nonlocal state, losses
            t0 = time.perf_counter()
            for _ in range(n_dispatches):
                state, losses = train_scan(state, images, labels, rng)
            float(losses[-1])  # forces completion of the whole chain
            return time.perf_counter() - t0

        shorts, longs = [], []
        for trial in range(trials):
            shorts.append(timed(n_short))
            longs.append(timed(n_long))
            log(f"  trial {trial}: T({n_short})={shorts[-1] * 1e3:.0f}ms "
                f"T({n_long})={longs[-1] * 1e3:.0f}ms")
        # min-min differencing: each leg's minimum is its fixed RTT + true
        # compute with the least noise; their difference cancels the RTT
        # without a single trial's jitter polluting both terms
        extra_steps = (n_long - n_short) * k
        per_step = (min(longs) - min(shorts)) / extra_steps
    from distributed_ml_pytorch_tpu.utils.flops import compiled_flops

    # XLA's cost_analysis counts a lax.scan body ONCE (not x trip count —
    # verified against a bare scanned matmul), so the k-step scan program's
    # reported flops ARE the per-step flops (+ negligible outside-body ops)
    scan_flops = compiled_flops(train_scan, state, images, labels, rng)
    rate = Rate.make(batch / per_step, scan_flops, per_step)
    method = ("device-true trace" if dev.platform == "tpu"
              else f"min-min differenced over {trials} trials")
    log(f"jax [{dev.platform}]: {method}, batch {batch}, {k}-step scans → "
        f"{per_step * 1e6:.1f} us/step, "
        f"{rate:.1f} img/s ({rate.mfu_note()}), final loss {float(losses[-1]):.4f}")
    return rate


def make_torch_alexnet():
    """The reference's CIFAR AlexNet as one torch Sequential (SURVEY.md C7) —
    the single spec shared by the throughput baseline here and the
    steps-to-accuracy comparison in ``bench_all.py``."""
    import torch.nn as tnn

    return tnn.Sequential(
        tnn.Conv2d(3, 64, 11, stride=4, padding=5), tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Conv2d(64, 192, 5, padding=2), tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Conv2d(192, 384, 3, padding=1), tnn.ReLU(),
        tnn.Conv2d(384, 256, 3, padding=1), tnn.ReLU(),
        tnn.Conv2d(256, 256, 3, padding=1), tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Flatten(),
        tnn.Linear(256, 10),
    )


def bench_torch_cpu(batch: int = BATCH, steps: int = BASELINE_STEPS) -> float | None:
    """images/sec of the reference workload (torch CPU, same recipe).

    The model is the reference's CIFAR AlexNet re-stated from its architecture
    spec (SURVEY.md C7: five convs 3→64 k11 s4 p5 / 64→192 k5 p2 / 192→384 k3
    p1 / 384→256 k3 p1 / 256→256 k3 p1, three 2×2 maxpools, Linear(256, 10)).
    """
    try:
        import torch
        import torch.nn.functional as F
    except Exception as e:  # torch unavailable: no measured baseline
        log(f"torch baseline unavailable: {e}")
        return None

    torch.manual_seed(0)
    model = make_torch_alexnet()
    opt = torch.optim.SGD(model.parameters(), lr=LR, momentum=0.0)
    images_np, labels_np = make_batch(batch)
    images = torch.from_numpy(images_np.transpose(0, 3, 1, 2).copy())  # NCHW
    labels = torch.from_numpy(labels_np.astype(np.int64))

    def step():
        opt.zero_grad()
        loss = F.cross_entropy(model(images), labels)
        loss.backward()
        opt.step()
        return loss.detach()

    for _ in range(2):
        step()
    rates = []
    for _ in range(3):  # the CPU is shared; median out scheduler noise
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step()
        dt = time.perf_counter() - t0
        rates.append(steps * batch / dt)
    med = float(np.median(rates))
    log(f"torch [cpu]: median of 3x{steps}-step windows, batch {batch} "
        f"→ {med:.1f} img/s, final loss {float(loss):.4f}")
    return med


def main() -> None:
    ips = bench_jax()
    base = bench_torch_cpu()
    vs = round(ips / base, 2) if base else None  # null = baseline not measurable here
    rec = {
        "metric": "alexnet_cifar10_train_throughput_per_chip",
        "value": round(ips, 1),
        "unit": "images/sec/chip",
        "vs_baseline": vs,
    }
    if isinstance(ips, Rate):
        rec.update(ips.record_fields())
    # measured MFU ceiling for this leg (VERDICT r2 #5, audited per-fusion
    # in round 5 — BASELINE.md #1): the batch-64 reference recipe is
    # bound by conv-kernel geometry at small spatial maps, not by MXU or
    # HBM. Round 5 removed the one provably wasteful fusion family
    # (select_and_scatter pool backwards, 7.1 us/step -> a reshape-max
    # custom vjp, bit-identical incl. ties) for +6.6%; the audited
    # remainder is conv fusions whose alternatives measured slower
    # (space-to-depth, two im2col forms, bf16) with SGD updates already
    # fused into the backward conv epilogues. Scaling batch on the
    # identical architecture lifts MFU to a plateau of ~35% of bf16 peak
    # (1.61M img/s at b256, 1.64M at b1024, device-true) — the
    # architecture's structural ceiling on this chip; the recipe's batch
    # 64 is the binding constraint.
    rec["mfu_ceiling_note"] = (
        "batch-64 recipe is conv-geometry-bound (per-fusion audit in "
        "BASELINE.md #1; pool-backward waste removed in round 5 for +6.6%); "
        "same architecture plateaus at ~35% MFU / 1.64M img/s by batch "
        "256-1024 (measured, device-true) - that plateau is the structural "
        "ceiling the recipe's fixed batch keeps out of reach")
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
