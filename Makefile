# C11/C13 parity: canned topologies and dev targets (reference Makefile:1-38).
# The reference's 3-process PS topology on localhost keeps the same names:
#   make server / make first / make second  (world-size 3, rank 0 = server)
# plus `make launch` which runs all three in one command.

PY ?= python

# --- canned PS topology (reference Makefile:13-20) ---
# The PS topology is host-side: N local processes must not fight over the one
# TPU chip, so the hand-launched ranks run on the CPU platform (same env that
# distributed_ml_pytorch_tpu.launch forces for `make launch`).
PS_ENV = JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS=

first:
	$(PS_ENV) $(PY) -m distributed_ml_pytorch_tpu.training.cli --mode ps --rank 1 --world-size 3

second:
	$(PS_ENV) $(PY) -m distributed_ml_pytorch_tpu.training.cli --mode ps --rank 2 --world-size 3

server:
	$(PS_ENV) $(PY) -m distributed_ml_pytorch_tpu.training.cli --mode ps --rank 0 --world-size 3 --server

launch:
	$(PY) -m distributed_ml_pytorch_tpu.launch --world-size 3

# sharded parameter server (DistBelief layout): 2 shard servers + 2 workers
sharded:
	$(PY) -m distributed_ml_pytorch_tpu.launch --world-size 4 --n-servers 2

# --- single-process baselines (reference Makefile:22-26; `gpu` → `tpu`) ---
single:
	$(PY) -m distributed_ml_pytorch_tpu.training.cli --no-distributed --backend cpu

tpu:
	$(PY) -m distributed_ml_pytorch_tpu.training.cli --no-distributed

gpu: tpu

# --- TPU-native extras ---
sync:
	$(PY) -m distributed_ml_pytorch_tpu.training.cli --mode sync

local-sgd:
	$(PY) -m distributed_ml_pytorch_tpu.training.cli --mode local-sgd

p2p:
	$(PY) -m distributed_ml_pytorch_tpu.parallel.p2p

# continuous-batching inference hub (serving/cli.py); CTRL-C prints the
# SLO summary. `make serve-demo` runs the self-contained in-process demo.
serve:
	$(PY) -m distributed_ml_pytorch_tpu.serving.cli

serve-demo:
	$(PY) -m distributed_ml_pytorch_tpu.serving.cli --demo 6

# fleet serving (serving/fleet.py): 3 engine replicas behind a FleetRouter
# — occupancy + session-affinity routing, stream migration across engine
# death, overload shed/brownout. CTRL-C prints the fleet summary.
serve-fleet:
	$(PY) -m distributed_ml_pytorch_tpu.serving.cli --fleet 3

serve-fleet-demo:
	$(PY) -m distributed_ml_pytorch_tpu.serving.cli --fleet 2 --demo 6

bench:
	$(PY) bench.py

bench-serving:
	$(PY) bench_serving.py

bench-all:
	$(PY) bench_all.py

# MFU regression gate (ISSUE 9): re-checks a bench record's per-leg MFU
# against the recorded floors in bench_floors.json and exits non-zero on
# a breach or a missing leg. The default target is the no-device smoke on
# a canned record (gate LOGIC is exercised; wired into `make test`);
# after a real rig run: python bench.py --gate
bench-gate:
	$(PY) bench.py --gate --json tests/data/bench_gate_smoke.json

# conv-epilogue cost ladder (fused Pallas kernels vs the unfused XLA
# chain, per AlexNet tail shape) — the compute-plane microbench phase
bench-compute:
	$(PY) bench_all.py --only compute_microbench

# seeded fault-injection suite (utils/chaos.py + the reliability layer):
# deterministic drop/dup/corrupt/partition/crash scenarios on the PS and
# serving planes, soak variants included (they carry both markers)
chaos:
	$(PY) -m pytest tests/ -q -m chaos

# codec-plane suite (utils/codecs.py, ISSUE 18): WIRE_PLANES registry
# totality over the codec-id-bearing schemas, loss-contract numerics
# (int8 bound, tok16 exactness, delta-reply identity on the real server)
codec:
	$(PY) -m pytest tests/ -q -m codec

# elastic control-plane suite (coord/): membership + leases, coordinator-
# driven shard rebalancing (the join/crash acceptance scenario), straggler
# speculation with first-result-wins dedup, serving fleet hook
coord:
	$(PY) -m pytest tests/ -q -m coord

# disaster-recovery drill suite (coord/drill.py + utils/wal.py): snapshot
# barrier -> kill shard subsets mid-epoch -> restore from manifest + WAL
# with zero acked-update loss, byte-identical fault logs across repeats;
# soak variants additionally carry the slow marker
drill:
	$(PY) -m pytest tests/ -q -m drill

# one-command drill demo (prints MTTR + replayed counts + accounting)
drill-demo:
	$(PY) -m distributed_ml_pytorch_tpu.coord.cli --drill

# fleet-serving suite (serving/fleet.py): multi-engine routing, stream
# migration across engine death (token-identical, byte-identical chaos
# logs), overload shed/brownout, per-engine lease health
fleet:
	$(PY) -m pytest tests/ -q -m fleet

# overload soak (slow-marked): the fleet at 2x its sustainable arrival
# rate must shed/brownout instead of collapsing — goodput-under-SLO >= 80%
# of the 1x value and every shed request explicitly rejected
soak:
	$(PY) -m pytest tests/ -q -m soak

# numerical-health suite (ISSUE 8): admission gate + UpdateNack quarantine,
# SDC chaos (bit-perfect-on-the-wire payload corruption), worker reputation,
# and the coordinator auto-rollback barrier — the acceptance proves >=1
# automatic rollback under a seeded poisoned worker with byte-identical
# chaos logs and zero poison in any WAL
health:
	$(PY) -m pytest tests/ -q -m health

# one-command health demo (prints rollback MTTR, quarantine/nack counts,
# reputation revocations)
health-demo:
	$(PY) -m distributed_ml_pytorch_tpu.coord.cli --health

# health-plane bench phase: reject rate, nack round-trip, rollback MTTR
bench-health:
	$(PY) bench_all.py --only health

# MPMD pipeline-plane suite (ISSUE 10): stages as fleet members — per-stage
# compiled programs over the reliable wire, coordinator StagePlacement,
# stage kill -> lease-expiry detection -> checkpoint restart with
# watermark-bounded microbatch replay (byte-identical chaos logs 3x),
# stage speculation via standby takeover
mpmd:
	$(PY) -m pytest tests/ -q -m mpmd

# one-command MPMD demo (prints the loss trajectory, stage-restart MTTR,
# applied-microbatch accounting and chaos counts)
mpmd-demo:
	$(PY) -m distributed_ml_pytorch_tpu.coord.cli --mpmd

# MPMD bench phase: steady-state pipeline throughput, bubble fraction,
# and stage-kill MTTR before/during/after a restart; also leaves the
# fleet's flight-recorder dumps behind (analyze them with `make timeline`)
bench-mpmd:
	$(PY) bench_all.py --only mpmd

# timeline analyzer (ISSUE 12): merge a run's flight-recorder dumps and
# attribute each stage's wall clock (compute / wait-act / wait-grad /
# wire-blocked / ckpt) plus the wire's share (retransmits, credit-block,
# ack frames). Default dir = the newest bench-mpmd run's obs dumps; point
# it anywhere with: make timeline TIMELINE_DIR=path/to/obs
TIMELINE_DIR ?= $(shell ls -td "$${TMPDIR:-/tmp}"/bench_mpmd_*/obs 2>/dev/null | head -1)
timeline:
	@test -n "$(TIMELINE_DIR)" || (echo "no dump dir found — run 'make bench-mpmd' first or pass TIMELINE_DIR=<dir>"; exit 1)
	$(PY) -m distributed_ml_pytorch_tpu.analysis timeline $(TIMELINE_DIR)

# multi-tenant scheduler suite (ISSUE 16, coord/sched.py + coord/tenants.py):
# capacity ledger exclusivity, admit/pack/preempt/resume protocol against a
# real coordinator, autoscale actuation, and the park-and-restore drill
# (preempt a LIVE training shard at peak, resume bit-for-bit off-peak,
# byte-identical chaos logs 3x)
sched:
	$(PY) -m pytest tests/ -q -m sched

# one-command scheduler demo (prints preempt/resume MTTR, WAL replay and
# bit-identical restore proof, grants, decision log)
sched-demo:
	$(PY) -m distributed_ml_pytorch_tpu.coord.cli --sched-demo

# scheduler bench phase: preempt/resume MTTR + aggregate goodput (shared
# FleetScheduler vs two statically partitioned half-fleets)
bench-sched:
	$(PY) bench_all.py --only sched

# control-plane durability suite (ISSUE 17, coord/coordinator.py): the
# coordinator's own WAL+checkpoint restart, monotonic epoch fencing of
# every outbound control frame, the restart grace window, the coordfail
# distmodel plane, and the kill-the-coordinator drill (crash the arbiter
# mid-snapshot-barrier AND mid-preemption, restart, fleet re-attaches
# with nobody evicted and the parked member resumed bit-identically)
coordfail:
	$(PY) -m pytest tests/ -q -m coordfail

# control-plane durability bench phase: kill-the-coordinator MTTR, durable
# restore time, and steps/tokens lost to the outage (zero = fail-open held)
bench-coordfail:
	$(PY) bench_all.py --only coordfail

# adaptive-wire suite (ISSUE 7): RTT-driven retransmission, window/credit
# backpressure, circuit breakers, and seeded network weather (latency /
# jitter / bandwidth caps / one-way degradation) — the training acceptance
# proves graceful degradation with byte-identical chaos logs
netweather:
	$(PY) -m pytest tests/ -q -m netweather

# gray-failure plane (ISSUE 20, coord/grayhealth.py + utils/chaos.GrayRule):
# adaptive per-member/per-link suspicion on the LeaseRenew evidence tail,
# scheduled one-way partitions / lossy links / injected stalls, and the
# probation -> quarantine -> evict containment ladder; the drill acceptance
# runs a mid-training gray episode 3x with byte-identical chaos logs
gray:
	$(PY) -m pytest tests/ -q -m gray

# gray-failure bench phase: goodput through a 10s gray-link episode with
# containment on vs off, plus measured detection latency (floor-gated) and
# containment MTTR
bench-gray:
	$(PY) bench_all.py --only gray

# wire cost ladder + reliability before/after (bench_all phases): every
# transport layer priced raw -> reliable -> batched-ack -> WAL-deferred ->
# chaos-wrapped, plus the ack-tax recovery measurement
bench-wire:
	$(PY) bench_all.py --only transport_microbench --only reliability

# compressed gradient wire ladder (ISSUE 14, utils/compress.py): dense vs
# int8 vs top-k bytes-on-wire per push + acked pushes/s against a real
# decoding ParameterServer, plus the derived compression ratios
bench-wire-bytes:
	$(PY) bench_all.py --only wire_bytes

# distcheck (analysis/): protocol / concurrency / tracing-hygiene static
# analysis over the whole package — exits non-zero on any unsuppressed
# finding that is not in the checked-in baseline. Regenerate the baseline
# (mirrors the slow_tests.txt workflow) with:
#   python tests/regen_distcheck_baseline.py
lint:
	$(PY) -m distributed_ml_pytorch_tpu.analysis --baseline tests/distcheck_baseline.txt

# interprocedural dataflow corpus (ISSUE 19, analysis/distflow.py): the
# DC501-504 seeded-bug/clean-twin tests plus the bounded-state runtime
# witness tests — the checks themselves run inside `make lint`
distflow:
	$(PY) -m pytest tests/ -q -m distflow

# lint wall-clock phase: times the full distcheck pass (all checker
# families, distflow included) and gates it against the ceiling in
# bench_floors.json — static analysis must stay cheap enough for tier-1
bench-lint:
	$(PY) bench_all.py --only lint

# bounded protocol model checker (ISSUE 13, analysis/distmodel.py):
# exhaustively explores small configurations of the extracted wire
# protocol (2 workers x 2 updates PS; 2-life lease plane; 2x2 MPMD
# hand-off) under drop/dup/reorder/crash/restart schedules and fails on
# any exactly-once / acked=>applied / lease-monotonicity /
# watermark-replay violation. Seconds on one core; counterexamples (from
# `--mutate <name>`) are written as ChaosPlan JSON + pytest repro stubs:
#   python -m distributed_ml_pytorch_tpu.analysis distmodel --mutate no_dedup --out /tmp/ce
distmodel:
	$(PY) -m distributed_ml_pytorch_tpu.analysis distmodel

# fast core signal: distcheck + the bounded model check + the MFU-gate
# smoke + everything that runs in-process (no subprocess worlds, no
# end-to-end example trainings) — minutes on one core
test: lint distmodel bench-gate
	$(PY) -m pytest tests/ -x -q -m "not slow"

# the whole suite, subprocess worlds included (tens of minutes on one core)
test-all:
	$(PY) -m pytest tests/ -x -q

# one-command real-data verification (VERDICT r2 #6): downloads genuine
# CIFAR-10 where egress exists, re-runs steps-to-target + torch parity on
# it and appends the outcome to BASELINE.md; prints SKIP and exits 0 when
# offline, so it can run unconditionally
verify-real-data:
	$(PY) verify_real_data.py

# --- plots (reference Makefile:8-11) ---
graph:
	$(PY) -m distributed_ml_pytorch_tpu.graph
	mkdir -p docs && mv train_time.png test_time.png docs/

# --- packaging (reference Makefile:28-38) ---
install:
	pip install .

dist:
	$(PY) setup.py sdist bdist_wheel

.PHONY: first second server launch sharded single tpu gpu sync local-sgd p2p serve serve-demo serve-fleet serve-fleet-demo bench bench-serving bench-all bench-wire bench-wire-bytes bench-health bench-gate bench-compute bench-mpmd bench-sched bench-coordfail bench-gray bench-lint timeline chaos codec coord coordfail distflow drill drill-demo fleet gray health health-demo mpmd mpmd-demo netweather sched sched-demo soak lint distmodel test test-all verify-real-data graph install dist
