"""Sample from a Transformer LM — the inference-side executable example.

``examples/train_lm.py`` is the training entry into the LM API; this is its
decode counterpart: build (or restore) a ``TransformerLM`` and sample
continuations with the full knob surface of ``models/generate.py``:

    python -m examples.generate_text --new-tokens 64
    python -m examples.generate_text --temperature 0.8 --top-k 50 --top-p 0.9
    python -m examples.generate_text --kv-quant        # int8 KV cache
    python -m examples.generate_text --tp 4            # tensor-parallel decode
    python -m examples.generate_text --ckpt-dir /tmp/lm --d-model 128 ...

``--ckpt-dir`` restores params saved by ``examples/train_lm.py`` (orbax;
the model flags must match the training run — the restore validates
shapes). Without it, sampling runs from a fresh init: useless text, but the
full compiled path, which is what the example demonstrates.

Decode runs the ring-buffered block path for 17+ token runs (per-step ring
appends, static live-prefix cache reads, once-per-block merges — see
``models/generate.py``); ``--kv-quant`` stores completed blocks as int8 +
per-key scales for half the cache footprint.
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--prompt-len", type=int, default=32,
                   help="length of the random prompt (token ids)")
    p.add_argument("--new-tokens", type=int, default=64)
    p.add_argument("--batch", type=int, default=2,
                   help="number of prompts sampled in parallel")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy; >0 = categorical sampling")
    p.add_argument("--top-k", type=int, default=0,
                   help="keep only the k highest logits (0 = off)")
    p.add_argument("--top-p", type=float, default=1.0,
                   help="nucleus truncation mass (1.0 = off)")
    p.add_argument("--kv-quant", action="store_true",
                   help="int8 KV cache: half the cache footprint, exact "
                        "prefill logits (models/generate.py)")
    p.add_argument("--tp", type=int, default=0, metavar="D",
                   help="tensor-parallel decode over D model-axis devices "
                        "(generate_tp; requires D to divide --n-heads)")
    p.add_argument("--ckpt-dir", type=str, default="",
                   help="restore params from a train_lm.py orbax checkpoint")
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--d-ff", type=int, default=256)
    p.add_argument("--max-len", type=int, default=0,
                   help="learned-position table size (0 = derived from the "
                        "decode length). Restoring a train_lm.py checkpoint "
                        "with learned positions requires the TRAINING run's "
                        "table size: train_lm uses max(--seq, 256)")
    p.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    p.add_argument("--pos-encoding", default="learned",
                   choices=["learned", "rope"])
    p.add_argument("--seed", type=int, default=0)
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.d_model % args.n_heads:
        parser.error(f"--d-model {args.d_model} must divide by --n-heads "
                     f"{args.n_heads}")
    if args.temperature <= 0.0 and (args.top_k or args.top_p < 1.0):
        parser.error("--top-k/--top-p need --temperature > 0 (greedy decode "
                     "ignores them)")
    if args.tp and args.kv_quant:
        parser.error("--kv-quant is not supported with --tp (generate_tp "
                     "runs the exact-cache path) — drop one of the flags")
    # the kv_quant guard against runs the blocked path cannot serve lives
    # below (it needs the constructed model)

    import time

    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.models import TransformerLM
    from distributed_ml_pytorch_tpu.models.generate import generate, generate_tp

    total = args.prompt_len + args.new_tokens
    lm = TransformerLM(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff,
        # blocked decode pads the step loop to whole 16-token blocks; keep
        # the learned-position table large enough for the padded positions
        # (checkpoint restores must instead match the training run's table
        # via --max-len: the param shapes are part of the checkpoint)
        max_len=args.max_len or max(total + 16, 256),
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
        pos_encoding=args.pos_encoding,
    )
    if args.kv_quant:
        from distributed_ml_pytorch_tpu.models.generate import uses_block_decode

        blocked, _ = uses_block_decode(lm, args.prompt_len, args.new_tokens)
        if not blocked:
            parser.error(
                "--kv-quant only applies on the ring-buffered block path "
                "(>= 17 new tokens, prompt length > 1, <= 1025 new tokens, "
                "and the padded run must fit --max-len) — this shape would "
                "silently run the exact cache")

    if not args.ckpt_dir:
        params = lm.init(
            jax.random.key(args.seed), jnp.zeros((1, 8), jnp.int32)
        )["params"]
    else:
        from distributed_ml_pytorch_tpu.utils.checkpoint import Checkpointer

        with Checkpointer(args.ckpt_dir) as ckpt:
            step = ckpt.latest_step()
            if step is None:
                raise SystemExit(
                    f"no checkpoint under {args.ckpt_dir} — train one with "
                    "examples/train_lm.py --ckpt-dir first")
            # train_lm checkpoints a TrainState; restore against a template
            # of the same shape and keep its params
            import optax
            from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
                create_lm_train_state,
            )

            # abstract template: no wasted full init before orbax
            # overwrites everything (Checkpointer.restore accepts shapes)
            template = jax.eval_shape(lambda: create_lm_train_state(
                lm, jax.random.key(args.seed), optax.sgd(0.1)))
            state, step = ckpt.restore(template)
            params = state.params
            print(f"restored params from step {step} of {args.ckpt_dir}")

    rng = np.random.default_rng(args.seed)
    prompt = jnp.asarray(
        rng.integers(0, args.vocab, size=(args.batch, args.prompt_len)),
        jnp.int32,
    )
    sample_rng = jax.random.key(args.seed + 1)
    kwargs = dict(
        temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
        rng=sample_rng if args.temperature > 0 else None,
    )

    t0 = time.perf_counter()
    if args.tp:
        from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

        n_dev = len(jax.devices())
        if args.tp > n_dev:
            raise SystemExit(f"--tp {args.tp} exceeds {n_dev} devices")
        if args.n_heads % args.tp:
            raise SystemExit(f"--tp {args.tp} must divide --n-heads "
                             f"{args.n_heads}")
        mesh = make_mesh({"data": 1, "model": args.tp},
                         devices=jax.devices()[: args.tp])
        out = generate_tp(lm, params, prompt, args.new_tokens, mesh, **kwargs)
        mode = f"tensor-parallel over {args.tp} devices"
    else:
        out = generate(lm, params, prompt, args.new_tokens,
                       kv_quant=args.kv_quant, **kwargs)
        mode = "int8 KV cache" if args.kv_quant else "bf16/f32 KV cache"
    out = np.asarray(out)
    dt = time.perf_counter() - t0

    n_generated = args.batch * args.new_tokens
    print(f"decode ({mode}): {n_generated} tokens in {dt:.2f}s "
          f"(compile included) on {jax.devices()[0].platform}")
    for b in range(args.batch):
        print(f"[{b}] prompt : {' '.join(map(str, out[b, :args.prompt_len]))}")
        print(f"[{b}] sampled: {' '.join(map(str, out[b, args.prompt_len:]))}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
