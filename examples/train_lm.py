"""Train the Transformer LM under any parallelism mode — executable example.

The reference's ``example/main.py`` is the CNN application; this is its
long-context counterpart: one script that builds a ``TransformerLM``, picks a
parallelism strategy, and trains on synthetic token streams, printing loss
and steady-state tokens/sec. It is the documented entry into the LM API:

    python -m examples.train_lm --mode single --steps 20
    python -m examples.train_lm --mode sp      # ring attention over seq axis
    python -m examples.train_lm --mode ulysses # all-to-all head re-sharding
    python -m examples.train_lm --mode fsdp    # ZeRO-3 sharded state
    python -m examples.train_lm --mode tp      # Megatron GSPMD shardings
    python -m examples.train_lm --mode pp      # GPipe stages over layers
    python -m examples.train_lm --mode moe     # dp x ep Switch-MoE experts
    python -m examples.train_lm --mode composite  # 3-D dp x fsdp x tp

Every mode supports ``--steps-per-dispatch K`` (K steps fused into one
compiled program via ``lax.scan`` over the mode's own sharded step — the
same chunked-dispatch idea as the CNN trainer's flag) and checkpoint/resume
via ``--ckpt-dir``/``--ckpt-every``/``--resume`` (orbax, sharding-aware:
states restore directly into the mode's device layout).

On one host, meshes come up on whatever devices exist (use
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
for the virtual-mesh simulation); on a pod, run under
``runtime.initialize_distributed`` and the same code scales.
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--mode", default="single",
                   choices=["single", "sp", "ulysses", "fsdp", "tp", "pp",
                            "moe", "composite"])
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--n-experts", type=int, default=4,
                   help="(--mode moe) experts per MoE layer")
    p.add_argument("--microbatches", type=int, default=4,
                   help="(--mode pp) GPipe microbatches per step")
    p.add_argument("--pp-dp", type=int, default=1, metavar="D",
                   help="(--mode pp) data-parallel pipeline replicas on a "
                        "(data=D, stage) mesh — dp x pp composition")
    p.add_argument("--pp-tp", type=int, default=1, metavar="T",
                   help="(--mode pp) tensor-parallel width INSIDE each "
                        "pipeline stage (Megatron block sharding over a "
                        "model axis); composes with --pp-dp for the full "
                        "dp x pp x tp 3-D layout")
    p.add_argument("--loss-chunk", type=int, default=0, metavar="C",
                   help="(single/fsdp modes) compute the LM loss in C-token "
                        "sequence chunks without materializing the full "
                        "(batch, seq, vocab) logits — required at very long "
                        "context (e.g. --seq 32768); 0 = dense loss")
    p.add_argument("--steps-per-dispatch", type=int, default=1, metavar="K",
                   help="fuse K steps (distinct batches) into one compiled "
                        "program via lax.scan; --steps must divide by K")
    p.add_argument("--ckpt-dir", type=str, default="",
                   help="enable orbax checkpointing under this directory")
    p.add_argument("--ckpt-every", type=int, default=100,
                   help="save every N global steps")
    p.add_argument("--resume", action="store_true", default=False,
                   help="restore the latest checkpoint from --ckpt-dir")
    p.add_argument("--batch", type=int, default=8, help="global batch (sequences)")
    p.add_argument("--seq", type=int, default=256, help="global sequence length")
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--d-ff", type=int, default=256)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    p.add_argument("--pos-encoding", default="learned", choices=["learned", "rope"])
    p.add_argument("--remat", action="store_true",
                   help="per-block rematerialization (long sequences)")
    p.add_argument("--seed", type=int, default=0)
    return p


def _scalar_loss(metrics) -> float:
    """Last scalar loss out of any mode's metrics: moe returns (loss, aux),
    chunked dispatch returns per-step stacks — take the primary, then the
    final element."""
    if isinstance(metrics, tuple):
        metrics = metrics[0]
    return float(np.asarray(metrics).reshape(-1)[-1])


def _stack_sharded(samples):
    """Stack identically-sharded per-step arrays onto a leading scan axis,
    keeping each step's sharding (spec lifted to ``P(None, *spec)``).
    Host-only inputs (e.g. pp's microbatched numpy arrays) stay numpy —
    jit shards them on entry."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    host = np.stack([np.asarray(a) for a in samples])
    sh = getattr(samples[0], "sharding", None)
    if isinstance(sh, NamedSharding):
        host = jax.device_put(
            host, NamedSharding(sh.mesh, PartitionSpec(None, *sh.spec))
        )
    return host


def _make_chunked_step(step):
    """K steps in one compiled program: ``lax.scan`` over the mode's own
    step (jit-of-jit inlines it; inner donation is subsumed by the outer)."""
    from functools import partial

    import jax

    @partial(jax.jit, donate_argnums=(0,))
    def chunked(state, tokens_k, targets_k):
        return jax.lax.scan(lambda s, b: step(s, *b), state, (tokens_k, targets_k))

    return chunked


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.steps < 1:
        parser.error("--steps must be >= 1")
    if args.d_model % args.n_heads:
        parser.error(
            f"--d-model {args.d_model} must be divisible by --n-heads "
            f"{args.n_heads} (attention splits d_model into heads)"
        )
    if args.loss_chunk and args.seq % args.loss_chunk:
        parser.error(
            f"--seq {args.seq} must divide by --loss-chunk {args.loss_chunk}"
        )

    import math

    import jax
    import jax.numpy as jnp
    import optax

    from distributed_ml_pytorch_tpu.models import TransformerLM
    from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
        create_lm_train_state,
        next_token_targets,
    )

    lm = TransformerLM(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff,
        max_len=max(args.seq, 256),
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
        pos_encoding=args.pos_encoding, remat=args.remat,
    )
    tx = optax.sgd(args.lr)
    rng = np.random.default_rng(args.seed)
    tokens = rng.integers(0, args.vocab, size=(args.batch, args.seq)).astype(np.int32)
    targets = next_token_targets(tokens)

    n_dev = len(jax.devices())
    if args.mode in ("sp", "ulysses"):
        from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
            make_sp_train_step,
            shard_lm_batch,
        )
        from distributed_ml_pytorch_tpu.parallel.ulysses import make_ulysses_train_step
        from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

        # each axis must divide what it shards (seq over the seq axis, batch
        # over data; Ulysses additionally shards heads over the seq axis)
        d_seq = math.gcd(n_dev, args.seq)
        if args.mode == "ulysses":
            d_seq = math.gcd(d_seq, args.n_heads)
        d_data = math.gcd(n_dev // d_seq, args.batch)
        mesh = make_mesh(
            {"data": d_data, "seq": d_seq}, devices=jax.devices()[: d_data * d_seq]
        )
        state = create_lm_train_state(lm, jax.random.key(args.seed), tx)
        make = make_sp_train_step if args.mode == "sp" else make_ulysses_train_step
        step = make(lm, tx, mesh)
        shard = lambda t, g: shard_lm_batch(mesh, t, g)
        desc = f"{d_data}x{d_seq} dp x seq ({'ring' if args.mode == 'sp' else 'all-to-all'})"
    elif args.mode in ("single", "fsdp"):
        from distributed_ml_pytorch_tpu.parallel.fsdp import (
            create_fsdp_train_state,
            make_fsdp_lm_train_step,
            param_shard_fraction,
            shard_fsdp_batch,
        )
        from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh
        from distributed_ml_pytorch_tpu.training.trainer import TrainState

        # the batch shards over the data axis, so the mesh cannot be wider;
        # "single" is literally fsdp on a 1-device mesh (same step factory,
        # provably identical update semantics — fsdp.make_sharded_step)
        n_fsdp = 1 if args.mode == "single" else math.gcd(n_dev, args.batch)
        mesh = make_mesh({"data": n_fsdp}, devices=jax.devices()[:n_fsdp])

        def init_fn(key):
            params = lm.init(key, jnp.zeros((1, 8), jnp.int32))["params"]
            return TrainState.create(params, tx)

        state, shardings = create_fsdp_train_state(
            init_fn, jax.random.key(args.seed), mesh
        )
        step = make_fsdp_lm_train_step(lm, tx, mesh, shardings,
                                       loss_chunk=args.loss_chunk)
        shard = lambda t, g: shard_fsdp_batch(mesh, t, g)
        desc = "single-device" if args.mode == "single" else (
            f"{n_fsdp}-way fsdp "
            f"({param_shard_fraction(state, mesh):.3f} of params/device)"
        )
    elif args.mode == "tp":
        from distributed_ml_pytorch_tpu.parallel.tensor_parallel import (
            create_tp_train_state,
            make_tp_train_step,
            shard_tp_batch,
        )
        from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

        # the model axis must divide every dimension tp shards
        d_model_axis = math.gcd(math.gcd(n_dev, args.n_heads),
                                math.gcd(args.d_ff, args.vocab))
        d_data = math.gcd(n_dev // d_model_axis, args.batch)
        mesh = make_mesh(
            {"data": d_data, "model": d_model_axis},
            devices=jax.devices()[: d_data * d_model_axis],
        )
        state = create_tp_train_state(lm, jax.random.key(args.seed), tx, mesh)
        step = make_tp_train_step(lm, tx, mesh)
        shard = lambda t, g: shard_tp_batch(mesh, t, g)
        desc = f"{d_data}x{d_model_axis} dp x tp"
    elif args.mode == "pp":
        from jax.sharding import Mesh

        from distributed_ml_pytorch_tpu.parallel.pipeline import (
            PipelineLMConfig,
            create_pp_train_state,
            make_pp_train_step,
            microbatch,
        )

        # stages must divide the layer count; microbatches must divide batch
        d_pp = int(args.pp_dp)
        d_tp = int(args.pp_tp)
        if d_pp < 1:
            parser.error(f"--pp-dp must be >= 1, got {d_pp}")
        if d_tp < 1:
            parser.error(f"--pp-tp must be >= 1, got {d_tp}")
        if n_dev % (d_pp * d_tp):
            parser.error(f"--pp-dp {d_pp} x --pp-tp {d_tp} must divide the "
                         f"device count {n_dev}")
        if args.n_heads % d_tp or args.d_ff % d_tp:
            parser.error(f"--pp-tp {d_tp} must divide n_heads "
                         f"{args.n_heads} and d_ff {args.d_ff}")
        n_stages = math.gcd(n_dev // (d_pp * d_tp), args.n_layers)
        n_mb = math.gcd(args.microbatches, args.batch)
        cfg = PipelineLMConfig(
            vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
            n_layers=args.n_layers, d_ff=args.d_ff, max_len=max(args.seq, 256),
        )
        model_axis = "model" if d_tp > 1 else None
        tp_desc = f" x {d_tp} tp-in-stage" if d_tp > 1 else ""
        if d_pp > 1:
            if (args.batch // n_mb) % d_pp:
                parser.error(f"--pp-dp {d_pp} must divide the per-microbatch "
                             f"batch {args.batch // n_mb}")
            shape = ((d_pp, n_stages, d_tp) if d_tp > 1
                     else (d_pp, n_stages))
            axes = (("data", "stage", "model") if d_tp > 1
                    else ("data", "stage"))
            mesh = Mesh(
                np.array(jax.devices()[: d_pp * n_stages * d_tp]).reshape(
                    shape),
                axes,
            )
            step = make_pp_train_step(cfg, tx, mesh, n_microbatches=n_mb,
                                      data_axis="data", model_axis=model_axis)
            desc = (f"{d_pp}x{n_stages} dp x pp GPipe{tp_desc}, {n_mb} "
                    f"microbatches, grads averaged over {d_pp} pipeline "
                    "replicas")
        else:
            shape = (n_stages, d_tp) if d_tp > 1 else (n_stages,)
            axes = ("stage", "model") if d_tp > 1 else ("stage",)
            mesh = Mesh(
                np.array(jax.devices()[: n_stages * d_tp]).reshape(shape),
                axes)
            step = make_pp_train_step(cfg, tx, mesh, n_microbatches=n_mb,
                                      model_axis=model_axis)
            desc = f"{n_stages}-stage GPipe{tp_desc}, {n_mb} microbatches"
        state = create_pp_train_state(cfg, jax.random.key(args.seed), tx,
                                      mesh, model_axis=model_axis)
        shard = lambda t, g: microbatch(t, g, n_mb)
    elif args.mode == "moe":
        from distributed_ml_pytorch_tpu.models.moe import MoETransformerLM
        from distributed_ml_pytorch_tpu.parallel.expert_parallel import (
            create_ep_train_state,
            make_ep_train_step,
            shard_ep_batch,
        )
        from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

        # experts divide over the expert axis; batch over the data axis
        d_expert = math.gcd(n_dev, args.n_experts)
        d_data = math.gcd(n_dev // d_expert, args.batch)
        mesh = make_mesh(
            {"data": d_data, "expert": d_expert},
            devices=jax.devices()[: d_data * d_expert],
        )
        moe = MoETransformerLM(
            vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
            n_layers=args.n_layers, d_ff=args.d_ff, n_experts=args.n_experts,
            max_len=max(args.seq, 256),
            dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
            remat=args.remat,
        )
        state = create_ep_train_state(moe, jax.random.key(args.seed), tx, mesh)
        step = make_ep_train_step(moe, tx, mesh)
        shard = lambda t, g: shard_ep_batch(mesh, t, g)
        desc = f"{d_data}x{d_expert} dp x ep ({args.n_experts} experts)"
    else:  # composite
        from distributed_ml_pytorch_tpu.parallel.composite import (
            create_composite_train_state,
            make_composite_train_step,
            shard_composite_batch,
        )
        from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

        # the model axis must divide heads/d_ff/vocab (1 when they're odd);
        # whatever it doesn't use goes to the combined data x fsdp group,
        # which must divide the batch
        d_model_c = math.gcd(2, math.gcd(args.n_heads, math.gcd(args.d_ff, args.vocab)))
        if d_model_c > n_dev:
            d_model_c = 1  # fewer devices than the model axis wants
        combined = math.gcd(n_dev // d_model_c, args.batch)
        d_data = 2 if combined % 2 == 0 and combined > 1 else 1
        shape = {"data": d_data, "fsdp": combined // d_data, "model": d_model_c}
        n_used = 1
        for v in shape.values():
            n_used *= v
        mesh = make_mesh(shape, devices=jax.devices()[:n_used])
        state, shardings = create_composite_train_state(
            lm, jax.random.key(args.seed), tx, mesh
        )
        step = make_composite_train_step(lm, tx, mesh, shardings)
        shard = lambda t, g: shard_composite_batch(mesh, t, g)
        desc = "x".join(str(v) for v in shape.values()) + " dp x fsdp x tp"

    k = args.steps_per_dispatch
    if k < 1:
        parser.error("--steps-per-dispatch must be >= 1")
    if args.steps % k:
        parser.error(f"--steps {args.steps} must divide by "
                     f"--steps-per-dispatch {k}")
    if k > 1:
        # K distinct host batches stacked on a scan axis, each sharded the
        # way this mode shards a single batch (spec lifted to P(None, *spec))
        pairs = []
        for _ in range(k):
            t = rng.integers(0, args.vocab,
                             size=(args.batch, args.seq)).astype(np.int32)
            pairs.append(shard(t, next_token_targets(t)))
        batch = tuple(_stack_sharded(leaves) for leaves in zip(*pairs))
        step = _make_chunked_step(step)
    else:
        batch = shard(tokens, targets)

    ckpt, start_step = None, 0
    if args.ckpt_dir:
        from distributed_ml_pytorch_tpu.utils.checkpoint import (
            Checkpointer,
            maybe_restore,
        )

        ckpt = Checkpointer(args.ckpt_dir, save_interval_steps=args.ckpt_every)
        if args.resume:
            state, start_step = maybe_restore(ckpt, state)
            if start_step:
                print(f"resumed from checkpoint step {start_step}")

    print(
        f"training {args.n_layers}-layer LM "
        f"({desc}, {mesh.devices.size} of {n_dev} devices)"
    )
    n_disp = args.steps // k
    t0 = time.perf_counter()
    loss = None
    for i in range(n_disp):
        state, loss = step(state, *batch)
        if i == 0:
            jax.block_until_ready(loss)
            t0 = time.perf_counter()  # exclude compile from the rate
        if ckpt is not None:
            ckpt.save(start_step + (i + 1) * k, state)
        if i % max(1, n_disp // 5) == 0:
            print(f"  step {i * k:4d}  loss {_scalar_loss(loss):.4f}")
    final = _scalar_loss(loss)
    dt = time.perf_counter() - t0
    rate = (n_disp - 1) * k * args.batch * args.seq / dt if n_disp > 1 else 0.0
    print(f"final loss {final:.4f}; ~{rate:.0f} tokens/s "
          f"(naive wall-clock, see bench_all.py for the differenced method)")
    if ckpt is not None:
        ckpt.save(start_step + args.steps, state, force=True)
        ckpt.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
