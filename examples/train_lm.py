"""Train the Transformer LM under any parallelism mode — executable example.

The reference's ``example/main.py`` is the CNN application; this is its
long-context counterpart: one script that builds a ``TransformerLM``, picks a
parallelism strategy, and trains on synthetic token streams, printing loss
and steady-state tokens/sec. It is the documented entry into the LM API:

    python -m examples.train_lm --mode single --steps 20
    python -m examples.train_lm --mode sp      # ring attention over seq axis
    python -m examples.train_lm --mode ulysses # all-to-all head re-sharding
    python -m examples.train_lm --mode fsdp    # ZeRO-3 sharded state
    python -m examples.train_lm --mode tp      # Megatron GSPMD shardings
    python -m examples.train_lm --mode composite  # 3-D dp x fsdp x tp

On one host, meshes come up on whatever devices exist (use
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
for the virtual-mesh simulation); on a pod, run under
``runtime.initialize_distributed`` and the same code scales.
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--mode", default="single",
                   choices=["single", "sp", "ulysses", "fsdp", "tp", "composite"])
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=8, help="global batch (sequences)")
    p.add_argument("--seq", type=int, default=256, help="global sequence length")
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--d-ff", type=int, default=256)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    p.add_argument("--pos-encoding", default="learned", choices=["learned", "rope"])
    p.add_argument("--remat", action="store_true",
                   help="per-block rematerialization (long sequences)")
    p.add_argument("--seed", type=int, default=0)
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.steps < 1:
        parser.error("--steps must be >= 1")
    if args.d_model % args.n_heads:
        parser.error(
            f"--d-model {args.d_model} must be divisible by --n-heads "
            f"{args.n_heads} (attention splits d_model into heads)"
        )

    import math

    import jax
    import jax.numpy as jnp
    import optax

    from distributed_ml_pytorch_tpu.models import TransformerLM
    from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
        create_lm_train_state,
        next_token_targets,
    )

    lm = TransformerLM(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff,
        max_len=max(args.seq, 256),
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
        pos_encoding=args.pos_encoding, remat=args.remat,
    )
    tx = optax.sgd(args.lr)
    rng = np.random.default_rng(args.seed)
    tokens = rng.integers(0, args.vocab, size=(args.batch, args.seq)).astype(np.int32)
    targets = next_token_targets(tokens)

    n_dev = len(jax.devices())
    if args.mode in ("sp", "ulysses"):
        from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
            make_sp_train_step,
            shard_lm_batch,
        )
        from distributed_ml_pytorch_tpu.parallel.ulysses import make_ulysses_train_step
        from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

        # each axis must divide what it shards (seq over the seq axis, batch
        # over data; Ulysses additionally shards heads over the seq axis)
        d_seq = math.gcd(n_dev, args.seq)
        if args.mode == "ulysses":
            d_seq = math.gcd(d_seq, args.n_heads)
        d_data = math.gcd(n_dev // d_seq, args.batch)
        mesh = make_mesh(
            {"data": d_data, "seq": d_seq}, devices=jax.devices()[: d_data * d_seq]
        )
        state = create_lm_train_state(lm, jax.random.key(args.seed), tx)
        make = make_sp_train_step if args.mode == "sp" else make_ulysses_train_step
        step = make(lm, tx, mesh)
        batch = shard_lm_batch(mesh, tokens, targets)
        desc = f"{d_data}x{d_seq} dp x seq ({'ring' if args.mode == 'sp' else 'all-to-all'})"
    elif args.mode in ("single", "fsdp"):
        from distributed_ml_pytorch_tpu.parallel.fsdp import (
            create_fsdp_train_state,
            make_fsdp_lm_train_step,
            param_shard_fraction,
            shard_fsdp_batch,
        )
        from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh
        from distributed_ml_pytorch_tpu.training.trainer import TrainState

        # the batch shards over the data axis, so the mesh cannot be wider;
        # "single" is literally fsdp on a 1-device mesh (same step factory,
        # provably identical update semantics — fsdp.make_sharded_step)
        n_fsdp = 1 if args.mode == "single" else math.gcd(n_dev, args.batch)
        mesh = make_mesh({"data": n_fsdp}, devices=jax.devices()[:n_fsdp])

        def init_fn(key):
            params = lm.init(key, jnp.zeros((1, 8), jnp.int32))["params"]
            return TrainState.create(params, tx)

        state, shardings = create_fsdp_train_state(
            init_fn, jax.random.key(args.seed), mesh
        )
        step = make_fsdp_lm_train_step(lm, tx, mesh, shardings)
        batch = shard_fsdp_batch(mesh, tokens, targets)
        desc = "single-device" if args.mode == "single" else (
            f"{n_fsdp}-way fsdp "
            f"({param_shard_fraction(state, mesh):.3f} of params/device)"
        )
    elif args.mode == "tp":
        from distributed_ml_pytorch_tpu.parallel.tensor_parallel import (
            create_tp_train_state,
            make_tp_train_step,
            shard_tp_batch,
        )
        from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

        # the model axis must divide every dimension tp shards
        d_model_axis = math.gcd(math.gcd(n_dev, args.n_heads),
                                math.gcd(args.d_ff, args.vocab))
        d_data = math.gcd(n_dev // d_model_axis, args.batch)
        mesh = make_mesh(
            {"data": d_data, "model": d_model_axis},
            devices=jax.devices()[: d_data * d_model_axis],
        )
        state = create_tp_train_state(lm, jax.random.key(args.seed), tx, mesh)
        step = make_tp_train_step(lm, tx, mesh)
        batch = shard_tp_batch(mesh, tokens, targets)
        desc = f"{d_data}x{d_model_axis} dp x tp"
    else:  # composite
        from distributed_ml_pytorch_tpu.parallel.composite import (
            create_composite_train_state,
            make_composite_train_step,
            shard_composite_batch,
        )
        from distributed_ml_pytorch_tpu.runtime.mesh import make_mesh

        # the model axis must divide heads/d_ff/vocab (1 when they're odd);
        # whatever it doesn't use goes to the combined data x fsdp group,
        # which must divide the batch
        d_model_c = math.gcd(2, math.gcd(args.n_heads, math.gcd(args.d_ff, args.vocab)))
        if d_model_c > n_dev:
            d_model_c = 1  # fewer devices than the model axis wants
        combined = math.gcd(n_dev // d_model_c, args.batch)
        d_data = 2 if combined % 2 == 0 and combined > 1 else 1
        shape = {"data": d_data, "fsdp": combined // d_data, "model": d_model_c}
        n_used = 1
        for v in shape.values():
            n_used *= v
        mesh = make_mesh(shape, devices=jax.devices()[:n_used])
        state, shardings = create_composite_train_state(
            lm, jax.random.key(args.seed), tx, mesh
        )
        step = make_composite_train_step(lm, tx, mesh, shardings)
        batch = shard_composite_batch(mesh, tokens, targets)
        desc = "x".join(str(v) for v in shape.values()) + " dp x fsdp x tp"

    print(
        f"training {args.n_layers}-layer LM "
        f"({desc}, {mesh.devices.size} of {n_dev} devices)"
    )
    t0 = time.perf_counter()
    loss = None
    for i in range(args.steps):
        state, loss = step(state, *batch)
        if i == 0:
            jax.block_until_ready(loss)
            t0 = time.perf_counter()  # exclude compile from the rate
        if i % max(1, args.steps // 5) == 0:
            print(f"  step {i:4d}  loss {float(loss):.4f}")
    final = float(loss)
    dt = time.perf_counter() - t0
    rate = (args.steps - 1) * args.batch * args.seq / dt if args.steps > 1 else 0.0
    print(f"final loss {final:.4f}; ~{rate:.0f} tokens/s "
          f"(naive wall-clock, see bench_all.py for the differenced method)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
