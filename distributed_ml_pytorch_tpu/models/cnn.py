"""L3 models: LeNet and AlexNet (parity with reference ``example/models.py:5-49``).

Flax ``linen`` modules, NHWC layout (TPU-native: XLA tiles NHWC convs onto the
MXU directly), architecture matched layer-for-layer to the reference so that
parameter counts and receptive fields agree:

- ``LeNet`` (reference ``example/models.py:5-23``): conv(3→6,k5,valid) → pool2
  → relu, conv(6→16,k5,valid) → channel dropout → pool2 → relu, flatten(400)
  → fc120 → relu → dropout → fc84 → relu → fc10.
- ``AlexNet`` (reference ``example/models.py:25-49``): five convs
  (3→64 k11 s4 p5, 64→192 k5 p2, 192→384 k3 p1, 384→256 k3 p1, 256→256 k3 p1)
  with three 2×2 maxpools, then a single ``Dense(num_classes)`` classifier on
  the 256-feature map (1×1 spatial at 32×32 input).

Weight init follows the reference's torch defaults (Kaiming-uniform with
fan_in, uniform bias) closely enough for training parity; compute dtype is
configurable so the hot path can run bfloat16 on the MXU with float32 params.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

# the reshape-max pool (first-max tie vjp, round 5) moved to the kernels
# layer so the Pallas-fused conv epilogues (round 9) share its tie
# semantics; re-exported here because this is its historical import site
from distributed_ml_pytorch_tpu.ops.fused_conv import (  # noqa: F401
    max_pool_2x2,
    relu_pool2,
)


class LeNet(nn.Module):
    """LeNet-5 variant (reference ``example/models.py:5-23``)."""

    num_classes: int = 10
    dropout_rate: float = 0.5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = x.astype(self.dtype)
        # conv1: 3→6 k5 VALID; torch F.max_pool2d(...,2) then relu (:16)
        x = nn.Conv(6, (5, 5), padding="VALID", dtype=self.dtype, name="conv1")(x)
        x = nn.relu(max_pool_2x2(x))
        # conv2: 6→16 k5 VALID; Dropout2d (channel dropout) precedes pool (:17)
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype, name="conv2")(x)
        # torch Dropout2d zeroes whole channels: broadcast over H,W (NHWC dims 1,2)
        x = nn.Dropout(self.dropout_rate, broadcast_dims=(1, 2), deterministic=not train)(x)
        x = nn.relu(max_pool_2x2(x))
        x = x.reshape((x.shape[0], -1))  # 5*5*16 = 400 (:18)
        x = nn.relu(nn.Dense(120, dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(nn.Dense(84, dtype=self.dtype, name="fc2")(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc3")(x)
        return x.astype(jnp.float32)


class AlexNet(nn.Module):
    """CIFAR-sized AlexNet (reference ``example/models.py:25-49``).

    ``fused_epilogue=True`` swaps each relu→pool tail for the Pallas-fused
    ``relu_pool2`` kernel (``ops/fused_conv.py``): bit-identical forward,
    first-max-tie backward matching the unfused chain element-for-element
    (tested), so the flag changes kernels, never trajectories or the param
    tree — checkpoints are interchangeable. Off-TPU it lowers to the exact
    unfused chain, so the flag is safe to leave on. The conv bias stays
    inside ``nn.Conv`` (XLA folds it into the conv epilogue — the audit's
    shipped state); the fused op's optional-bias form exists for callers
    that keep bias separate.
    """

    num_classes: int = 10
    dtype: Any = jnp.float32
    fused_epilogue: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        del train  # no dropout in the reference AlexNet
        x = x.astype(self.dtype)
        conv = lambda f, k, s, p, name: nn.Conv(
            f, (k, k), strides=(s, s), padding=[(p, p), (p, p)], dtype=self.dtype, name=name
        )
        pool_tail = (
            relu_pool2 if self.fused_epilogue
            else lambda v: max_pool_2x2(nn.relu(v))
        )
        x = pool_tail(conv(64, 11, 4, 5, "conv1")(x))     # 32→8→4
        x = pool_tail(conv(192, 5, 1, 2, "conv2")(x))     # 4→2
        x = nn.relu(conv(384, 3, 1, 1, "conv3")(x))
        x = nn.relu(conv(256, 3, 1, 1, "conv4")(x))
        x = pool_tail(conv(256, 3, 1, 1, "conv5")(x))     # 2→1
        x = x.reshape((x.shape[0], -1))                   # 256 (:47-48)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="classifier")(x)
        return x.astype(jnp.float32)


def get_model(name: str, num_classes: int = 10, dtype: Any = jnp.float32,
              fused_epilogue: bool = False) -> nn.Module:
    """Model registry keyed by the CLI ``--model`` flag. ``fused_epilogue``
    selects the Pallas conv-epilogue kernels where the model supports them
    (AlexNet today; others ignore it)."""
    name = name.lower()
    if name == "lenet":
        return LeNet(num_classes=num_classes, dtype=dtype)
    if name == "alexnet":
        return AlexNet(num_classes=num_classes, dtype=dtype,
                       fused_epilogue=fused_epilogue)
    if name.startswith("resnet"):
        from distributed_ml_pytorch_tpu.models.resnet import get_resnet

        return get_resnet(name, num_classes=num_classes, dtype=dtype)
    raise ValueError(f"unknown model {name!r}")
