"""L3 models: LeNet and AlexNet (parity with reference ``example/models.py:5-49``).

Flax ``linen`` modules, NHWC layout (TPU-native: XLA tiles NHWC convs onto the
MXU directly), architecture matched layer-for-layer to the reference so that
parameter counts and receptive fields agree:

- ``LeNet`` (reference ``example/models.py:5-23``): conv(3→6,k5,valid) → pool2
  → relu, conv(6→16,k5,valid) → channel dropout → pool2 → relu, flatten(400)
  → fc120 → relu → dropout → fc84 → relu → fc10.
- ``AlexNet`` (reference ``example/models.py:25-49``): five convs
  (3→64 k11 s4 p5, 64→192 k5 p2, 192→384 k3 p1, 384→256 k3 p1, 256→256 k3 p1)
  with three 2×2 maxpools, then a single ``Dense(num_classes)`` classifier on
  the 256-feature map (1×1 spatial at 32×32 input).

Weight init follows the reference's torch defaults (Kaiming-uniform with
fan_in, uniform bias) closely enough for training parity; compute dtype is
configurable so the hot path can run bfloat16 on the MXU with float32 params.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


@jax.custom_vjp
def max_pool_2x2(x: jnp.ndarray) -> jnp.ndarray:
    """2×2 stride-2 max pool via reshape+max — the fast-backward pooling.

    Forward values equal ``nn.max_pool(x, (2, 2), strides=(2, 2))`` exactly
    (non-overlapping windows). The point is the BACKWARD: ``nn.max_pool``'s
    vjp lowers to XLA ``select_and_scatter``, measured at 7.1 µs of the
    57.8 µs batch-64 AlexNet train step (12%, device-true); this
    formulation's backward is a first-max one-hot select over the four
    window slots — plain elementwise ops XLA fuses — and cuts the step to
    53.9 µs (+7.2% img/s). The custom vjp routes each window's cotangent
    to the FIRST maximal element in window row-major order, matching both
    torch's MaxPool2d and the previous select_and_scatter lowering
    bit-for-bit on ties (common right after relu, where windows tie at 0)
    — NOT ``jnp.max``'s default split-among-ties vjp — so training
    trajectories (and the matched-init torch parity leg) are unchanged.
    Requires even spatial dims.
    """
    return _pool2_fwd(x)[0]


def _pool2_windows(x):
    b, h, w, c = x.shape
    xw = x.reshape(b, h // 2, 2, w // 2, 2, c).transpose(0, 1, 3, 2, 4, 5)
    return xw.reshape(b, h // 2, w // 2, 4, c)  # window row-major slot order


def _pool2_fwd(x):
    xw = _pool2_windows(x)
    m = xw.max(axis=3)
    return m, (x, m)


def _pool2_bwd(res, g):
    x, m = res
    b, h, w, c = x.shape
    xw = _pool2_windows(x)
    eq = (xw == m[:, :, :, None, :])
    # first max in slot order: an equal slot wins iff no earlier slot equals
    first = eq & (jnp.cumsum(eq, axis=3) == 1)
    scat = first.astype(g.dtype) * g[:, :, :, None, :]
    gx = scat.reshape(b, h // 2, w // 2, 2, 2, c).transpose(0, 1, 3, 2, 4, 5)
    return (gx.reshape(b, h, w, c),)


max_pool_2x2.defvjp(_pool2_fwd, _pool2_bwd)


class LeNet(nn.Module):
    """LeNet-5 variant (reference ``example/models.py:5-23``)."""

    num_classes: int = 10
    dropout_rate: float = 0.5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = x.astype(self.dtype)
        # conv1: 3→6 k5 VALID; torch F.max_pool2d(...,2) then relu (:16)
        x = nn.Conv(6, (5, 5), padding="VALID", dtype=self.dtype, name="conv1")(x)
        x = nn.relu(max_pool_2x2(x))
        # conv2: 6→16 k5 VALID; Dropout2d (channel dropout) precedes pool (:17)
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype, name="conv2")(x)
        # torch Dropout2d zeroes whole channels: broadcast over H,W (NHWC dims 1,2)
        x = nn.Dropout(self.dropout_rate, broadcast_dims=(1, 2), deterministic=not train)(x)
        x = nn.relu(max_pool_2x2(x))
        x = x.reshape((x.shape[0], -1))  # 5*5*16 = 400 (:18)
        x = nn.relu(nn.Dense(120, dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(nn.Dense(84, dtype=self.dtype, name="fc2")(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc3")(x)
        return x.astype(jnp.float32)


class AlexNet(nn.Module):
    """CIFAR-sized AlexNet (reference ``example/models.py:25-49``)."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        del train  # no dropout in the reference AlexNet
        x = x.astype(self.dtype)
        conv = lambda f, k, s, p, name: nn.Conv(
            f, (k, k), strides=(s, s), padding=[(p, p), (p, p)], dtype=self.dtype, name=name
        )
        x = nn.relu(conv(64, 11, 4, 5, "conv1")(x))      # 32→8
        x = max_pool_2x2(x)                               # 8→4
        x = nn.relu(conv(192, 5, 1, 2, "conv2")(x))
        x = max_pool_2x2(x)                               # 4→2
        x = nn.relu(conv(384, 3, 1, 1, "conv3")(x))
        x = nn.relu(conv(256, 3, 1, 1, "conv4")(x))
        x = nn.relu(conv(256, 3, 1, 1, "conv5")(x))
        x = max_pool_2x2(x)                               # 2→1
        x = x.reshape((x.shape[0], -1))                   # 256 (:47-48)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="classifier")(x)
        return x.astype(jnp.float32)


def get_model(name: str, num_classes: int = 10, dtype: Any = jnp.float32) -> nn.Module:
    """Model registry keyed by the CLI ``--model`` flag."""
    name = name.lower()
    if name == "lenet":
        return LeNet(num_classes=num_classes, dtype=dtype)
    if name == "alexnet":
        return AlexNet(num_classes=num_classes, dtype=dtype)
    if name.startswith("resnet"):
        from distributed_ml_pytorch_tpu.models.resnet import get_resnet

        return get_resnet(name, num_classes=num_classes, dtype=dtype)
    raise ValueError(f"unknown model {name!r}")
