"""L3 models: LeNet and AlexNet (parity with reference ``example/models.py:5-49``).

Flax ``linen`` modules, NHWC layout (TPU-native: XLA tiles NHWC convs onto the
MXU directly), architecture matched layer-for-layer to the reference so that
parameter counts and receptive fields agree:

- ``LeNet`` (reference ``example/models.py:5-23``): conv(3→6,k5,valid) → pool2
  → relu, conv(6→16,k5,valid) → channel dropout → pool2 → relu, flatten(400)
  → fc120 → relu → dropout → fc84 → relu → fc10.
- ``AlexNet`` (reference ``example/models.py:25-49``): five convs
  (3→64 k11 s4 p5, 64→192 k5 p2, 192→384 k3 p1, 384→256 k3 p1, 256→256 k3 p1)
  with three 2×2 maxpools, then a single ``Dense(num_classes)`` classifier on
  the 256-feature map (1×1 spatial at 32×32 input).

Weight init follows the reference's torch defaults (Kaiming-uniform with
fan_in, uniform bias) closely enough for training parity; compute dtype is
configurable so the hot path can run bfloat16 on the MXU with float32 params.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn


class LeNet(nn.Module):
    """LeNet-5 variant (reference ``example/models.py:5-23``)."""

    num_classes: int = 10
    dropout_rate: float = 0.5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = x.astype(self.dtype)
        # conv1: 3→6 k5 VALID; torch F.max_pool2d(...,2) then relu (:16)
        x = nn.Conv(6, (5, 5), padding="VALID", dtype=self.dtype, name="conv1")(x)
        x = nn.relu(nn.max_pool(x, (2, 2), strides=(2, 2)))
        # conv2: 6→16 k5 VALID; Dropout2d (channel dropout) precedes pool (:17)
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype, name="conv2")(x)
        # torch Dropout2d zeroes whole channels: broadcast over H,W (NHWC dims 1,2)
        x = nn.Dropout(self.dropout_rate, broadcast_dims=(1, 2), deterministic=not train)(x)
        x = nn.relu(nn.max_pool(x, (2, 2), strides=(2, 2)))
        x = x.reshape((x.shape[0], -1))  # 5*5*16 = 400 (:18)
        x = nn.relu(nn.Dense(120, dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(nn.Dense(84, dtype=self.dtype, name="fc2")(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc3")(x)
        return x.astype(jnp.float32)


class AlexNet(nn.Module):
    """CIFAR-sized AlexNet (reference ``example/models.py:25-49``)."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        del train  # no dropout in the reference AlexNet
        x = x.astype(self.dtype)
        conv = lambda f, k, s, p, name: nn.Conv(
            f, (k, k), strides=(s, s), padding=[(p, p), (p, p)], dtype=self.dtype, name=name
        )
        x = nn.relu(conv(64, 11, 4, 5, "conv1")(x))      # 32→8
        x = nn.max_pool(x, (2, 2), strides=(2, 2))        # 8→4
        x = nn.relu(conv(192, 5, 1, 2, "conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))        # 4→2
        x = nn.relu(conv(384, 3, 1, 1, "conv3")(x))
        x = nn.relu(conv(256, 3, 1, 1, "conv4")(x))
        x = nn.relu(conv(256, 3, 1, 1, "conv5")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))        # 2→1
        x = x.reshape((x.shape[0], -1))                   # 256 (:47-48)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="classifier")(x)
        return x.astype(jnp.float32)


def get_model(name: str, num_classes: int = 10, dtype: Any = jnp.float32) -> nn.Module:
    """Model registry keyed by the CLI ``--model`` flag."""
    name = name.lower()
    if name == "lenet":
        return LeNet(num_classes=num_classes, dtype=dtype)
    if name == "alexnet":
        return AlexNet(num_classes=num_classes, dtype=dtype)
    if name.startswith("resnet"):
        from distributed_ml_pytorch_tpu.models.resnet import get_resnet

        return get_resnet(name, num_classes=num_classes, dtype=dtype)
    raise ValueError(f"unknown model {name!r}")
