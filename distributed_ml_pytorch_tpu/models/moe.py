"""Mixture-of-Experts layers for the Transformer LM (Switch-style top-1).

The reference has no MoE (SURVEY.md §2.4 marks EP ABSENT) — this is a
capability extension, expressed the TPU way (GShard/Switch): routing is a
pair of dense one-hot einsums (dispatch and combine) over stacked expert
weights, so there is **no data-dependent control flow** — the whole layer is
three einsums XLA can partition. Sharding the stacked expert axis over an
``expert`` mesh mesh axis turns those einsums into all-to-all dispatch
/combine automatically (``parallel/expert_parallel.py``); unsharded, the same
code is a dense reference implementation.

Key shapes (B batch, S seq, D d_model, F d_ff, E experts, C capacity):

- router probs  ``[B, S, E]`` → top-1 expert per token
- dispatch      ``[B, S, E, C]`` one-hot (token → its slot in its expert)
- expert in     ``[E, B, C, D]`` = einsum(dispatch, x)
- expert FFN    ``[E, B, C, D]`` via stacked ``w_up [E, D, F]``, ``w_down [E, F, D]``
- combine       ``[B, S, D]`` = einsum(dispatch * router_prob, expert_out)

Tokens beyond an expert's capacity are *dropped* (pass through the residual
unchanged) — Switch semantics; the load-balance auxiliary loss pushes the
router toward uniform load so drops stay rare.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from distributed_ml_pytorch_tpu.models.transformer import MultiHeadAttention


def switch_route(
    router_probs: jnp.ndarray, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-1 routing with per-expert capacity, no data-dependent shapes.

    Returns ``(dispatch [B,S,E,C], combine [B,S,E,C])``; ``combine`` carries
    the router probability so the gradient reaches the router (straight-
    through on the argmax, exactly Switch).
    """
    b, s, e = router_probs.shape
    expert_idx = jnp.argmax(router_probs, axis=-1)                 # [B,S]
    # queue positions are COUNTS — int32, never the activation dtype: a
    # bf16 cumsum loses integer exactness past 256 and collides slots
    onehot_i = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)
    # position of each token within its expert's queue (exclusive cumsum
    # over the sequence), computed densely per expert
    pos_in_expert = jnp.cumsum(onehot_i, axis=1) - onehot_i         # [B,S,E]
    kept = ((pos_in_expert < capacity) & (onehot_i > 0)).astype(
        router_probs.dtype
    )                                                               # [B,S,E]
    slot = jax.nn.one_hot(
        jnp.sum(pos_in_expert * onehot_i, axis=-1), capacity,
        dtype=router_probs.dtype,
    )                                                               # [B,S,C]
    dispatch = kept[..., None] * slot[:, :, None, :]                # [B,S,E,C]
    gate = jnp.sum(router_probs * kept, axis=-1)                    # [B,S]
    combine = dispatch * gate[:, :, None, None]
    return dispatch, combine


def topk_route(
    router_probs: jnp.ndarray,
    capacity: int,
    k: int = 2,
    normalize: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """GShard-style top-k routing with per-expert capacity, dense shapes.

    Each token is dispatched to its ``k`` highest-probability experts.
    Capacity slots are granted **rank-major**: every token's rank-0 choice
    is queued before any token's rank-1 choice, so second choices are the
    first dropped under pressure (GShard's priority rule). ``normalize``
    rescales the k gates to sum to 1 (standard for k≥2); with ``k=1,
    normalize=False`` this reduces exactly to :func:`switch_route`.

    Returns ``(dispatch [B,S,E,C], combine [B,S,E,C])`` — identical
    contracts to :func:`switch_route`, so ``MoEMLP``'s einsums (and the
    ``expert``-axis sharding that turns them into all-to-alls) are unchanged.
    """
    b, s, e = router_probs.shape
    if not 1 <= k <= e:
        raise ValueError(f"top-k routing needs 1 <= k <= n_experts, got k={k}, e={e}")
    gate_sk, idx = jax.lax.top_k(router_probs, k)                   # [B,S,K], rank-sorted
    # queue positions are COUNTS — int32, never the activation dtype: a bf16
    # cumsum loses integer exactness past 256 and collides slots (the K·S
    # combined axis reaches that twice as fast as top-1)
    oh_ks = jnp.moveaxis(jax.nn.one_hot(idx, e, dtype=jnp.int32), 2, 1)  # [B,K,S,E]
    # queue position per (choice, token): exclusive cumsum over the combined
    # rank-major (K·S) axis — rank 0 occupies slots before any rank 1
    flat = oh_ks.reshape(b, k * s, e)
    pos = jnp.cumsum(flat, axis=1) - flat                           # [B,K*S,E]
    kept = ((pos < capacity) & (flat > 0)).astype(router_probs.dtype)
    slot = jax.nn.one_hot(
        jnp.sum(pos * flat, axis=-1), capacity,
        dtype=router_probs.dtype,
    )                                                               # [B,K*S,C]
    disp_flat = kept[..., None] * slot[:, :, None, :]               # [B,K*S,E,C]
    dispatch_k = disp_flat.reshape(b, k, s, e, capacity)
    dispatch = jnp.sum(dispatch_k, axis=1)                          # [B,S,E,C]
    gate_ks = jnp.moveaxis(gate_sk, 2, 1)                           # [B,K,S]
    if normalize:
        gate_ks = gate_ks / jnp.maximum(
            jnp.sum(gate_ks, axis=1, keepdims=True), 1e-9
        )
    combine = jnp.sum(dispatch_k * gate_ks[..., None, None], axis=1)
    return dispatch, combine


def load_balance_loss(router_probs: jnp.ndarray) -> jnp.ndarray:
    """Switch aux loss (eq. 4): E · Σ_e (fraction argmax-routed to e) · (mean prob of e).

    ``f_e`` uses the **pre-capacity** argmax assignment, not the truncated
    dispatch mask — under router collapse the hot expert's fraction must
    approach 1.0 (not saturate at capacity/seq) so the corrective gradient
    stays strong exactly when balancing matters most.
    """
    e = router_probs.shape[-1]
    expert_onehot = jax.nn.one_hot(
        jnp.argmax(router_probs, axis=-1), e, dtype=router_probs.dtype
    )
    frac_tokens = jnp.mean(expert_onehot, axis=(0, 1))               # [E]
    frac_probs = jnp.mean(router_probs, axis=(0, 1))                 # [E]
    return e * jnp.sum(frac_tokens * frac_probs)


class MoEMLP(nn.Module):
    """Switch FFN: top-1 router over ``n_experts`` stacked expert MLPs.

    The stacked leading expert axis of ``w_up``/``b_up``/``w_down``/``b_down``
    is the one ``parallel/expert_parallel.ep_param_specs`` shards over the
    ``expert`` mesh axis. The aux load-balance loss is ``sow``n under the
    ``"losses"`` collection (reduced by the train step).
    """

    d_model: int
    d_ff: int
    n_experts: int = 4
    capacity_factor: float = 2.0
    dtype: jnp.dtype = jnp.float32
    router_top_k: int = 1  # 1 = Switch; ≥2 = GShard top-k with gate renorm

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        e = self.n_experts
        # top-k emits k assignments per token, so capacity provisions k·S/E
        capacity = max(1, int(self.capacity_factor * self.router_top_k * s / e))
        router = nn.Dense(e, use_bias=False, dtype=self.dtype, name="router")
        probs = jax.nn.softmax(router(x).astype(jnp.float32), axis=-1).astype(x.dtype)
        if self.router_top_k == 1:
            dispatch, combine = switch_route(probs, capacity)
        else:
            dispatch, combine = topk_route(probs, capacity, k=self.router_top_k)
        self.sow("losses", "load_balance", load_balance_loss(probs))

        w_up = self.param(
            "w_up", nn.initializers.lecun_normal(batch_axis=(0,)), (e, d, self.d_ff)
        )
        b_up = self.param("b_up", nn.initializers.zeros, (e, self.d_ff))
        w_down = self.param(
            "w_down", nn.initializers.lecun_normal(batch_axis=(0,)), (e, self.d_ff, d)
        )
        b_down = self.param("b_down", nn.initializers.zeros, (e, d))

        xin = jnp.einsum("bsec,bsd->ebcd", dispatch, x)              # dispatch
        h = jnp.einsum("ebcd,edf->ebcf", xin, w_up) + b_up[:, None, None, :]
        h = nn.gelu(h)
        out = jnp.einsum("ebcf,efd->ebcd", h, w_down) + b_down[:, None, None, :]
        return jnp.einsum("bsec,ebcd->bsd", combine, out)            # combine


class MoEBlock(nn.Module):
    """Pre-LN Transformer block with a Switch-MoE FFN."""

    d_model: int
    n_heads: int
    d_ff: int
    n_experts: int = 4
    capacity_factor: float = 2.0
    dtype: jnp.dtype = jnp.float32
    router_top_k: int = 1
    attn_fn: Optional[Callable] = None
    decode: bool = False
    cache_size: int = 0
    decode_block: int = 0
    kv_quant: bool = False
    fused_qkv: bool = False

    @nn.compact
    def __call__(self, x, positions=None):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + MultiHeadAttention(self.d_model, self.n_heads, self.dtype,
                                   self.attn_fn, decode=self.decode,
                                   cache_size=self.cache_size,
                                   decode_block=self.decode_block,
                                   kv_quant=self.kv_quant,
                                   fused_qkv=self.fused_qkv,
                                   name="attn")(h, positions)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + MoEMLP(
            self.d_model, self.d_ff, self.n_experts, self.capacity_factor,
            self.dtype, router_top_k=self.router_top_k, name="moe",
        )(h)
        return x


class MoETransformerLM(nn.Module):
    """Causal LM whose FFNs are Switch-MoE layers (every block)."""

    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    n_experts: int = 4
    capacity_factor: float = 2.0
    max_len: int = 131072
    dtype: jnp.dtype = jnp.float32
    remat: bool = False
    router_top_k: int = 1
    attn_fn: Optional[Callable] = None
    #: decode support (models/generate.py): same contract as TransformerLM —
    #: the attention caches K/V; the MoE FFN needs no cache at all (routing
    #: is per token, and a single-token step's capacity floor of 1 slot per
    #: expert can never drop the token). Semantic note: because decode
    #: steps never drop, decode logits match the teacher-forced forward
    #: exactly ONLY where the full forward didn't drop tokens to capacity —
    #: over-capacity prompts route more tokens through expert FFNs at
    #: decode time than they did in training's forward (tested with a
    #: drop-free capacity in tests/test_moe_topk.py)
    decode: bool = False
    cache_size: int = 0
    decode_block: int = 0
    kv_quant: bool = False
    fused_qkv: bool = False

    @nn.compact
    def __call__(self, tokens, positions=None):
        if positions is None:
            positions = jnp.arange(tokens.shape[-1])[None, :]
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype, name="tok_embed")(tokens)
        x = x + nn.Embed(self.max_len, self.d_model, dtype=self.dtype, name="pos_embed")(positions)
        block_cls = nn.remat(MoEBlock) if self.remat and not self.decode else MoEBlock
        for i in range(self.n_layers):
            x = block_cls(
                self.d_model, self.n_heads, self.d_ff, self.n_experts,
                self.capacity_factor, self.dtype,
                router_top_k=self.router_top_k, attn_fn=self.attn_fn,
                decode=self.decode, cache_size=self.cache_size,
                decode_block=self.decode_block, kv_quant=self.kv_quant,
                fused_qkv=self.fused_qkv,
                name=f"block_{i}",
            )(x, positions)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.vocab_size, use_bias=False, dtype=self.dtype, name="lm_head")(x)
