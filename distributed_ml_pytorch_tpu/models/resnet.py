"""ResNet-18 / ResNet-50 (BASELINE.md configs #4 and #5).

The reference has no ResNet — ``BASELINE.json`` config #4 is explicitly "ResNet-18
swapped into models.py" (an *extension* of reference ``example/models.py``) and
config #5 is ResNet-50 at pod scale. These are standard He et al. residual
networks with one TPU-native design decision:

**GroupNorm instead of BatchNorm.** BatchNorm carries mutable running
statistics (a second variable collection threaded through every train/eval
step) and, under data parallelism, either desyncs per replica or needs a
cross-replica ``pmean`` of batch stats each step. GroupNorm is stateless —
the whole model stays a pure function of ``params``, which keeps every
parallel strategy in this framework (sync ``psum`` DP, async parameter
server, local-SGD) working on the same flat-parameter contract
(``utils/serialization.py``) with zero special cases, and it matches BN's
accuracy at the batch sizes used here. XLA fuses the normalization chain into
the surrounding convs either way.

Stems: the ImageNet stem (7×7/2 conv + 3×3/2 maxpool) shrinks a 32×32 CIFAR
image to 8×8 before the first block, so for small inputs the standard CIFAR
stem (3×3/1, no pool) is used. ``stem="auto"`` picks by input size at call
time (shapes are static under jit, so this is a trace-time branch).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax.numpy as jnp
from flax import linen as nn


def _norm(dtype: Any) -> Callable:
    # 32 channels/group is the GN paper's default; min() guards thin stems.
    def make(num_features: int, name: str):
        return nn.GroupNorm(
            num_groups=None,
            group_size=min(32, num_features),
            dtype=dtype,
            name=name,
        )

    return make


class BasicBlock(nn.Module):
    """2×3×3 residual block (ResNet-18/34)."""

    features: int
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        norm = _norm(self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.features, (3, 3), strides=(self.strides, self.strides),
                 padding=[(1, 1), (1, 1)], name="conv1")(x)
        y = nn.relu(norm(self.features, "norm1")(y))
        y = conv(self.features, (3, 3), padding=[(1, 1), (1, 1)], name="conv2")(y)
        y = norm(self.features, "norm2")(y)
        if residual.shape != y.shape:
            residual = conv(self.features, (1, 1),
                            strides=(self.strides, self.strides), name="downsample")(residual)
            residual = norm(self.features, "norm_down")(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1×1 → 3×3 → 1×1 bottleneck with 4× expansion (ResNet-50/101/152)."""

    features: int
    strides: int = 1
    dtype: Any = jnp.float32
    expansion: int = 4

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        norm = _norm(self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        out_features = self.features * self.expansion
        residual = x
        y = conv(self.features, (1, 1), name="conv1")(x)
        y = nn.relu(norm(self.features, "norm1")(y))
        y = conv(self.features, (3, 3), strides=(self.strides, self.strides),
                 padding=[(1, 1), (1, 1)], name="conv2")(y)
        y = nn.relu(norm(self.features, "norm2")(y))
        y = conv(out_features, (1, 1), name="conv3")(y)
        y = norm(out_features, "norm3")(y)
        if residual.shape != y.shape:
            residual = conv(out_features, (1, 1),
                            strides=(self.strides, self.strides), name="downsample")(residual)
            residual = norm(out_features, "norm_down")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Residual network over NHWC inputs.

    ``stage_sizes`` is blocks-per-stage, e.g. (2, 2, 2, 2) for ResNet-18 or
    (3, 4, 6, 3) for ResNet-50; stage widths are 64·2^i.
    """

    stage_sizes: Sequence[int]
    block: type = BasicBlock
    num_classes: int = 10
    stem: str = "auto"  # "imagenet" | "cifar" | "auto"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        del train  # stateless norm: identical train/eval graphs
        x = x.astype(self.dtype)
        norm = _norm(self.dtype)
        stem = self.stem
        if stem == "auto":
            stem = "cifar" if x.shape[1] <= 64 else "imagenet"
        if stem == "imagenet":
            x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                        use_bias=False, dtype=self.dtype, name="stem_conv")(x)
            x = nn.relu(norm(64, "stem_norm")(x))
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        else:
            x = nn.Conv(64, (3, 3), padding=[(1, 1), (1, 1)],
                        use_bias=False, dtype=self.dtype, name="stem_conv")(x)
            x = nn.relu(norm(64, "stem_norm")(x))
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if (i > 0 and j == 0) else 1
                x = self.block(
                    features=64 * 2 ** i, strides=strides, dtype=self.dtype,
                    name=f"stage{i + 1}_block{j + 1}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="classifier")(x)
        return x.astype(jnp.float32)


def get_resnet(name: str, num_classes: int = 10, dtype: Any = jnp.float32,
               stem: str = "auto") -> ResNet:
    configs = {
        "resnet18": dict(stage_sizes=(2, 2, 2, 2), block=BasicBlock),
        "resnet34": dict(stage_sizes=(3, 4, 6, 3), block=BasicBlock),
        "resnet50": dict(stage_sizes=(3, 4, 6, 3), block=BottleneckBlock),
        "resnet101": dict(stage_sizes=(3, 4, 23, 3), block=BottleneckBlock),
    }
    if name not in configs:
        raise ValueError(f"unknown resnet {name!r} (have {sorted(configs)})")
    return ResNet(num_classes=num_classes, dtype=dtype, stem=stem, **configs[name])
