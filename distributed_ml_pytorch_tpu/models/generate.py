"""Autoregressive decoding for the Transformer LM family.

The reference is a CNN classifier framework with no text generation at all
(SURVEY.md §2, image models only) — this is a capability extension that
completes the LM story: train with ``parallel/seq_parallel.py`` (or tp/pp),
then sample from the trained params here.

TPU-native decode structure:

- **Prefill** runs the whole prompt through the model in ONE call, writing
  every layer's K/V into the cache (``models/transformer.MultiHeadAttention``
  with ``decode=True``) — the MXU-friendly bulk phase.
- **Generation** is a ``lax.scan`` over single-token steps: one compiled
  program for the entire sampled continuation, cache threaded as carry — no
  per-token Python dispatch, no growing shapes (the cache is statically
  sized to ``prompt + max_new_tokens``). The per-layer cache
  ``dynamic_update_slice``s ARE updated in place inside the scan (measured:
  per-step time is flat in cache length; do not "optimize" them — a
  standalone, non-carried step DOES pay a full cache copy per append, and
  a pallas ``input_output_aliases`` append kernel still materialized
  copies on this runtime, so the scan-carry structure is the fast path).
- Sampling is temperature-controlled categorical (temperature 0 → greedy
  argmax) with optional top-k and/or nucleus (top-p) truncation
  (:func:`sample_tokens`), per-step rng folded from one key, fully
  deterministic given ``(params, prompt, rng)``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def sample_tokens(
    logits: jnp.ndarray,
    rng: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """One sampling decision over ``[B, vocab]`` logits.

    ``temperature=0`` is greedy argmax (k/p ignored — argmax is already the
    1-token nucleus). Otherwise: optional top-k truncation (keep the k
    highest logits), then optional nucleus truncation (keep the smallest
    prefix of the sorted distribution whose probability mass reaches
    ``top_p``; the top token always survives), then categorical sampling at
    the given temperature. All static-shape ops (sort + masks), so the
    whole thing lives inside the scanned decode program. Tokens whose
    logit exactly ties the nucleus cut-off logit are kept (the mask maps
    back through a threshold compare), matching the usual top-p contract.
    """
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    vocab = logits.shape[-1]
    neg = jnp.asarray(jnp.finfo(logits.dtype).min, logits.dtype)
    top_k = min(int(top_k), vocab) if top_k else 0
    if top_k > 0 or top_p < 1.0:
        # ONE descending sort serves both filters: the k-th entry is the
        # top-k threshold, and masking the sorted tail past k-1 gives the
        # nucleus pass the post-top-k distribution without re-sorting
        sort_desc = jnp.sort(logits, axis=-1)[..., ::-1]
        if top_k > 0:
            kth = sort_desc[..., top_k - 1][..., None]
            logits = jnp.where(logits < kth, neg, logits)
            sort_desc = jnp.where(jnp.arange(vocab) >= top_k, neg, sort_desc)
        if top_p < 1.0:
            probs = jax.nn.softmax(sort_desc, axis=-1)
            # exclusive cumulative mass: a token is cut iff the mass BEFORE
            # it already reaches top_p — the argmax token can never be cut
            exceeded = (jnp.cumsum(probs, axis=-1) - probs) >= top_p
            exceeded = exceeded.at[..., 0].set(False)  # even at top_p = 0
            cut = jnp.where(exceeded, jnp.inf, sort_desc)
            thresh = jnp.min(cut, axis=-1, keepdims=True)
            logits = jnp.where(logits < thresh, neg, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def _decode_model(model, cache_size: int):
    return model.clone(decode=True, cache_size=cache_size, attn_fn=None)


def _check_max_len(model, total: int) -> None:
    """RoPE rotates by position instead of indexing a table, so max_len does
    not bound its positions — the guard protects only learned embeddings."""
    max_len = getattr(model, "max_len", None)
    if (
        max_len is not None
        and total > max_len
        and getattr(model, "pos_encoding", "learned") != "rope"
    ):
        raise ValueError(
            f"prompt + max_new_tokens = {total} exceeds the model's max_len "
            f"{max_len} — position embeddings would go out of range"
        )


def init_cache(model, batch: int, cache_size: int):
    """Allocate the per-layer K/V cache (zeros, cursor at 0) for ``batch``
    sequences of total length ``cache_size``."""
    dec = _decode_model(model, cache_size)
    variables = jax.eval_shape(
        lambda: dec.init(
            jax.random.key(0),
            jnp.zeros((batch, 1), jnp.int32),
            jnp.zeros((batch, 1), jnp.int32),
        )
    )
    return jax.tree.map(jnp.zeros_like, variables["cache"])


def generate(
    model,
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """Sample ``max_new_tokens`` continuations of ``prompt`` ([B, P] int32).

    Returns ``[B, P + max_new_tokens]`` tokens. ``temperature=0`` is greedy;
    otherwise categorical sampling at the given temperature (``rng``
    required) with optional ``top_k`` / nucleus ``top_p`` truncation
    (:func:`sample_tokens`). Jit-compiled end-to-end: one prefill program +
    one scanned generation program, both cached across calls with the same
    shapes.
    """
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 sampling needs an rng key")
    rng = rng if rng is not None else jax.random.key(0)
    b, p = prompt.shape
    total = p + max_new_tokens
    _check_max_len(model, total)
    if max_new_tokens < 1:
        return prompt
    cache = init_cache(model, b, total)
    dec = _decode_model(model, total)
    return _generate_jit(
        dec, int(max_new_tokens), float(temperature), int(top_k), float(top_p),
        params, cache, prompt, rng
    )


def generate_tp(
    model,
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    mesh,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    data_axis: str = "data",
    model_axis: str = "model",
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """Tensor-parallel decode: ``generate`` semantics on a dp×tp mesh.

    Capability symmetry with the training-side TP
    (``parallel/tensor_parallel.py``): the same Megatron layout serves
    inference — params sharded by :func:`tp_param_specs` (q/k/v column-,
    o row-, lm_head vocab-sharded), batch over ``data_axis``, and the K/V
    cache sharded over *heads* on ``model_axis`` (heads follow the q/k/v
    column shards, so cache append + cached attention stay device-local;
    the per-block all-reduce on attention/MLP outputs is inserted by XLA).
    The compiled program is the same prefill+scan as :func:`generate` —
    GSPMD propagates the shardings through it; greedy decode is therefore
    bit-identical to the single-device path (tested).
    """
    from distributed_ml_pytorch_tpu.parallel.tensor_parallel import (
        _check_divisibility,
        tp_param_specs,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    _check_divisibility(model, int(mesh.shape[model_axis]))
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 sampling needs an rng key")
    rng = rng if rng is not None else jax.random.key(0)
    b, p = prompt.shape
    total = p + max_new_tokens
    _check_max_len(model, total)  # same guard as generate()
    if max_new_tokens < 1:
        return prompt

    param_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), tp_param_specs(params, model_axis),
        is_leaf=lambda x: isinstance(x, P),
    )
    params = jax.device_put(params, param_shardings)

    def cache_spec(path, leaf):
        name = getattr(path[-1], "key", str(path[-1]))
        if name in ("cached_k", "cached_v"):  # (b, heads, cache, head_dim)
            return NamedSharding(mesh, P(data_axis, model_axis, None, None))
        return NamedSharding(mesh, P())  # cursor

    cache = init_cache(model, b, total)
    cache = jax.device_put(
        cache, jax.tree_util.tree_map_with_path(cache_spec, cache)
    )
    prompt = jax.device_put(prompt, NamedSharding(mesh, P(data_axis, None)))
    dec = _decode_model(model, total)
    return _generate_jit(
        dec, int(max_new_tokens), float(temperature), int(top_k), float(top_p),
        params, cache, prompt, rng
    )


@partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _generate_jit(dec, max_new_tokens, temperature, top_k, top_p,
                  params, cache, prompt, rng):
    b, p = prompt.shape

    # prefill: whole prompt in one pass; next token comes from the last logit
    positions = jnp.arange(p)[None, :]
    logits, mutated = dec.apply(
        {"params": params, "cache": cache}, prompt, positions, mutable=["cache"]
    )
    cache = mutated["cache"]

    def sample(logits, step_rng):
        return sample_tokens(
            logits, step_rng, temperature=temperature, top_k=top_k, top_p=top_p
        ).astype(prompt.dtype)

    first = sample(logits[:, -1], jax.random.fold_in(rng, 0))

    def step(carry, t):
        cache, tok = carry
        pos = jnp.full((b, 1), p, jnp.int32) + t
        logits, mutated = dec.apply(
            {"params": params, "cache": cache}, tok[:, None], pos, mutable=["cache"]
        )
        nxt = sample(logits[:, -1], jax.random.fold_in(rng, t + 1))
        return (mutated["cache"], nxt), tok

    (_, last), toks = jax.lax.scan(
        step, (cache, first), jnp.arange(max_new_tokens - 1)
    )
    generated = jnp.concatenate(
        [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1
    )  # [B, max_new_tokens]
    return jnp.concatenate([prompt, generated], axis=1)
