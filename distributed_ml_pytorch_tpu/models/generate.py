"""Autoregressive decoding for the Transformer LM family.

The reference is a CNN classifier framework with no text generation at all
(SURVEY.md §2, image models only) — this is a capability extension that
completes the LM story: train with ``parallel/seq_parallel.py`` (or tp/pp),
then sample from the trained params here.

TPU-native decode structure:

- **Prefill** runs the whole prompt through the model in ONE call, writing
  every layer's K/V into the cache (``models/transformer.MultiHeadAttention``
  with ``decode=True``) — the MXU-friendly bulk phase.
- **Generation** runs single-token steps under ``lax.scan`` with NO
  per-token Python dispatch and no growing shapes. Two compiled forms:
  the plain path (one scan, caches as carry, one-slot
  ``dynamic_update_slice`` appends) for short runs and edge shapes, and
  the ring-buffered BLOCKED path (``_generate_blocked_jit``) for runs of
  ``DECODE_BLOCK`` steps or more. The blocked path exists because the
  one-slot append lands in the TPU's tiled sublane dimension and XLA
  materializes full-cache copies inside the scan (profiled at GPT-2-small
  batch 32: ~10 × 18.9 MB copies per step; a pallas
  ``input_output_aliases`` append kernel also materialized copies on this
  runtime) — appends go to a small per-layer ring instead, merged into
  the big cache once per block, and the unrolled outer loop gives each
  block a static live-prefix cache read. Measured with the fused QKV
  projection: +53% decode throughput at batch 32 and 97% of the measured
  HBM streaming roofline at batch 8 (BASELINE.md #8).
- Sampling is temperature-controlled categorical (temperature 0 → greedy
  argmax) with optional top-k and/or nucleus (top-p) truncation
  (:func:`sample_tokens`), per-step rng folded from one key, fully
  deterministic given ``(params, prompt, rng)``.

Numerics contract: blocked and plain paths compute the same attention
mathematically and are bit-identical on CPU (tested). On the TPU's MXU the
blocked path's three-part score concat and the fused QKV matmul reorder
f32 accumulation in the low bits, so greedy tokens can diverge after a few
steps when a near-random model has logit near-ties — the standard fused-
kernel float-order caveat, quality-neutral on trained models.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def sample_tokens(
    logits: jnp.ndarray,
    rng: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """One sampling decision over ``[B, vocab]`` logits.

    ``temperature=0`` is greedy argmax (k/p ignored — argmax is already the
    1-token nucleus). Otherwise: optional top-k truncation (keep the k
    highest logits), then optional nucleus truncation (keep the smallest
    prefix of the sorted distribution whose probability mass reaches
    ``top_p``; the top token always survives), then categorical sampling at
    the given temperature. All static-shape ops (sort + masks), so the
    whole thing lives inside the scanned decode program. Tokens whose
    logit exactly ties the nucleus cut-off logit are kept (the mask maps
    back through a threshold compare), matching the usual top-p contract.
    """
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    vocab = logits.shape[-1]
    neg = jnp.asarray(jnp.finfo(logits.dtype).min, logits.dtype)
    top_k = min(int(top_k), vocab) if top_k else 0
    if top_k > 0 or top_p < 1.0:
        # ONE descending sort serves both filters: the k-th entry is the
        # top-k threshold, and masking the sorted tail past k-1 gives the
        # nucleus pass the post-top-k distribution without re-sorting
        sort_desc = jnp.sort(logits, axis=-1)[..., ::-1]
        if top_k > 0:
            kth = sort_desc[..., top_k - 1][..., None]
            logits = jnp.where(logits < kth, neg, logits)
            sort_desc = jnp.where(jnp.arange(vocab) >= top_k, neg, sort_desc)
        if top_p < 1.0:
            probs = jax.nn.softmax(sort_desc, axis=-1)
            # exclusive cumulative mass: a token is cut iff the mass BEFORE
            # it already reaches top_p — the argmax token can never be cut
            exceeded = (jnp.cumsum(probs, axis=-1) - probs) >= top_p
            exceeded = exceeded.at[..., 0].set(False)  # even at top_p = 0
            cut = jnp.where(exceeded, jnp.inf, sort_desc)
            thresh = jnp.min(cut, axis=-1, keepdims=True)
            logits = jnp.where(logits < thresh, neg, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def sample_tokens_dynamic(
    logits: jnp.ndarray,
    rngs: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Per-row sampling over ``[B, vocab]`` logits with PER-ROW params.

    The serving engine's heterogeneous-batch face of :func:`sample_tokens`:
    every argument after ``logits`` is a length-``B`` array (one rng key,
    temperature, top-k, top-p per row), all TRACED — one compiled program
    serves any mix of greedy and sampled requests. Row semantics match
    :func:`sample_tokens` exactly: for a single row, the token equals
    ``sample_tokens(logits[None], key, t, k, p)[0]`` bit-for-bit on CPU
    (tested), because the masking math mirrors it op-for-op and a
    categorical draw over ``[vocab]`` consumes the same random bits as one
    over ``[1, vocab]``. ``temperature <= 0`` rows are greedy argmax.
    """
    vocab = logits.shape[-1]
    neg = jnp.asarray(jnp.finfo(logits.dtype).min, logits.dtype)

    def one(lg, key, t, k, p):
        greedy = jnp.argmax(lg, axis=-1)
        scaled = lg / jnp.where(t > 0.0, t, 1.0).astype(lg.dtype)
        # ONE descending sort serves both filters (same as sample_tokens);
        # the filters gate on their own params so off rows pass through
        sort_desc = jnp.sort(scaled, axis=-1)[::-1]
        kk = jnp.clip(k, 0, vocab)
        kth = sort_desc[jnp.maximum(kk - 1, 0)]
        use_k = kk > 0
        scaled = jnp.where(use_k & (scaled < kth), neg, scaled)
        sort_desc = jnp.where(use_k & (jnp.arange(vocab) >= kk), neg, sort_desc)
        probs = jax.nn.softmax(sort_desc, axis=-1)
        exceeded = (jnp.cumsum(probs, axis=-1) - probs) >= p
        exceeded = exceeded.at[0].set(False)
        cut = jnp.where(exceeded, jnp.inf, sort_desc)
        thresh = jnp.min(cut, axis=-1)
        scaled = jnp.where((p < 1.0) & (scaled < thresh), neg, scaled)
        sampled = jax.random.categorical(key, scaled, axis=-1)
        return jnp.where(t > 0.0, sampled, greedy)

    return jax.vmap(one)(
        logits, rngs,
        jnp.asarray(temperature, jnp.float32),
        jnp.asarray(top_k, jnp.int32),
        jnp.asarray(top_p, jnp.float32),
    )


def _fuse_qkv_params(params, name: str = ""):
    """Rewrite a trained param tree into the ``fused_qkv`` module layout:
    every attention dict {q, k, v, o} becomes {qkv, o} with the three
    kernels concatenated on the output axis (``y[..., :d] == x @ W_q``
    etc., bit-compatible column blocks). Runs INSIDE the decode jit, so
    checkpoints and callers keep the unfused layout; the concat is
    loop-invariant and XLA hoists it out of the token scans.

    The rewrite is anchored on the attention module NAME ("attn", as
    ``TransformerBlock`` declares it) in addition to the {q,k,v,o} child
    keys, so an unrelated module that happens to have those child names is
    left alone — and the q/k/v kernels are checked 2-D and equal-shaped
    before concatenating (the MHA projections are all (d_model, d_model))."""
    if (
        isinstance(params, dict)
        and name == "attn"
        and {"q", "k", "v", "o"} <= set(params)
    ):
        kernels = [params[n]["kernel"] for n in ("q", "k", "v")]
        if not all(k.ndim == 2 and k.shape == kernels[0].shape for k in kernels):
            raise ValueError(
                "attn q/k/v kernels are not same-shaped 2-D: "
                f"{[k.shape for k in kernels]}")
        out = {n: v for n, v in params.items() if n not in ("q", "k", "v")}
        out["qkv"] = {"kernel": jnp.concatenate(kernels, axis=-1)}
        return out
    if isinstance(params, dict):
        return {n: _fuse_qkv_params(v, name=n) for n, v in params.items()}
    return params


def _decode_model(model, cache_size: int, decode_block: int = 0,
                  kv_quant: bool = False):
    kw = {}
    if decode_block and hasattr(model, "decode_block"):
        kw["decode_block"] = decode_block
        if hasattr(model, "fused_qkv"):
            kw["fused_qkv"] = True
        if kv_quant and hasattr(model, "kv_quant"):
            kw["kv_quant"] = True
    elif kv_quant:
        # never swallow the request: an int8 cache only exists under the
        # blocked path, and a caller sizing batch/context for the halved
        # footprint must not silently get the full-size exact cache
        raise ValueError(
            "kv_quant=True requires decode_block > 0 (int8 quantization "
            "happens at block merges; generate() enables both together)")
    return model.clone(decode=True, cache_size=cache_size, attn_fn=None, **kw)


#: ring size for blocked decode — measured sweet spot at batch 32 (merge
#: copies amortize to ~1 big-cache copy per 16 steps while the ring stays
#: small enough to copy cheaply inside the scan)
DECODE_BLOCK = 16

#: compile-size bound for the blocked path: its outer loop is UNROLLED (one
#: differently-shaped inner scan per block, which is what makes each
#: block's cache read a static live-prefix slice), so program size and
#: compile time grow linearly with the block count. Longer generations
#: fall back to the plain one-scan path — slower per token but O(1)
#: compile. 64 blocks = 1024 tokens at the default ring size.
MAX_UNROLLED_BLOCKS = 64


def split_cache(cache):
    """Split a decode cache pytree into (big, small): the per-layer big K/V
    caches vs everything else (rings, cursors, ring_base). The big part is
    closed over as a CONSTANT by the blocked scan's inner loop — carrying it
    would reintroduce the per-step full-cache copies the ring exists to
    avoid. Public: the serving slot pool (``serving/cache.py``) splits its
    stacked per-slot caches with the same name-based rule."""
    big, small = {}, {}
    for name, val in cache.items():
        if isinstance(val, dict):
            b, s = split_cache(val)
            if b:
                big[name] = b
            if s:
                small[name] = s
        elif name in ("cached_k", "cached_v", "scale_k", "scale_v"):
            big[name] = val
        else:
            small[name] = val
    return big, small


def join_cache(big, small):
    """Inverse of :func:`split_cache`: reassemble the full cache pytree."""
    out = dict(small)
    for name, val in big.items():
        if isinstance(val, dict):
            out[name] = join_cache(val, small.get(name, {}))
        else:
            out[name] = val
    return out


def _check_max_len(model, total: int) -> None:
    """RoPE rotates by position instead of indexing a table, so max_len does
    not bound its positions — the guard protects only learned embeddings."""
    max_len = getattr(model, "max_len", None)
    if (
        max_len is not None
        and total > max_len
        and getattr(model, "pos_encoding", "learned") != "rope"
    ):
        raise ValueError(
            f"prompt + max_new_tokens = {total} exceeds the model's max_len "
            f"{max_len} — position embeddings would go out of range"
        )


def init_cache(model, batch: int, cache_size: int, decode_block: int = 0,
               kv_quant: bool = False):
    """Allocate the per-layer K/V cache (zeros, cursor at 0) for ``batch``
    sequences of total length ``cache_size``.

    ``kv_quant=True`` caches carry a SINGLE-PREFILL CONTRACT: the first
    multi-token apply must happen at cursor 0 (a fresh cache). A second
    multi-token prefill into a non-empty quantized cache returns NaN
    outputs by design (``MultiHeadAttention._block_cached_attention``) —
    the quant prefill attends with its exact in-hand K/V and deliberately
    does not read earlier blocks back. :func:`generate` always satisfies
    this; direct module users chaining prefills must re-init the cache
    (or use the exact bf16 cache, which has no such restriction). The
    serving slot pool (``serving/cache.py``) also satisfies it under slot
    REUSE: every admission prefills a fresh zeroed lane cache and scatters
    it over the recycled slot, so the contract holds per occupancy, not
    just per allocation."""
    dec = _decode_model(model, cache_size, decode_block=decode_block,
                        kv_quant=kv_quant)
    variables = jax.eval_shape(
        lambda: dec.init(
            jax.random.key(0),
            jnp.zeros((batch, 1), jnp.int32),
            jnp.zeros((batch, 1), jnp.int32),
        )
    )
    return jax.tree.map(jnp.zeros_like, variables["cache"])


def uses_block_decode(model, prompt_len: int,
                      max_new_tokens: int) -> Tuple[bool, int]:
    """Whether :func:`generate` will take the ring-buffered block path for
    this shape, plus the padded cache allocation it would use. Public so
    callers that REQUIRE block-path behavior (``kv_quant`` only applies
    there) can check instead of trusting a silent fallback.

    The blocked path pads the step loop to a multiple of ``DECODE_BLOCK``;
    it runs when the generation is long enough to amortize a block, short
    enough to bound the unrolled compile, the padding fits the learned
    position table (RoPE is unbounded), and the prompt has more than one
    token — a one-token prompt's prefill would be indistinguishable from a
    single-token decode step inside ``_block_cached_attention`` (``s == 1``
    is the branch discriminator) and its K/V would be orphaned in the ring.
    """
    T = DECODE_BLOCK
    n_steps = max_new_tokens - 1
    n_blocks = -(-n_steps // T)
    padded_total = prompt_len + n_blocks * T
    blocked = (
        hasattr(model, "decode_block")
        and n_steps >= T
        and n_blocks <= MAX_UNROLLED_BLOCKS
        and prompt_len > 1
        and (getattr(model, "pos_encoding", "learned") == "rope"
             or padded_total <= getattr(model, "max_len", padded_total))
    )
    return blocked, padded_total


def generate(
    model,
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    top_k: int = 0,
    top_p: float = 1.0,
    kv_quant: bool = False,
) -> jnp.ndarray:
    """Sample ``max_new_tokens`` continuations of ``prompt`` ([B, P] int32).

    Returns ``[B, P + max_new_tokens]`` tokens. ``temperature=0`` is greedy;
    otherwise categorical sampling at the given temperature (``rng``
    required) with optional ``top_k`` / nucleus ``top_p`` truncation
    (:func:`sample_tokens`). Jit-compiled end-to-end: one prefill program +
    one scanned generation program, both cached across calls with the same
    shapes. ``kv_quant=True`` stores completed blocks' K/V as int8 with
    per-key scales (half the dominant decode HBM read; small quantization
    noise on cross-block attention only) — it applies only when the
    blocked path runs; shapes that fall back to the plain scan keep the
    exact full-size cache and a ``UserWarning`` is emitted (pre-check with
    :func:`uses_block_decode` to avoid the fallback). Quantized caches are
    single-prefill (see :func:`init_cache`); ``generate`` always satisfies
    that contract internally.
    """
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 sampling needs an rng key")
    rng = rng if rng is not None else jax.random.key(0)
    b, p = prompt.shape
    total = p + max_new_tokens
    _check_max_len(model, total)
    if max_new_tokens < 1:
        return prompt

    blocked, padded_total = uses_block_decode(model, p, max_new_tokens)
    if blocked:
        cache = init_cache(model, b, padded_total, decode_block=DECODE_BLOCK,
                           kv_quant=kv_quant)
        dec = _decode_model(model, padded_total, decode_block=DECODE_BLOCK,
                            kv_quant=kv_quant)
        return _generate_blocked_jit(
            dec, int(max_new_tokens), float(temperature), int(top_k),
            float(top_p), params, cache, prompt, rng
        )
    if kv_quant:
        # the plain scan keeps the exact full-size bf16 cache — more
        # accurate, but NOT the halved footprint the caller sized for, so
        # the fallback must be audible (callers can pre-check with
        # uses_block_decode())
        import warnings

        warnings.warn(
            "kv_quant=True requested but this shape falls back to the plain "
            "decode scan (int8 quantization only exists under the blocked "
            "path: needs prompt_len > 1 and "
            f"{DECODE_BLOCK} <= max_new_tokens - 1 <= "
            f"{DECODE_BLOCK * MAX_UNROLLED_BLOCKS}, within max_len) — using "
            "the exact FULL-SIZE bf16 cache; the halved-footprint capacity "
            "win does not apply",
            stacklevel=2,
        )
    # kv_quant needs the blocked structure (quantize-at-merge); the plain
    # scan keeps the exact full-size cache (warned above — more accurate,
    # but not the halved footprint the caller asked for)
    cache = init_cache(model, b, total)
    dec = _decode_model(model, total)
    return _generate_jit(
        dec, int(max_new_tokens), float(temperature), int(top_k), float(top_p),
        params, cache, prompt, rng
    )


def generate_tp(
    model,
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    mesh,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    data_axis: str = "data",
    model_axis: str = "model",
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """Tensor-parallel decode: ``generate`` semantics on a dp×tp mesh.

    Capability symmetry with the training-side TP
    (``parallel/tensor_parallel.py``): the same Megatron layout serves
    inference — params sharded by :func:`tp_param_specs` (q/k/v column-,
    o row-, lm_head vocab-sharded), batch over ``data_axis``, and the K/V
    cache sharded over *heads* on ``model_axis`` (heads follow the q/k/v
    column shards, so cache append + cached attention stay device-local;
    the per-block all-reduce on attention/MLP outputs is inserted by XLA).
    The compiled program is the same prefill+scan as :func:`generate` —
    GSPMD propagates the shardings through it; greedy decode is therefore
    bit-identical to the single-device path (tested).
    """
    from distributed_ml_pytorch_tpu.parallel.tensor_parallel import (
        _check_divisibility,
        tp_param_specs,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    _check_divisibility(model, int(mesh.shape[model_axis]))
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 sampling needs an rng key")
    rng = rng if rng is not None else jax.random.key(0)
    b, p = prompt.shape
    total = p + max_new_tokens
    _check_max_len(model, total)  # same guard as generate()
    if max_new_tokens < 1:
        return prompt

    param_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), tp_param_specs(params, model_axis),
        is_leaf=lambda x: isinstance(x, P),
    )
    params = jax.device_put(params, param_shardings)

    def cache_spec(path, leaf):
        name = getattr(path[-1], "key", str(path[-1]))
        if name in ("cached_k", "cached_v"):  # (b, heads, cache, head_dim)
            return NamedSharding(mesh, P(data_axis, model_axis, None, None))
        return NamedSharding(mesh, P())  # cursor

    cache = init_cache(model, b, total)
    cache = jax.device_put(
        cache, jax.tree_util.tree_map_with_path(cache_spec, cache)
    )
    prompt = jax.device_put(prompt, NamedSharding(mesh, P(data_axis, None)))
    dec = _decode_model(model, total)
    return _generate_jit(
        dec, int(max_new_tokens), float(temperature), int(top_k), float(top_p),
        params, cache, prompt, rng
    )


@partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _generate_jit(dec, max_new_tokens, temperature, top_k, top_p,
                  params, cache, prompt, rng):
    b, p = prompt.shape

    # prefill: whole prompt in one pass; next token comes from the last logit
    positions = jnp.arange(p)[None, :]
    logits, mutated = dec.apply(
        {"params": params, "cache": cache}, prompt, positions, mutable=["cache"]
    )
    cache = mutated["cache"]

    def sample(logits, step_rng):
        return sample_tokens(
            logits, step_rng, temperature=temperature, top_k=top_k, top_p=top_p
        ).astype(prompt.dtype)

    first = sample(logits[:, -1], jax.random.fold_in(rng, 0))

    def step(carry, t):
        cache, tok = carry
        pos = jnp.full((b, 1), p, jnp.int32) + t
        logits, mutated = dec.apply(
            {"params": params, "cache": cache}, tok[:, None], pos, mutable=["cache"]
        )
        nxt = sample(logits[:, -1], jax.random.fold_in(rng, t + 1))
        return (mutated["cache"], nxt), tok

    (_, last), toks = jax.lax.scan(
        step, (cache, first), jnp.arange(max_new_tokens - 1)
    )
    generated = jnp.concatenate(
        [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1
    )  # [B, max_new_tokens]
    return jnp.concatenate([prompt, generated], axis=1)


def _tree_slice_big(big, live):
    """Static live-prefix view of every big cache: (b, h, C, d) -> (b, h,
    live, d), and (b, h, C) scale arrays -> (b, h, live). A static slice
    fuses into the attention read, so each block reads exactly the K/V
    written so far instead of the full padded cache."""
    return jax.tree.map(
        lambda a: a[:, :, :live, :] if a.ndim == 4 else a[:, :, :live], big)


def merge_ring_caches(big, small, live):
    """Merge every layer's ring into its FULL big cache at offset ``live``;
    returns the updated big pytree (rings themselves are reused — the next
    block's strict ring mask hides stale slots). Quantized caches
    (``kv_quant``: int8 values + scale arrays present) quantize the exact
    bf16 ring here, once per block. ``live`` may be a static int (the
    blocked generate path — the static offset fuses) or a traced scalar
    (the serving slot pool vmaps this over slots with per-slot offsets)."""
    if "cached_k" in big:
        from distributed_ml_pytorch_tpu.models.transformer import quantize_kv

        out = dict(big)
        rk, rv = small["ring_k"], small["ring_v"]
        if "scale_k" in big:
            rk, ks = quantize_kv(rk)
            rv, vs = quantize_kv(rv)
            out["scale_k"] = jax.lax.dynamic_update_slice(
                big["scale_k"], ks, (0, 0, live))
            out["scale_v"] = jax.lax.dynamic_update_slice(
                big["scale_v"], vs, (0, 0, live))
        out["cached_k"] = jax.lax.dynamic_update_slice(
            big["cached_k"], rk, (0, 0, live, 0))
        out["cached_v"] = jax.lax.dynamic_update_slice(
            big["cached_v"], rv, (0, 0, live, 0))
        return out
    return {
        name: (merge_ring_caches(val, small.get(name, {}), live)
               if isinstance(val, dict) else val)
        for name, val in big.items()
    }


def reset_ring_state(small, live):
    """Per-block small-state reset: cursor and ring_base both sit at the
    block's start position ``live`` (rings keep stale data — masked out).
    ``live`` may be static or traced, like :func:`merge_ring_caches`."""
    out = {}
    for name, val in small.items():
        if isinstance(val, dict):
            out[name] = reset_ring_state(val, live)
        elif name in ("cursor", "ring_base"):
            out[name] = jnp.asarray(live, jnp.int32)
        else:
            out[name] = val
    return out


@partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _generate_blocked_jit(dec, max_new_tokens, temperature, top_k, top_p,
                          params, cache, prompt, rng):
    """Ring-buffered decode: an UNROLLED outer loop over DECODE_BLOCK-token
    blocks, an inner scan over single-token steps. Three structural wins
    over the naive one-token scan (measured at GPT-2-small batch 32,
    device-true):

    - single-token steps write a small per-layer ring instead of the big
      cache, so the scan carries no big-cache copies (the naive scan paid
      ~10 full 18.9 MB copies per step — see ``decode_block`` in
      models/transformer.py);
    - the big caches cross each inner scan as closed-over constants and are
      merged once per block with a static-offset update;
    - because the outer loop is unrolled, each block's live cache length is
      STATIC: the block's attention reads a fused live-prefix slice
      (b, h, p + blk*T, d) instead of the full padded cache — the average
      read drops from the allocation size to the true live size.

    The step loop is padded to a whole number of blocks; padded steps
    sample garbage the caller never sees (their K/V lands after every real
    token's, so no real attention read touches it). Net effect at batch 32:
    2.43 ms/step -> ~1.26 ms/step with the fused QKV projection (see
    BASELINE.md #8)."""
    T = dec.decode_block
    b, p = prompt.shape
    n_steps = max_new_tokens - 1
    n_blocks = -(-n_steps // T)
    if getattr(dec, "fused_qkv", False):
        params = _fuse_qkv_params(params)

    positions = jnp.arange(p)[None, :]
    logits, mutated = dec.apply(
        {"params": params, "cache": cache}, prompt, positions, mutable=["cache"]
    )
    big, small = split_cache(mutated["cache"])

    def sample(logits, step_rng):
        return sample_tokens(
            logits, step_rng, temperature=temperature, top_k=top_k, top_p=top_p
        ).astype(prompt.dtype)

    tok = sample(logits[:, -1], jax.random.fold_in(rng, 0))
    all_toks = []
    for blk in range(n_blocks):
        live = p + blk * T
        dec_blk = dec.clone(cache_size=live)
        big_view = _tree_slice_big(big, live)
        small = reset_ring_state(small, live)

        def inner(carry, t, dec_blk=dec_blk, big_view=big_view, blk=blk):
            small, tok = carry
            step_idx = blk * T + t
            pos = jnp.full((b, 1), p, jnp.int32) + step_idx
            logits, mut = dec_blk.apply(
                {"params": params, "cache": join_cache(big_view, small)},
                tok[:, None], pos, mutable=["cache"],
            )
            _, small = split_cache(mut["cache"])
            nxt = sample(logits[:, -1], jax.random.fold_in(rng, step_idx + 1))
            return (small, nxt), tok

        (small, tok), toks = jax.lax.scan(inner, (small, tok), jnp.arange(T))
        big = merge_ring_caches(big, small, live)
        all_toks.append(jnp.moveaxis(toks, 0, 1))  # [B, T] inputs of each step

    generated = jnp.concatenate(all_toks + [tok[:, None]], axis=1)
    return jnp.concatenate([prompt, generated[:, :max_new_tokens]], axis=1)
