"""Decoder-only Transformer LM — the long-context model family.

The reference's model zoo is two CIFAR CNNs (``example/models.py:5-49``); the
TPU framework adds a Transformer because long-context training is first-class
here (SURVEY.md §5.7 records the reference owes nothing — this is a
capability extension, not parity). The design is shaped by how it trains:

- **Attention is injectable.** ``attn_fn(q, k, v)`` defaults to the
  blockwise online-softmax kernel (``ops/attention.py``) over the local
  sequence; under sequence parallelism the trainer passes
  ``parallel/ring.ring_attention`` bound to the mesh axis, and the same
  module then computes exact full-sequence attention over sharded chunks.
  Nothing else in the model knows the sequence is distributed.
- **Positions are an input**, not ``arange(seq)``: a device holding chunk
  ``i`` of a sharded sequence feeds its global positions, so learned
  position embeddings are correct under sharding.
- Pre-LN blocks, GELU MLP, bf16-friendly (dtype threads through every
  dense/embed); weights stay f32 (master copies), activations cast.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from distributed_ml_pytorch_tpu.ops.attention import (
    blockwise_attention,
    finalize_attention,
)


def default_attn_fn(q, k, v):
    """Causal attention over the local (= full, when unsharded) sequence."""
    acc, _m, l = blockwise_attention(q, k, v, causal=True)
    return finalize_attention(acc, l).astype(q.dtype)


class MultiHeadAttention(nn.Module):
    d_model: int
    n_heads: int
    dtype: jnp.dtype = jnp.float32
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        b, s, _ = x.shape
        head_dim = self.d_model // self.n_heads
        proj = lambda name: nn.Dense(self.d_model, use_bias=False, dtype=self.dtype, name=name)
        split = lambda t: t.reshape(b, s, self.n_heads, head_dim).transpose(0, 2, 1, 3)
        q, k, v = (split(proj(n)(x)) for n in ("q", "k", "v"))
        attn = self.attn_fn or default_attn_fn
        out = attn(q, k, v)  # (b, h, s, hd)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, self.d_model)
        return nn.Dense(self.d_model, use_bias=False, dtype=self.dtype, name="o")(out)


class Block(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    dtype: jnp.dtype = jnp.float32
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + MultiHeadAttention(
            self.d_model, self.n_heads, self.dtype, self.attn_fn, name="attn"
        )(h)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.d_ff, dtype=self.dtype)(h)
        h = nn.gelu(h)
        x = x + nn.Dense(self.d_model, dtype=self.dtype)(h)
        return x


class TransformerLM(nn.Module):
    """Causal LM over token ids; ``positions`` carries global positions so the
    sequence axis can be sharded (each device passes its chunk's offsets)."""

    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_len: int = 131072
    dtype: jnp.dtype = jnp.float32
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, tokens, positions=None):
        if positions is None:
            positions = jnp.arange(tokens.shape[-1])[None, :]
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype, name="tok_embed")(tokens)
        x = x + nn.Embed(self.max_len, self.d_model, dtype=self.dtype, name="pos_embed")(positions)
        for i in range(self.n_layers):
            x = Block(
                self.d_model, self.n_heads, self.d_ff, self.dtype, self.attn_fn,
                name=f"block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.vocab_size, use_bias=False, dtype=self.dtype, name="lm_head")(x)
