"""Decoder-only Transformer LM — the long-context model family.

The reference's model zoo is two CIFAR CNNs (``example/models.py:5-49``); the
TPU framework adds a Transformer because long-context training is first-class
here (SURVEY.md §5.7 records the reference owes nothing — this is a
capability extension, not parity). The design is shaped by how it trains:

- **Attention is injectable.** ``attn_fn(q, k, v)`` defaults to the
  blockwise online-softmax kernel (``ops/attention.py``) over the local
  sequence; under sequence parallelism the trainer passes
  ``parallel/ring.ring_attention`` bound to the mesh axis, and the same
  module then computes exact full-sequence attention over sharded chunks.
  Nothing else in the model knows the sequence is distributed.
- **Positions are an input**, not ``arange(seq)``: a device holding chunk
  ``i`` of a sharded sequence feeds its global positions, so position
  encoding is correct under sharding — for the learned table AND for RoPE
  (``pos_encoding="rope"``), which rotates q/k by global position inside
  attention before any ring/Ulysses exchange.
- Pre-LN blocks, GELU MLP, bf16-friendly (dtype threads through every
  dense/embed); weights stay f32 (master copies), activations cast.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from distributed_ml_pytorch_tpu.ops.attention import auto_attention


def default_attn_fn(q, k, v):
    """Causal attention over the local (= full, when unsharded) sequence:
    the Pallas flash kernel on TPU when the shape fits its blocking (6.3×
    the scan forward and at splash-kernel parity incl. the fused backward,
    device-true — ops/attention.py), the differentiable blockwise scan
    everywhere else."""
    return auto_attention(q, k, v, causal=True)


def apply_rope(x: jax.Array, positions: jax.Array, base: float = 10000.0) -> jax.Array:
    """Rotary position embedding for one projection.

    ``x`` is ``(batch, heads, seq, head_dim)``; ``positions`` carries the
    GLOBAL position of every token ``(batch, seq)`` or ``(1, seq)`` — the
    same positions-are-an-input design that makes learned embeddings
    sharding-transparent makes RoPE exact under sequence sharding: each
    device rotates its local chunk by its global offsets BEFORE ring/Ulysses
    attention exchanges anything, and a decode step rotates by the cache
    cursor's absolute position. Rotation happens in f32 (angles lose
    precision fast in bf16); the result is cast back to ``x.dtype``.
    """
    half = x.shape[-1] // 2
    if 2 * half != x.shape[-1]:
        raise ValueError(f"rope needs an even head_dim, got {x.shape[-1]}")
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)  # (half,)
    angles = positions[..., None].astype(jnp.float32) * freqs  # (b, s, half)
    cos = jnp.cos(angles)[:, None]  # (b, 1, s, half) — broadcast over heads
    sin = jnp.sin(angles)[:, None]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-key symmetric int8 quantization of a K or V block ``(..., d)``:
    returns ``(int8 values, f32 scale (...,))`` with ``x ≈ int8 * scale``.
    Absmax over the head dim — each cached position/head keeps its own
    scale, so one outlier key cannot crush every other key's resolution."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(absmax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


class MultiHeadAttention(nn.Module):
    """Causal MHA; with ``decode=True`` it maintains a K/V cache (flax
    ``"cache"`` collection) for incremental autoregressive decoding: each call
    appends the new keys/values at the cache cursor and attends the (short)
    query block over everything written so far."""

    d_model: int
    n_heads: int
    dtype: jnp.dtype = jnp.float32
    attn_fn: Optional[Callable] = None
    decode: bool = False
    cache_size: int = 0
    rope: bool = False
    #: >0 enables ring-buffered block decode: single-token steps write a
    #: small (b, h, decode_block, d) ring instead of the big cache, and the
    #: caller merges full rings into the big cache every decode_block steps
    #: (models/generate.py's blocked scan does this). Why: a one-slot
    #: dynamic_update_slice on the big cache lands in the TPU's tiled
    #: sublane dim and XLA materializes a full-cache copy per layer per
    #: step inside the decode scan (measured 83-100 us per 18.9 MB cache at
    #: batch 32 vs 46 us for BOTH attention reads at the HBM roofline);
    #: buffering appends in a ring the scan can copy cheaply and merging
    #: once per block amortizes the big-cache write to ~1 copy / T steps.
    decode_block: int = 0
    #: store the big decode cache as int8 with per-(batch, head, position)
    #: f32 scales (``quantize_kv``) — HALVES THE CACHE'S HBM FOOTPRINT
    #: (2x the decode batch or context per chip). Rings and the in-flight
    #: block stay exact (self.dtype); quantization happens once per block
    #: at merge time. Requires decode_block > 0. Throughput note (measured,
    #: GPT-2-small batch 32): isolated int8 cache reads run ~0.6x the bf16
    #: time, but inside the full decode program the fused
    #: convert+dequantize read drops to ~half the bf16 GB/s — bytes halve,
    #: read TIME stays ~flat, so this is a capacity knob on this runtime,
    #: not a speed knob (20.2k tok/s bf16 vs 18.8k int8, fused-QKV path).
    kv_quant: bool = False
    #: decode-path knob: compute q/k/v with ONE (d_model, 3*d_model) matmul
    #: instead of three — one weight DMA per layer per step instead of
    #: three, targeting the measured weight-stall share of the decode step.
    #: Param tree changes shape (attn/qkv instead of attn/{q,k,v});
    #: models/generate.py fuses trained q/k/v kernels on the fly
    #: (_fuse_qkv_params), so checkpoints stay in the unfused layout.
    fused_qkv: bool = False

    @nn.compact
    def __call__(self, x, positions=None):
        b, s, _ = x.shape
        head_dim = self.d_model // self.n_heads
        proj = lambda name: nn.Dense(self.d_model, use_bias=False, dtype=self.dtype, name=name)
        split = lambda t: t.reshape(b, s, self.n_heads, head_dim).transpose(0, 2, 1, 3)
        if self.fused_qkv:
            qkv = nn.Dense(3 * self.d_model, use_bias=False, dtype=self.dtype,
                           name="qkv")(x)
            q, k, v = (split(qkv[..., i * self.d_model:(i + 1) * self.d_model])
                       for i in range(3))
        else:
            q, k, v = (split(proj(n)(x)) for n in ("q", "k", "v"))
        if self.rope:
            if positions is None:
                raise ValueError("rope=True needs the tokens' global positions")
            q = apply_rope(q, positions)
            k = apply_rope(k, positions)  # cached k (decode) is stored rotated
        if self.decode:
            if self.attn_fn is not None:
                raise ValueError(
                    "decode=True uses cached dense attention and cannot honor "
                    "an injected attn_fn — clone the model with attn_fn=None "
                    "for decoding (models/generate.py does this)"
                )
            out = self._cached_attention(q, k, v, b, s, head_dim)
        else:
            attn = self.attn_fn or default_attn_fn
            out = attn(q, k, v)  # (b, h, s, hd)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, self.d_model)
        return nn.Dense(self.d_model, use_bias=False, dtype=self.dtype, name="o")(out)

    def _cached_attention(self, q, k, v, b, s, head_dim):
        if self.cache_size < 1:
            raise ValueError("decode=True needs cache_size > 0")
        if self.kv_quant and self.decode_block <= 0:
            raise ValueError(
                "kv_quant=True requires decode_block > 0 — the int8 cache "
                "is quantized at block-merge time (models/generate.py "
                "enables both together)")
        # cache lives in the model's activation dtype (half the HBM under
        # bf16), or int8 + per-key scales under kv_quant; scores/softmax
        # compute in f32 for stability
        store_dt = jnp.int8 if self.kv_quant else self.dtype
        shape = (b, self.n_heads, self.cache_size, head_dim)
        cache_k = self.variable("cache", "cached_k", jnp.zeros, shape, store_dt)
        cache_v = self.variable("cache", "cached_v", jnp.zeros, shape, store_dt)
        cursor = self.variable("cache", "cursor", lambda: jnp.zeros((), jnp.int32))
        scale_k = scale_v = None
        if self.kv_quant:
            sshape = (b, self.n_heads, self.cache_size)
            scale_k = self.variable("cache", "scale_k", jnp.zeros, sshape, jnp.float32)
            scale_v = self.variable("cache", "scale_v", jnp.zeros, sshape, jnp.float32)
        idx = cursor.value
        if self.decode_block > 0:
            return self._block_cached_attention(
                q, k, v, b, s, head_dim, cache_k, cache_v, cursor,
                scale_k, scale_v)
        ck = jax.lax.dynamic_update_slice(cache_k.value, k.astype(self.dtype), (0, 0, idx, 0))
        cv = jax.lax.dynamic_update_slice(cache_v.value, v.astype(self.dtype), (0, 0, idx, 0))
        cache_k.value, cache_v.value, cursor.value = ck, cv, idx + s
        # Scores accumulate in f32 ON THE MXU (preferred_element_type) with
        # the cache read at its stored bf16 — an ``astype(f32)`` here would
        # materialize a full f32 copy of the cache EVERY step per layer
        # (measured: the cast traffic alone was ~56 MB/layer/step at batch
        # 32, dominating the decode step). Same for the PV einsum: probs
        # drop to the cache dtype so the MXU reads cv directly.
        scores = jnp.einsum(
            "bhsd,bhcd->bhsc", q, ck, preferred_element_type=jnp.float32
        )
        scores = scores / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
        # causal over absolute positions: query i (at idx+i) sees keys ≤ idx+i
        key_pos = jnp.arange(self.cache_size)
        q_pos = idx + jnp.arange(s)
        mask = key_pos[None, :] <= q_pos[:, None]  # (s, cache)
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum(
            "bhsc,bhcd->bhsd", probs.astype(self.dtype), cv,
            preferred_element_type=jnp.float32,
        ).astype(q.dtype)

    def _block_cached_attention(self, q, k, v, b, s, head_dim,
                                cache_k, cache_v, cursor,
                                scale_k=None, scale_v=None):
        """Ring-buffered decode (see ``decode_block``): single-token steps
        never write the big cache. They attend over three parts — the big
        cache masked to positions before ``ring_base``, the ring masked to
        slots written so far this block, and the fresh token — and append
        K/V to the ring. Multi-token (prefill) calls bulk-write the big
        cache and anchor ``ring_base`` at the end of the prompt; the
        CALLER must merge the ring into the big cache at
        ``ring_base`` and advance ``ring_base`` by ``decode_block`` every
        ``decode_block`` single-token steps (``models/generate.py``).

        Under ``kv_quant`` the big cache holds int8 + per-key f32 scales:
        K scales fold into the scores AFTER the int8→dtype einsum, V scales
        fold into the attention weights BEFORE theirs — both reads stream
        the int8 bytes. Prefill attention then uses the in-hand exact K/V
        (not a read-back of its own quantization), so prompt logits are
        exact and only cross-block reads see quantization noise."""
        T = self.decode_block
        quant = self.kv_quant
        ring_shape = (b, self.n_heads, T, head_dim)
        ring_k = self.variable("cache", "ring_k", jnp.zeros, ring_shape, self.dtype)
        ring_v = self.variable("cache", "ring_v", jnp.zeros, ring_shape, self.dtype)
        ring_base = self.variable(
            "cache", "ring_base", lambda: jnp.zeros((), jnp.int32))
        idx = cursor.value
        k = k.astype(self.dtype)
        v = v.astype(self.dtype)
        scale = jnp.sqrt(jnp.asarray(head_dim, jnp.float32))

        def big_k_scores(qq):
            """(b, h, s, C) scores against the big cache, dequantized."""
            sc = jnp.einsum("bhsd,bhcd->bhsc", qq,
                            cache_k.value.astype(self.dtype),
                            preferred_element_type=jnp.float32)
            if quant:
                sc = sc * scale_k.value[:, :, None, :]
            return sc

        def big_v_apply(weights):
            """(b, h, s, d) output from big-cache V under f32 weights."""
            if quant:
                weights = weights * scale_v.value[:, :, None, :]
            return jnp.einsum("bhsc,bhcd->bhsd", weights.astype(self.dtype),
                              cache_v.value.astype(self.dtype),
                              preferred_element_type=jnp.float32)

        if s != 1:  # prefill: bulk write straight to the big cache
            if quant:
                k8, ks = quantize_kv(k)
                v8, vs = quantize_kv(v)
                cache_k.value = jax.lax.dynamic_update_slice(
                    cache_k.value, k8, (0, 0, idx, 0))
                cache_v.value = jax.lax.dynamic_update_slice(
                    cache_v.value, v8, (0, 0, idx, 0))
                scale_k.value = jax.lax.dynamic_update_slice(
                    scale_k.value, ks, (0, 0, idx))
                scale_v.value = jax.lax.dynamic_update_slice(
                    scale_v.value, vs, (0, 0, idx))
            else:
                cache_k.value = jax.lax.dynamic_update_slice(
                    cache_k.value, k, (0, 0, idx, 0))
                cache_v.value = jax.lax.dynamic_update_slice(
                    cache_v.value, v, (0, 0, idx, 0))
            cursor.value = idx + s
            ring_base.value = idx + s
            if not quant:
                # attention over what's now in the big cache — identical
                # math to the unblocked path's prefill
                scores = big_k_scores(q) / scale
                key_pos = jnp.arange(self.cache_size)
                q_pos = idx + jnp.arange(s)
                mask = key_pos[None, :] <= q_pos[:, None]
                scores = jnp.where(mask[None, None], scores, -jnp.inf)
                probs = jax.nn.softmax(scores, axis=-1)
                return big_v_apply(probs).astype(q.dtype)
            # quant prefill: attend with the exact in-hand K/V — reading
            # back the just-written range would see its own quantization
            # noise. SINGLE-PREFILL CONTRACT: the cache must be empty
            # (cursor 0) — a big-cache read for an earlier prefill's keys
            # would burn two full-cache einsums that generate() (the only
            # in-tree caller, always cursor 0) never needs; misuse is
            # poisoned with NaN instead of silently dropping the past
            s_loc = jnp.einsum("bhsd,bhtd->bhst", q, k,
                               preferred_element_type=jnp.float32)
            causal = jnp.arange(s)[None, :] <= jnp.arange(s)[:, None]  # (s_q, s_k)
            s_loc = jnp.where(causal[None, None], s_loc, -jnp.inf)
            probs = jax.nn.softmax(s_loc / scale, axis=-1)
            out = jnp.einsum(
                "bhst,bhtd->bhsd", probs.astype(self.dtype), v,
                preferred_element_type=jnp.float32)
            out = jnp.where(idx == 0, out, jnp.nan)
            return out.astype(q.dtype)

        t = idx - ring_base.value  # slot in the current block, 0..T-1
        # part 1: completed blocks, read from the big cache (strict mask —
        # positions >= ring_base live in the ring, big-cache slots there
        # are stale)
        s_past = jnp.where(
            (jnp.arange(self.cache_size) < ring_base.value)[None, None, None, :],
            big_k_scores(q), -jnp.inf)
        # part 2: this block's earlier tokens, read from the ring
        s_ring = jnp.einsum(
            "bhsd,bhtd->bhst", q, ring_k.value,
            preferred_element_type=jnp.float32)
        s_ring = jnp.where(
            (jnp.arange(T) < t)[None, None, None, :], s_ring, -jnp.inf)
        # part 3: the fresh token attending to itself
        s_self = jnp.einsum(
            "bhsd,bhsd->bhs", q, k, preferred_element_type=jnp.float32)
        scores = jnp.concatenate(
            [s_past, s_ring, s_self[..., None]], axis=-1) / scale
        probs = jax.nn.softmax(scores, axis=-1)
        p_dt = probs.astype(self.dtype)
        out = (
            big_v_apply(probs[..., : self.cache_size])
            + jnp.einsum("bhst,bhtd->bhsd",
                         p_dt[..., self.cache_size: self.cache_size + T],
                         ring_v.value, preferred_element_type=jnp.float32)
            + probs[..., self.cache_size + T:].astype(jnp.float32) * v
        )
        ring_k.value = jax.lax.dynamic_update_slice(ring_k.value, k, (0, 0, t, 0))
        ring_v.value = jax.lax.dynamic_update_slice(ring_v.value, v, (0, 0, t, 0))
        cursor.value = idx + 1
        return out.astype(q.dtype)


class Block(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    dtype: jnp.dtype = jnp.float32
    attn_fn: Optional[Callable] = None
    decode: bool = False
    cache_size: int = 0
    rope: bool = False
    decode_block: int = 0
    kv_quant: bool = False
    fused_qkv: bool = False

    @nn.compact
    def __call__(self, x, positions=None):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + MultiHeadAttention(
            self.d_model, self.n_heads, self.dtype, self.attn_fn,
            decode=self.decode, cache_size=self.cache_size, rope=self.rope,
            decode_block=self.decode_block, kv_quant=self.kv_quant,
            fused_qkv=self.fused_qkv, name="attn",
        )(h, positions)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.d_ff, dtype=self.dtype)(h)
        h = nn.gelu(h)
        x = x + nn.Dense(self.d_model, dtype=self.dtype)(h)
        return x


class TransformerLM(nn.Module):
    """Causal LM over token ids; ``positions`` carries global positions so the
    sequence axis can be sharded (each device passes its chunk's offsets)."""

    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_len: int = 131072
    dtype: jnp.dtype = jnp.float32
    attn_fn: Optional[Callable] = None
    decode: bool = False
    cache_size: int = 0
    decode_block: int = 0
    kv_quant: bool = False
    fused_qkv: bool = False
    remat: bool = False
    pos_encoding: str = "learned"  # "learned" (table) | "rope" (rotary in-attn)
    #: head=False returns the post-LayerNorm hidden states instead of
    #: logits — the entry point for sequence-chunked losses that must not
    #: materialize the full (batch, seq, vocab) logits tensor at long
    #: context (training/trainer.chunked_lm_loss); the lm_head params stay
    #: in the tree (flax ignores unused subtrees) and are applied by the
    #: chunked loss itself
    head: bool = True

    @nn.compact
    def __call__(self, tokens, positions=None):
        if self.pos_encoding not in ("learned", "rope"):
            raise ValueError(f"unknown pos_encoding {self.pos_encoding!r}")
        use_rope = self.pos_encoding == "rope"
        if positions is None:
            positions = jnp.arange(tokens.shape[-1])[None, :]
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype, name="tok_embed")(tokens)
        if not use_rope:
            x = x + nn.Embed(self.max_len, self.d_model, dtype=self.dtype, name="pos_embed")(positions)
        # remat: recompute each block's intra-block intermediates (attention
        # scores, d_ff tensors) in the backward pass instead of keeping them
        # in HBM; only the n_layers block-boundary residuals stay resident —
        # the standard long-context trade of FLOPs for HBM (jax.checkpoint
        # per block)
        block_cls = nn.remat(Block) if self.remat and not self.decode else Block
        for i in range(self.n_layers):
            x = block_cls(
                self.d_model, self.n_heads, self.d_ff, self.dtype, self.attn_fn,
                decode=self.decode, cache_size=self.cache_size, rope=use_rope,
                decode_block=self.decode_block, kv_quant=self.kv_quant,
                fused_qkv=self.fused_qkv, name=f"block_{i}",
            )(x, positions)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        if not self.head:
            return x
        return nn.Dense(self.vocab_size, use_bias=False, dtype=self.dtype, name="lm_head")(x)
