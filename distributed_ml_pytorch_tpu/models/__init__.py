from distributed_ml_pytorch_tpu.models.cnn import LeNet, AlexNet, get_model
from distributed_ml_pytorch_tpu.models.resnet import ResNet, get_resnet
from distributed_ml_pytorch_tpu.models.transformer import TransformerLM
from distributed_ml_pytorch_tpu.models.generate import generate, generate_tp

__all__ = [
    "LeNet", "AlexNet", "ResNet", "TransformerLM", "get_model", "get_resnet",
    "generate", "generate_tp",
]
