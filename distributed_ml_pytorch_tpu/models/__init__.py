from distributed_ml_pytorch_tpu.models.cnn import LeNet, AlexNet, get_model

__all__ = ["LeNet", "AlexNet", "get_model"]
