from distributed_ml_pytorch_tpu.models.cnn import LeNet, AlexNet, get_model
from distributed_ml_pytorch_tpu.models.resnet import ResNet, get_resnet

__all__ = ["LeNet", "AlexNet", "ResNet", "get_model", "get_resnet"]
