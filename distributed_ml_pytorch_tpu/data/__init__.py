from distributed_ml_pytorch_tpu.data.cifar10 import (
    CIFAR10_CLASSES,
    download_cifar10,
    get_dataset,
    load_cifar10,
    synthetic_cifar10,
    iterate_batches,
    prefetch_to_device,
    shard_for_process,
)

__all__ = [
    "CIFAR10_CLASSES",
    "download_cifar10",
    "get_dataset",
    "load_cifar10",
    "synthetic_cifar10",
    "iterate_batches",
    "prefetch_to_device",
    "shard_for_process",
]
