"""C6: CIFAR-10 input pipeline (parity with reference ``example/main.py:23-29,35-38``).

The reference uses torchvision to download CIFAR-10 to ``./data`` and applies
``Normalize((0.5,0.5,0.5), (0.5,0.5,0.5))``. This module:

- loads the standard ``cifar-10-batches-py`` pickle layout from disk when
  present (same ``./data`` root convention);
- otherwise generates a **deterministic synthetic CIFAR-10 stand-in** —
  class-conditional structured images — so training/eval/benchmarks run in
  air-gapped environments (this build environment has no network egress).
  The synthetic set is learnable (distinct per-class statistics), letting
  loss-decrease and accuracy-improvement tests be meaningful;
- applies the same (x/255 - 0.5)/0.5 normalization to [-1, 1];
- provides a batching iterator (shuffle-per-epoch like the reference's
  ``DataLoader(shuffle=True)``) and per-process sharding for multi-host
  pods (each controller feeds its addressable devices — the TPU analog of
  one DataLoader per worker rank).

Layout is NHWC (TPU-native), not the reference's NCHW.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import shutil
import tarfile
from typing import Iterator, Tuple

import numpy as np

CIFAR10_CLASSES = (
    "plane", "car", "bird", "cat", "deer", "dog", "frog", "horse", "ship", "truck",
)  # reference ``example/main.py:112``

_BATCHES_DIR = "cifar-10-batches-py"
_TARBALL = "cifar-10-python.tar.gz"

# canonical distribution + its published md5 (the same pair torchvision's
# CIFAR10(download=True) verifies against — reference ``example/main.py:24``)
CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
CIFAR10_MD5 = "c58f30108f718f92721af3b95e74349a"


def _file_md5(path: str) -> str:
    digest = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            digest.update(chunk)
    return digest.hexdigest()


def download_cifar10(root: str, url: str | None = None,
                     md5: str | None = None,
                     timeout: float = 30.0) -> str:
    """Guarded CIFAR-10 acquisition (reference ``example/main.py:24``
    ``download=True``): fetch the tarball to ``root``, verify its md5,
    install atomically (.part → rename), extract, and return the batches
    directory. Raises on network failure or checksum mismatch — callers
    decide whether the synthetic stand-in is an acceptable fallback.

    ``url`` may be any scheme urllib supports; tests exercise the full
    verify/extract path with a fabricated archive over ``file://``.
    """
    import urllib.request

    # resolved at call time (not def time) so tests/deployments can point
    # the module-level URL/MD5 at a mirror
    url = CIFAR10_URL if url is None else url
    md5 = CIFAR10_MD5 if md5 is None else md5

    os.makedirs(root, exist_ok=True)
    dest = os.path.join(root, _TARBALL)
    if os.path.isfile(dest) and md5 and _file_md5(dest) != md5:
        # a corrupt/torn tarball left by earlier tooling must fail HERE as
        # a checksum mismatch, not later as an opaque extract error —
        # remove it and re-download (ADVICE r2). Racing launcher ranks may
        # both see the mismatch; the loser's remove finds nothing (fine),
        # and at worst a concurrently-installed GOOD tarball is removed and
        # benignly re-fetched by the verified path below
        import contextlib

        with contextlib.suppress(FileNotFoundError):
            os.remove(dest)
    if not os.path.isfile(dest):
        # per-process .part name: N launcher ranks may race this download
        # (launch_world spawns workers that all call get_dataset); each
        # fetches privately and the os.replace installs atomically —
        # last-finisher wins with identical, verified bytes
        part = f"{dest}.{os.getpid()}.part"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp, \
                    open(part, "wb") as f:
                shutil.copyfileobj(resp, f)
            if md5:
                digest = _file_md5(part)
                if digest != md5:
                    raise ValueError(
                        f"checksum mismatch for {url}: got {digest}, "
                        f"want {md5} — refusing to install"
                    )
            os.replace(part, dest)  # atomic: readers never see a torn tarball
        finally:
            if os.path.exists(part):
                os.remove(part)
    d = os.path.join(root, _BATCHES_DIR)
    if os.path.isdir(d):  # already installed: don't re-extract 170 MB
        return d
    # extract into a private dir, then one atomic rename: concurrent ranks
    # must never read a half-extracted batches dir
    tmp_extract = f"{d}.{os.getpid()}.extract"
    with tarfile.open(dest, "r:gz") as tf:
        tf.extractall(tmp_extract, filter="data")
    extracted = os.path.join(tmp_extract, _BATCHES_DIR)
    if not os.path.isdir(extracted):
        shutil.rmtree(tmp_extract, ignore_errors=True)
        raise FileNotFoundError(
            f"archive at {dest} did not contain {_BATCHES_DIR}/"
        )
    try:
        os.rename(extracted, d)
    except OSError:
        if not os.path.isdir(d):  # a real failure, not "another rank won"
            raise
    finally:
        shutil.rmtree(tmp_extract, ignore_errors=True)
    return d


def _normalize(images_u8: np.ndarray) -> np.ndarray:
    """uint8 [0,255] → float32 in [-1,1] (reference Normalize((0.5,)*3,(0.5,)*3))."""
    return (images_u8.astype(np.float32) / 255.0 - 0.5) / 0.5


def _load_pickle_batches(root: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray] | None:
    d = os.path.join(root, _BATCHES_DIR)
    if not os.path.isdir(d):
        tb = os.path.join(root, _TARBALL)
        if os.path.isfile(tb):
            with tarfile.open(tb, "r:gz") as tf:
                tf.extractall(root, filter="data")
        if not os.path.isdir(d):
            return None

    def read(name):
        with open(os.path.join(d, name), "rb") as f:
            entry = pickle.load(f, encoding="bytes")
        data = entry[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # →NHWC
        labels = np.asarray(entry[b"labels"], dtype=np.int32)
        return data, labels

    train = [read(f"data_batch_{i}") for i in range(1, 6)]
    x_train = np.concatenate([t[0] for t in train])
    y_train = np.concatenate([t[1] for t in train])
    x_test, y_test = read("test_batch")
    return x_train, y_train, x_test, y_test


def synthetic_cifar10(
    n_train: int = 50000, n_test: int = 10000, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic class-conditional 32×32×3 uint8 images.

    Each class gets a fixed low-frequency template (random sinusoid mixture)
    plus per-sample noise, so a CNN can separate classes — loss decreases and
    accuracy climbs well above chance, making the training-parity tests and
    benchmarks meaningful without the real dataset.
    """
    rng = np.random.default_rng(seed)
    yy, xx = np.meshgrid(np.arange(32), np.arange(32), indexing="ij")
    templates = []
    for _ in range(10):
        img = np.zeros((32, 32, 3), np.float32)
        for c in range(3):
            for _k in range(3):
                fy, fx = rng.uniform(0.5, 3.0, size=2)
                ph = rng.uniform(0, 2 * np.pi, size=2)
                img[:, :, c] += rng.uniform(0.3, 1.0) * np.sin(
                    2 * np.pi * fy * yy / 32 + ph[0]
                ) * np.cos(2 * np.pi * fx * xx / 32 + ph[1])
        templates.append(img)
    templates = np.stack(templates)  # (10,32,32,3)
    templates = (templates - templates.min()) / (np.ptp(templates) + 1e-6)

    def make(n, split_seed):
        r = np.random.default_rng(split_seed)
        labels = r.integers(0, 10, size=n).astype(np.int32)
        noise = r.normal(0.0, 0.25, size=(n, 32, 32, 3)).astype(np.float32)
        imgs = np.clip(templates[labels] + noise, 0.0, 1.0)
        return (imgs * 255).astype(np.uint8), labels

    x_train, y_train = make(n_train, seed + 1)
    x_test, y_test = make(n_test, seed + 2)
    return x_train, y_train, x_test, y_test


def load_cifar10(
    root: str = "./data", synthetic: bool | None = None, seed: int = 0,
    n_train: int = 50000, n_test: int = 10000, download: bool | None = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, bool]:
    """Return ``(x_train, y_train, x_test, y_test, is_synthetic)``, normalized.

    ``synthetic=None`` auto-detects: real data if on disk under ``root``
    (reference downloads to ``./data``, ``example/main.py:24-25``), else the
    deterministic stand-in.

    ``download=None`` attempts the network fetch exactly when the caller
    demanded real data (``synthetic=False``) and it isn't on disk — a
    deployed user gets the dataset with zero manual steps, while offline
    auto-detect runs never stall on a dead network. ``download=True``
    forces the attempt even under auto-detect; failures then fall back to
    the stand-in (auto-detect semantics) instead of raising.
    """
    loaded = None
    if synthetic is not True:
        loaded = _load_pickle_batches(root)
        if loaded is None and (download or (download is None and synthetic is False)):
            try:
                download_cifar10(root)
                loaded = _load_pickle_batches(root)
            except Exception as e:
                if synthetic is False:
                    raise FileNotFoundError(
                        f"CIFAR-10 not under {root!r} and download failed: {e}"
                    ) from e
        if loaded is None and synthetic is False:
            raise FileNotFoundError(
                f"CIFAR-10 not found under {root!r} (no {_BATCHES_DIR}/ or {_TARBALL}); "
                "pass download=True (or fix the network), or synthetic=True/None "
                "for the deterministic stand-in"
            )
    if loaded is not None:
        x_train, y_train, x_test, y_test = loaded
        is_synth = False
    else:
        x_train, y_train, x_test, y_test = synthetic_cifar10(n_train, n_test, seed)
        is_synth = True
    return _normalize(x_train), y_train, _normalize(x_test), y_test, is_synth


def get_dataset(args) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """CLI-facing loader (parity with reference ``get_dataset``, ``example/main.py:23``)."""
    x_train, y_train, x_test, y_test, _ = load_cifar10(
        root=getattr(args, "data_root", "./data"),
        synthetic=True if getattr(args, "synthetic_data", False) else None,
        n_train=getattr(args, "synthetic_train_size", 50000),
        n_test=getattr(args, "synthetic_test_size", 10000),
        download=True if getattr(args, "download", False) else None,
    )
    return x_train, y_train, x_test, y_test


def shard_for_process(
    x: np.ndarray, y: np.ndarray, process_index: int, process_count: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Strided (interleaved) per-host shard: rank r takes elements r, r+P,
    r+2P, … — each controller loads 1/process_count of the data, the pod
    analog of the reference's one-DataLoader-per-worker-rank."""
    n = (len(x) // process_count) * process_count
    return (
        x[process_index:n:process_count],
        y[process_index:n:process_count],
    )


def iterate_batches(
    x: np.ndarray,
    y: np.ndarray,
    batch_size: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    drop_last: bool = True,
    start_iter: int = 0,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Per-epoch shuffled minibatch iterator (reference DataLoader semantics,
    ``example/main.py:27``). ``drop_last=True`` keeps shapes static for jit —
    a ragged final batch would trigger recompilation on TPU.

    ``start_iter`` fast-forwards a resumed run without materializing the
    skipped batches (the permutation is a pure function of ``(seed, epoch)``,
    so skipping is just an offset into it); yielded pairs are
    ``(i, (bx, by))``-compatible via ``enumerate(..., start=start_iter)``.
    """
    n = len(x)
    idx = np.arange(n)
    if shuffle:
        np.random.default_rng(seed + epoch).shuffle(idx)
    limit = (n // batch_size) * batch_size if drop_last else n
    for start in range(start_iter * batch_size, limit, batch_size):
        sel = idx[start : start + batch_size]
        yield x[sel], y[sel]


def prefetch_to_device(
    it: Iterator[Tuple[np.ndarray, np.ndarray]], size: int = 2
) -> Iterator[Tuple]:
    """Overlap host→device transfer with device compute.

    ``jax.device_put`` is asynchronous: keeping ``size`` batches in flight
    means the next batch's HBM transfer runs while the current step computes,
    hiding input latency (the brief's "minimise host↔device transfers"
    concern — the transfers still happen, but off the critical path). The
    reference's DataLoader(num_workers=1) overlaps host decode only; this
    overlaps the device copy itself.

    The yielded leaves are committed device arrays; numerics are unchanged,
    so training with or without prefetch is bit-identical.
    """
    import collections

    import jax

    queue: "collections.deque" = collections.deque()

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                batch = next(it)
            except StopIteration:
                return
            queue.append(tuple(jax.device_put(a) for a in batch))

    enqueue(max(1, int(size)))
    while queue:
        yield queue.popleft()
        enqueue(1)
