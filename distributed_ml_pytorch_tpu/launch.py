"""C11 parity: localhost multi-process launcher.

The reference launches its 3-process PS topology by hand from three shells
(``Makefile:13-20``) and its p2p demo with ``torch.multiprocessing`` spawn
(``pytorch_p2p_ex.py:26-36``). This module does both in one command::

    python -m distributed_ml_pytorch_tpu.launch --world-size 3 -- \
        --model lenet --epochs 1 --synthetic-data

spawning rank 0 as the parameter server and ranks 1..N-1 as workers, all
against a TCP rendezvous on localhost. Everything after ``--`` is forwarded to
the trainer CLI verbatim. On a real TPU pod this launcher is unnecessary —
the pod runtime starts one controller per host and ``runtime.mesh`` handles
rendezvous — so this exists for the single-host smoke topology the reference
relies on (SURVEY.md §4).
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time
from typing import List


def _free_port() -> str:
    with socket.socket() as s:
        s.bind(("", 0))
        return str(s.getsockname()[1])


def cpu_platform_env(base: dict | None = None, n_devices: int = 1) -> dict:
    """Env for running a process on the CPU platform with ``n_devices`` virtual
    devices (shared by the launcher and the integration tests): the PS path is
    a host-side topology, so N local processes must not fight over one TPU
    chip, and the boot-time TPU plugin registration is skipped."""
    env = dict(base if base is not None else os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS=env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}",
    )
    return env


def rank_env(rank: int, *, cpu: bool = True,
             tpu_worker_rank: int | None = None) -> dict:
    """Per-rank environment for the PS topology.

    The control plane is host-side, so by default every rank runs on the CPU
    platform (N local processes must not fight over one chip). Passing
    ``tpu_worker_rank`` pins exactly that rank to the process's default
    (accelerator) platform — the DownPour layout the reference was built
    for: a central server plus workers that actually train on the
    accelerator (``asgd/optim/Asynchronous.py:42-70``), with push/pull
    crossing the device↔host boundary at the step cadence.
    """
    if tpu_worker_rank is not None:
        # pinning means EXCLUSIVE chip access: every other rank goes to the
        # CPU platform even under cpu=False, or N processes would fight over
        # libtpu's single-owner device and crash — the exact failure the
        # flag exists to prevent
        if rank == tpu_worker_rank:
            return dict(os.environ)  # default platform: the TPU when present
        return cpu_platform_env()
    return cpu_platform_env() if cpu else dict(os.environ)


def launch_world(
    world_size: int,
    extra_args: List[str],
    *,
    port: str | None = None,
    cpu: bool = True,
    tpu_worker_rank: int | None = None,
    poll_interval: float = 0.2,
) -> int:
    """Spawn 1 server + (world_size-1) workers; returns the worst exit code.

    Children are monitored: if any process exits nonzero while others are
    still running, the rest are killed — a crashed worker must not leave the
    server blocked in accept()/run() forever.
    """
    if tpu_worker_rank is not None and not 1 <= tpu_worker_rank < world_size:
        # rank 0 is always the server (it never trains — pinning it wastes
        # the chip and mislabels CPU numbers as TPU numbers); out-of-range
        # ranks would silently pin nothing
        raise ValueError(
            f"tpu_worker_rank={tpu_worker_rank} must be a worker rank "
            f"(1..{world_size - 1})"
        )
    port = port or _free_port()
    common = [
        sys.executable, "-m", "distributed_ml_pytorch_tpu.training.cli",
        "--mode", "ps", "--world-size", str(world_size), "--port", port,
    ] + list(extra_args)
    envs = [
        rank_env(r, cpu=cpu, tpu_worker_rank=tpu_worker_rank)
        for r in range(world_size)
    ]
    procs = [
        subprocess.Popen(common + ["--rank", "0", "--server"], env=envs[0])
    ]
    for rank in range(1, world_size):
        procs.append(
            subprocess.Popen(common + ["--rank", str(rank)], env=envs[rank])
        )
    try:
        while True:
            codes = [p.poll() for p in procs]
            if all(c is not None for c in codes):
                # any nonzero (including negative signal codes) is a failure
                return next((c for c in codes if c != 0), 0)
            if any(c not in (None, 0) for c in codes):
                bad = next(c for c in codes if c not in (None, 0))
                print(
                    f"launch: a process exited with code {bad}; terminating the rest",
                    file=sys.stderr,
                )
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                return bad
            time.sleep(poll_interval)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Launch the PS topology on localhost (server + workers)"
    )
    parser.add_argument("--world-size", type=int, default=3)
    parser.add_argument("--port", type=str, default=None)
    parser.add_argument("--tpu", action="store_true",
                        help="let processes use the default (TPU) platform instead of CPU")
    parser.add_argument("--tpu-worker", type=int, default=None, metavar="RANK",
                        help="pin this worker rank to the default (TPU) "
                             "platform while the server and other ranks stay "
                             "on CPU — the DownPour accelerator-worker layout")
    args, extra = parser.parse_known_args(argv)
    if extra and extra[0] == "--":
        extra = extra[1:]
    return launch_world(args.world_size, extra, port=args.port,
                        cpu=not args.tpu, tpu_worker_rank=args.tpu_worker)


if __name__ == "__main__":
    sys.exit(main())
