"""C11 parity: localhost multi-process launcher.

The reference launches its 3-process PS topology by hand from three shells
(``Makefile:13-20``) and its p2p demo with ``torch.multiprocessing`` spawn
(``pytorch_p2p_ex.py:26-36``). This module does both in one command::

    python -m distributed_ml_pytorch_tpu.launch --world-size 3 -- \
        --model lenet --epochs 1 --synthetic-data

spawning rank 0 as the parameter server and ranks 1..N-1 as workers, all
against a TCP rendezvous on localhost. Everything after ``--`` is forwarded to
the trainer CLI verbatim. On a real TPU pod this launcher is unnecessary —
the pod runtime starts one controller per host and ``runtime.mesh`` handles
rendezvous — so this exists for the single-host smoke topology the reference
relies on (SURVEY.md §4).
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time
from typing import List


def _free_port() -> str:
    with socket.socket() as s:
        s.bind(("", 0))
        return str(s.getsockname()[1])


def _free_port_block(n: int, attempts: int = 50) -> str:
    """A base port with ``n`` CONSECUTIVE free ports (sharded PS binds
    base..base+n-1, one star per shard) — verified by binding them all."""
    for _ in range(attempts):
        base = int(_free_port())
        socks = []
        try:
            for i in range(n):
                s = socket.socket()
                s.bind(("", base + i))
                socks.append(s)
            return str(base)
        except (OSError, OverflowError):  # taken, or base+i ran past 65535
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(f"no block of {n} consecutive free ports found")


def cpu_platform_env(base: dict | None = None, n_devices: int = 1) -> dict:
    """Env for running a process on the CPU platform with ``n_devices`` virtual
    devices (shared by the launcher and the integration tests): the PS path is
    a host-side topology, so N local processes must not fight over one TPU
    chip, and the boot-time TPU plugin registration is skipped."""
    env = dict(base if base is not None else os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS=env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}",
    )
    return env


def rank_env(rank: int, *, cpu: bool = True,
             tpu_worker_rank: int | None = None) -> dict:
    """Per-rank environment for the PS topology.

    The control plane is host-side, so by default every rank runs on the CPU
    platform (N local processes must not fight over one chip). Passing
    ``tpu_worker_rank`` pins exactly that rank to the process's default
    (accelerator) platform — the DownPour layout the reference was built
    for: a central server plus workers that actually train on the
    accelerator (``asgd/optim/Asynchronous.py:42-70``), with push/pull
    crossing the device↔host boundary at the step cadence.
    """
    if tpu_worker_rank is not None:
        # pinning means EXCLUSIVE chip access: every other rank goes to the
        # CPU platform even under cpu=False, or N processes would fight over
        # libtpu's single-owner device and crash — the exact failure the
        # flag exists to prevent
        if rank == tpu_worker_rank:
            return dict(os.environ)  # default platform: the TPU when present
        return cpu_platform_env()
    return cpu_platform_env() if cpu else dict(os.environ)


def launch_world(
    world_size: int,
    extra_args: List[str],
    *,
    port: str | None = None,
    cpu: bool = True,
    tpu_worker_rank: int | None = None,
    n_servers: int = 1,
    poll_interval: float = 0.2,
) -> int:
    """Spawn ``n_servers`` server rank(s) + workers; returns the worst exit
    code. ``n_servers > 1`` launches the sharded-PS layout (ranks
    0..n_servers-1 each hold a contiguous slice of the central vector).

    Children are monitored: if any process exits nonzero while others are
    still running, the rest are killed — a crashed worker must not leave the
    server blocked in accept()/run() forever.
    """
    if not 1 <= n_servers < world_size:
        raise ValueError(
            f"n_servers={n_servers} must leave at least one worker in a "
            f"world of {world_size}"
        )
    if tpu_worker_rank is not None and not n_servers <= tpu_worker_rank < world_size:
        # server ranks never train — pinning one wastes the chip and
        # mislabels CPU numbers as TPU numbers; out-of-range ranks would
        # silently pin nothing
        raise ValueError(
            f"tpu_worker_rank={tpu_worker_rank} must be a worker rank "
            f"({n_servers}..{world_size - 1})"
        )
    port = port or (_free_port_block(n_servers) if n_servers > 1 else _free_port())
    common = [
        sys.executable, "-m", "distributed_ml_pytorch_tpu.training.cli",
        "--mode", "ps", "--world-size", str(world_size), "--port", port,
    ] + (["--n-servers", str(n_servers)] if n_servers > 1 else []) + list(extra_args)
    envs = [
        rank_env(r, cpu=cpu, tpu_worker_rank=tpu_worker_rank)
        for r in range(world_size)
    ]
    procs = [
        subprocess.Popen(common + ["--rank", str(r), "--server"], env=envs[r])
        for r in range(n_servers)
    ]
    for rank in range(n_servers, world_size):
        procs.append(
            subprocess.Popen(common + ["--rank", str(rank)], env=envs[rank])
        )
    try:
        while True:
            codes = [p.poll() for p in procs]
            if all(c is not None for c in codes):
                # any nonzero (including negative signal codes) is a failure
                return next((c for c in codes if c != 0), 0)
            if any(c not in (None, 0) for c in codes):
                bad = next(c for c in codes if c not in (None, 0))
                print(
                    f"launch: a process exited with code {bad}; terminating the rest",
                    file=sys.stderr,
                )
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                return bad
            time.sleep(poll_interval)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Launch the PS topology on localhost (server + workers)"
    )
    parser.add_argument("--world-size", type=int, default=3)
    parser.add_argument("--port", type=str, default=None)
    parser.add_argument("--tpu", action="store_true",
                        help="let processes use the default (TPU) platform instead of CPU")
    parser.add_argument("--tpu-worker", type=int, default=None, metavar="RANK",
                        help="pin this worker rank to the default (TPU) "
                             "platform while the server and other ranks stay "
                             "on CPU — the DownPour accelerator-worker layout")
    parser.add_argument("--n-servers", type=int, default=1, metavar="K",
                        help="shard the parameter server across K ranks "
                             "(the DistBelief layout)")
    args, extra = parser.parse_known_args(argv)
    if extra and extra[0] == "--":
        extra = extra[1:]
    return launch_world(args.world_size, extra, port=args.port,
                        cpu=not args.tpu, tpu_worker_rank=args.tpu_worker,
                        n_servers=args.n_servers)


if __name__ == "__main__":
    sys.exit(main())
