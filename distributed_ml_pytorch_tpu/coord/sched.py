"""The multi-tenant fleet scheduler (ISSUE 16 tentpole).

DistBelief's production setting was a SHARED cluster: training jobs,
pipelines and serving fleets competed for the same machines, and the
framework's coordinator assigned work to whatever capacity existed —
not a dedicated pod per demo. This module promotes ``coord/coordinator``
to that role: tenants (``coord/tenants.py``) register demands with
priorities, the :class:`FleetScheduler` owns a :class:`CapacityLedger`
over fleet members and makes placement decisions:

- **admit / pack** — a free slot is granted directly (``SlotGrant`` to
  the node agent, which spawns the tenant's member kind — an
  ``EngineMember`` for a serving tenant).
- **preempt** — when a higher-priority tenant's demand is unmet, the
  scheduler parks a low-priority training member: it first drives a
  fleet snapshot barrier (the ``FleetManifest`` the park restores from
  — the ``require_manifest`` gate the ``sched`` model checks), then
  sends ``PreemptRequest``; the victim commits its WAL group, reports
  ``PreemptDone`` and stops serving WITHOUT a ``CoordLeave`` — a parked
  life, not a dead one (its lease is exempt from expiry, its shard-map
  range stays put so workers degrade to held pushes, and a resume
  rejoins the SAME range).
- **resume** — off-peak, the grant is revoked (the agent retires the
  engine) and ``ResumeRequest`` tells the agent to restore the parked
  member bit-for-bit: fresh ``ElasticShardServer`` over the manifest's
  checkpoint + exactly-once WAL replay (``restore_from_manifest``),
  rejoining as a newer incarnation of the same rank.

The capacity ledger is EXCLUSIVE by construction: a slot is granted to
the waiting tenant only after the victim's ``PreemptDone`` frees it
(``enforce_exclusive``; the ``double_grant_slot`` model mutation drops
exactly this gate and ``audit()`` is the runtime detector).

Like every coordinator decision, scheduling is synchronous and clock-
injected: ``tick(now)`` runs on the coordinator's serve thread (wired
via ``coord.sched``), so tests drive the whole protocol with
``handle()``/``tick()`` calls and a fake clock. Decisions ride a capped
:class:`~.obs.BoundedEvents` ring carrying the tenant id (total/dropped
accounting — no append-forever maps) and double as ``sched``-plane
flight-recorder events, so ``make timeline`` attributes where shared-
capacity seconds went.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional

from distributed_ml_pytorch_tpu.coord.tenants import (
    TENANT_TRAINING,
    Tenant,
    TenantRegistry,
)
from distributed_ml_pytorch_tpu.utils import obs
from distributed_ml_pytorch_tpu.utils.messaging import MessageCode

_LOGGER = logging.getLogger(__name__)

#: slot states (the sched plane's protocol states, mirrored by the
#: ``analysis/distmodel.SchedModel`` bounded checker)
FREE = "free"          # unowned capacity
HELD = "held"          # a tenant's member runs here
PARKING = "parking"    # preempt in flight: snapshot barrier / PreemptRequest
PARKED = "parked"      # victim parked under a manifest; slot re-granted
RESUMING = "resuming"  # ResumeRequest sent; awaiting the rank's new life


@dataclasses.dataclass
class Slot:
    """One schedulable unit of fleet capacity.

    ``owners`` is a LIST so the ledger can represent the illegal state
    (two tenants owning one slot) instead of silently collapsing it —
    ``audit()`` is the runtime detector for the ``double_grant_slot``
    protocol bug, and a detector that cannot represent the bug detects
    nothing.
    """

    slot_id: int
    rank: Optional[int] = None  # coordinator rank of the occupying member
    owners: List[int] = dataclasses.field(default_factory=list)
    state: str = FREE
    grant_id: int = 0
    #: the parked member's restore ticket: rank, old incarnation, the
    #: manifest snapshot id, its [lo,hi) range and apply_seq at park
    parked: Optional[dict] = None


class CapacityLedger:
    """Who owns which slot — the scheduler's single source of truth.

    ``enforce_exclusive`` is the correctness gate: a grant over a slot
    another tenant still owns is REFUSED until the preempt protocol
    frees it. The ``double_grant_slot`` mutation (and a misconfigured
    deployment) drops the gate; :meth:`audit` reports every slot the
    drop corrupted.
    """

    def __init__(self, *, enforce_exclusive: bool = True) -> None:
        self.enforce_exclusive = bool(enforce_exclusive)
        self.slots: Dict[int, Slot] = {}
        self._next_slot = 0

    def add_slot(self, *, rank: Optional[int] = None,
                 tenant_id: Optional[int] = None) -> Slot:
        slot = Slot(slot_id=self._next_slot, rank=rank)
        self._next_slot += 1
        if tenant_id is not None:
            slot.owners.append(int(tenant_id))
            slot.state = HELD
        self.slots[slot.slot_id] = slot
        return slot

    def owned(self, tenant_id: int) -> List[Slot]:
        return [s for s in self.slots.values() if tenant_id in s.owners]

    def free_slots(self) -> List[Slot]:
        return [s for s in self.slots.values()
                if not s.owners and s.state == FREE]

    def grant(self, slot: Slot, tenant_id: int, grant_id: int) -> bool:
        """Grant ``slot`` to ``tenant_id``; False when exclusivity refuses."""
        others = [o for o in slot.owners if o != tenant_id]
        if others and self.enforce_exclusive:
            return False
        if tenant_id not in slot.owners:
            slot.owners.append(int(tenant_id))
        slot.grant_id = int(grant_id)
        return True

    def release(self, slot: Slot, tenant_id: int) -> None:
        if tenant_id in slot.owners:
            slot.owners.remove(tenant_id)

    def audit(self) -> List[str]:
        """Runtime exclusivity check: every multi-owner slot is a
        violation (the model invariant's real-ledger twin)."""
        return [
            f"slot {s.slot_id} double-granted: owned by tenants "
            f"{sorted(set(s.owners))}"
            for s in self.slots.values() if len(set(s.owners)) > 1
        ]


class FleetScheduler:
    """Placement decisions over the coordinator's member fleet.

    Attach to a :class:`~.coordinator.Coordinator` (the constructor sets
    ``coord.sched``); the coordinator's ``tick`` drives :meth:`tick` on
    the serve thread and dispatches ``PreemptDone`` frames to
    :meth:`on_preempt_done`. Actuation goes to the node agent member at
    ``actuator_rank`` over the wire (``SlotGrant`` / ``ResumeRequest``)
    and/or to the in-process ``on_grant`` / ``on_resume`` callbacks a
    colocated harness sets.
    """

    def __init__(
        self,
        coord,
        *,
        registry: Optional[TenantRegistry] = None,
        require_manifest: bool = True,
        enforce_exclusive: bool = True,
        actuator_rank: Optional[int] = None,
        preempt_timeout: float = 30.0,
        resume_timeout: float = 30.0,
    ) -> None:
        self.coord = coord
        self.registry = registry if registry is not None else TenantRegistry()
        self.ledger = CapacityLedger(enforce_exclusive=enforce_exclusive)
        #: the park-with-manifest gate: a preempt first drives a fleet
        #: snapshot barrier and only parks once the manifest is durable.
        #: Dropping it is the ``park_without_manifest`` mutation — the
        #: parked state may then be unrestorable (acked deltas lost).
        self.require_manifest = bool(require_manifest)
        self.actuator_rank = actuator_rank
        self.preempt_timeout = float(preempt_timeout)
        self.resume_timeout = float(resume_timeout)
        #: capped decision ring (the ISSUE 16 small fix): every scale /
        #: preempt / resume decision carries its tenant id and total /
        #: dropped accounting — scheduler state holds NO unbounded maps
        self.decisions = obs.BoundedEvents(maxlen=512)
        #: in-process actuators (optional; the wire path is the agent):
        #: on_grant(grant_id, tenant_id, action, slot),
        #: on_resume(grant_id, parked_dict)
        self.on_grant = None
        self.on_resume = None
        self._next_grant = 1
        self._pending: Optional[dict] = None    # one preempt in flight
        self._resuming: Optional[dict] = None   # one resume in flight
        self.preempts_done = 0
        self.preempts_aborted = 0
        self.resumes_done = 0
        self.preempt_mttrs: List[float] = []
        self.resume_mttrs: List[float] = []
        coord.sched = self
        # a durable coordinator restart (ISSUE 17) re-seeds the ledger from
        # its checkpoint and reconciles slots against the WAL'd park table
        # — the scheduler is usually attached AFTER the restore ran
        if getattr(coord, "_sched_restore", None) is not None \
                or getattr(coord, "_parked_durable", None):
            coord._restore_sched_state(self)

    # ---------------------------------------------------------- bookkeeping
    def _log(self, tenant_id: int, msg: str) -> None:
        line = f"tenant {tenant_id}: {msg}"
        self.decisions.append(line)
        # mirror onto the coordinator's decision log (same capped ring the
        # CLI tails) and the fleet timeline as a sched-plane event
        self.coord.events.append(f"sched {line}")
        if self.coord.recorder is not None:
            self.coord.recorder.event("sched", corr=int(tenant_id), msg=msg)
        _LOGGER.info("sched: %s", line)

    def parked_ranks(self) -> set:
        """Ranks whose silence is a PARK, not a death — the coordinator's
        lease expiry and snapshot barrier exempt them."""
        out = set()
        for s in self.ledger.slots.values():
            if s.parked is not None and s.state in (PARKED, RESUMING):
                out.add(s.parked["rank"])
        return out

    def register_member_slot(self, rank: int, tenant_id: int) -> Slot:
        """Record an existing member as a tenant-held slot."""
        return self.ledger.add_slot(rank=rank, tenant_id=tenant_id)

    def summary(self) -> dict:
        return {
            "preempts_done": self.preempts_done,
            "preempts_aborted": self.preempts_aborted,
            "resumes_done": self.resumes_done,
            "preempt_mttr_s": list(self.preempt_mttrs),
            "resume_mttr_s": list(self.resume_mttrs),
            "decisions_total": self.decisions.total,
            "decisions_dropped": self.decisions.dropped,
            "audit": self.ledger.audit(),
            "slots": {s.slot_id: {"state": s.state,
                                  "owners": sorted(set(s.owners)),
                                  "rank": s.rank}
                      for s in self.ledger.slots.values()},
        }

    # ----------------------------------------------------------------- tick
    def tick(self, now: float) -> None:
        """One scheduling pass (serve thread, via ``Coordinator.tick``)."""
        self._drive_pending(now)
        self._drive_resuming(now)
        self._evaluate(now)

    def _evaluate(self, now: float) -> None:
        for tenant in self.registry.all():  # priority-descending
            have = len(self.ledger.owned(tenant.tenant_id))
            if (self._pending is not None
                    and self._pending["for"] == tenant.tenant_id):
                have += 1  # a preempt already in flight counts as packed
            shortfall = tenant.demand - have
            if shortfall > 0:
                self._pack(tenant, shortfall, now)
            elif shortfall < 0:
                self._shrink(tenant, -shortfall, now)

    def _pack(self, tenant: Tenant, shortfall: int, now: float) -> None:
        for slot in self.ledger.free_slots():
            if shortfall <= 0:
                return
            gid = self._next_grant
            self._next_grant += 1
            self.ledger.grant(slot, tenant.tenant_id, gid)
            slot.state = HELD
            shortfall -= 1
            self._log(tenant.tenant_id,
                      f"admit: free slot {slot.slot_id} granted "
                      f"(grant {gid})")
            self._actuate_grant(gid, tenant.tenant_id, 1, slot)
        if shortfall <= 0 or self._pending is not None:
            return
        victim = self._pick_victim(tenant)
        if victim is None:
            return
        slot, victim_tenant = victim
        if not self.ledger.enforce_exclusive:
            # the double_grant_slot bug surface: capacity handed to the
            # new tenant BEFORE the victim's park completes — the ledger
            # now shows two owners, audit() flags it
            gid = self._next_grant
            self._next_grant += 1
            self.ledger.grant(slot, tenant.tenant_id, gid)
            self._log(tenant.tenant_id,
                      f"grant of slot {slot.slot_id} issued while tenant "
                      f"{victim_tenant.tenant_id} still holds it "
                      f"(exclusivity off)")
            self._actuate_grant(gid, tenant.tenant_id, 1, slot)
        self._start_preempt(slot, victim_tenant, tenant, now)

    def _pick_victim(self, tenant: Tenant):
        """Lowest-priority HELD slot whose owner outranks nobody — never
        preempt a peer or superior, never below the owner's min_slots."""
        for victim in self.registry.by_priority_asc():
            if victim.priority >= tenant.priority:
                return None
            owned = [s for s in self.ledger.owned(victim.tenant_id)
                     if s.state == HELD and s.rank is not None]
            if len(owned) <= victim.min_slots or not owned:
                continue
            return owned[-1], victim
        return None

    def _shrink(self, tenant: Tenant, surplus: int, now: float) -> None:
        if self._resuming is not None:
            return
        # shed parked-backed slots first: releasing one both retires the
        # borrowed member AND resumes the parked victim
        owned = sorted(self.ledger.owned(tenant.tenant_id),
                       key=lambda s: s.parked is None)
        for slot in owned[:surplus]:
            if slot.state not in (HELD, PARKED):
                continue
            self.ledger.release(slot, tenant.tenant_id)
            self._log(tenant.tenant_id,
                      f"release: slot {slot.slot_id} revoked "
                      f"(grant {slot.grant_id})")
            self._actuate_grant(slot.grant_id, tenant.tenant_id, 0, slot)
            if slot.parked is not None:
                self._start_resume(slot, now)
                return  # one resume in flight at a time
            slot.state = FREE

    # -------------------------------------------------------------- preempt
    def _start_preempt(self, slot: Slot, victim: Tenant, for_tenant: Tenant,
                       now: float) -> None:
        slot.state = PARKING
        gid = self._next_grant
        self._next_grant += 1
        self._pending = {
            "slot": slot,
            "victim": victim.tenant_id,
            "for": for_tenant.tenant_id,
            "grant_id": gid,
            "started": now,
            "manifest_baseline": self.coord.manifests_written,
            "snap_requested": False,
            "sent": False,
        }
        self._log(for_tenant.tenant_id,
                  f"preempt: parking tenant {victim.tenant_id}'s member "
                  f"rank {slot.rank} (slot {slot.slot_id}, grant {gid}, "
                  f"manifest {'required' if self.require_manifest else 'SKIPPED'})")
        self._drive_pending(now)

    def _drive_pending(self, now: float) -> None:
        p = self._pending
        if p is None:
            return
        slot = p["slot"]
        if now - p["started"] > self.preempt_timeout:
            slot.state = HELD
            self.preempts_aborted += 1
            self._pending = None
            self._log(p["for"],
                      f"preempt of slot {slot.slot_id} ABANDONED after "
                      f"{self.preempt_timeout:.0f}s (grant {p['grant_id']})")
            return
        if p["sent"]:
            return
        if self.require_manifest:
            if not p["snap_requested"]:
                p["snap_requested"] = True
                self.coord.trigger_snapshot()
                return
            if self.coord.manifests_written <= p["manifest_baseline"]:
                return  # barrier still in flight; next tick re-checks
            snap_id = int(self.coord.last_manifest.snapshot_id)
        else:
            lm = self.coord.last_manifest
            snap_id = int(lm.snapshot_id) if lm is not None else 0
        from distributed_ml_pytorch_tpu.coord.coordinator import (
            encode_preempt_request,
        )

        p["sent"] = True
        p["snap_id"] = snap_id
        self.coord._send(slot.rank, MessageCode.PreemptRequest,
                         encode_preempt_request(p["grant_id"], snap_id))
        self._log(p["for"],
                  f"preempt: PreemptRequest grant {p['grant_id']} snapshot "
                  f"{snap_id} -> rank {slot.rank}")

    def on_preempt_done(self, sender: int, *, grant_id: int, snap_id: int,
                        lo: int, hi: int, apply_seq: int,
                        now: float) -> None:
        """Wired from ``Coordinator.handle`` (PreemptDone dispatch)."""
        p = self._pending
        if p is None or grant_id != p["grant_id"] or p["slot"].rank != sender:
            self._log(-1, f"stale PreemptDone from rank {sender} "
                          f"(grant {grant_id})")
            return
        slot = p["slot"]
        member = self.coord.members.get(sender)
        parked = {
            "rank": sender,
            "tenant": p["victim"],
            "incarnation": member.incarnation if member is not None else 0,
            "snapshot_id": snap_id,
            "lo": lo,
            "hi": hi,
            "apply_seq": apply_seq,
            # the borrowing side of the hand-over, so a coordinator that
            # crashes between this park and its next checkpoint can
            # resynthesize the slot — owner, grant and all — from the
            # WAL'd ticket alone (never strand the victim, never
            # double-grant its capacity)
            "slot_id": slot.slot_id,
            "borrower": p["for"],
            "grant_id": grant_id,
        }
        # journal the park BEFORE the ledger mutates (ISSUE 17): a
        # coordinator crash right after this line must restore the member
        # as PARKED — never strand it under a re-armed lease or hand its
        # slot out twice
        self.coord.note_parked(sender, parked)
        slot.parked = parked
        self.ledger.release(slot, p["victim"])
        slot.state = PARKED
        mttr = now - p["started"]
        self.preempts_done += 1
        self.preempt_mttrs.append(mttr)
        self._log(p["victim"],
                  f"parked: rank {sender} [{lo},{hi}) at apply seq "
                  f"{apply_seq} under snapshot {snap_id} "
                  f"({mttr * 1e3:.0f} ms)")
        # only NOW is the slot free for the waiting tenant (the exclusive
        # hand-over the double_grant_slot mutation breaks)
        self.ledger.grant(slot, p["for"], grant_id)
        self._log(p["for"],
                  f"grant: slot {slot.slot_id} -> tenant {p['for']} "
                  f"(grant {grant_id})")
        self._actuate_grant(grant_id, p["for"], 1, slot)
        self._pending = None

    # --------------------------------------------------------------- resume
    def _start_resume(self, slot: Slot, now: float) -> None:
        from distributed_ml_pytorch_tpu.coord.coordinator import (
            encode_resume_request,
        )

        slot.state = RESUMING
        gid = self._next_grant
        self._next_grant += 1
        self._resuming = {
            "slot": slot,
            "grant_id": gid,
            "started": now,
            "incarnation": slot.parked["incarnation"],
        }
        self._log(slot.parked["tenant"],
                  f"resume: restoring rank {slot.parked['rank']} from "
                  f"snapshot {slot.parked['snapshot_id']} (grant {gid})")
        if self.actuator_rank is not None:
            self.coord._send(
                self.actuator_rank, MessageCode.ResumeRequest,
                encode_resume_request(gid, slot.parked["rank"],
                                      slot.parked["snapshot_id"]))
        if self.on_resume is not None:
            self.on_resume(gid, dict(slot.parked))

    def _drive_resuming(self, now: float) -> None:
        r = self._resuming
        if r is None:
            return
        slot = r["slot"]
        parked = slot.parked
        member = self.coord.members.get(parked["rank"])
        if member is not None and member.incarnation > r["incarnation"]:
            # the rank's new life joined: the park round-tripped — journal
            # the unpark first (log-then-mutate, ISSUE 17)
            self.coord.note_unparked(parked["rank"])
            tenant_id = parked["tenant"]
            slot.parked = None
            slot.owners = [tenant_id]
            slot.state = HELD
            mttr = now - r["started"]
            self.resumes_done += 1
            self.resume_mttrs.append(mttr)
            self._resuming = None
            self._log(tenant_id,
                      f"resumed: rank {parked['rank']} rejoined as inc "
                      f"{member.incarnation} ({mttr * 1e3:.0f} ms) — slot "
                      f"{slot.slot_id} back to tenant {tenant_id}")
            return
        if now - r["started"] > self.resume_timeout:
            slot.state = PARKED
            self._resuming = None
            self._log(parked["tenant"],
                      f"resume of rank {parked['rank']} ABANDONED after "
                      f"{self.resume_timeout:.0f}s — still parked")

    # ------------------------------------------------------------- actuation
    def _actuate_grant(self, grant_id: int, tenant_id: int, action: int,
                       slot: Slot) -> None:
        if self.actuator_rank is not None:
            from distributed_ml_pytorch_tpu.coord.coordinator import (
                encode_slot_grant,
            )

            self.coord._send(
                self.actuator_rank, MessageCode.SlotGrant,
                encode_slot_grant(grant_id, tenant_id, action, slot.slot_id))
        if self.on_grant is not None:
            self.on_grant(grant_id, tenant_id, action, slot)
