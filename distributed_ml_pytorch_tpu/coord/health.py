"""The numerical-health acceptance scenario as reusable machinery
(ISSUE 8 tentpole).

:func:`health_scenario` stands up the full training immune system in one
process — coordinator (auto-rollback watchdog + worker reputation) + N
elastic WAL'd shard servers behind the admission gate + M DownPour workers
over reliable transports — and runs the ISSUE 8 script:

1. train cleanly; at a scripted step, drive a snapshot barrier so a good
   :class:`FleetManifest` exists (the rollback target);
2. a **poisoned worker**'s push channel suffers seeded SDC: first a
   norm-preserving-enough *scale* corruption (``×factor``, re-stamped CRC —
   bit-perfect on the wire) that SLIPS the admission gate's z-score and
   silently drives the central params toward divergence, then *NaN*
   injection that the gate catches and quarantines, nacking every one;
3. the fleet's loss telemetry (EWMAs riding lease renewals) diverges; the
   coordinator's watchdog broadcasts a **RollbackRequest barrier**: shards
   restore the manifest snapshot in place (checkpoint + WAL capped at its
   apply seq, tail dropped), workers drop their in-flight accumulators and
   pull, training resumes — MTTR is measured;
4. the repeat offender's nack count (riding its renewals) crosses the
   reputation limit and its lease is **revoked** (rejoin only after a
   cooldown, with fresh params);
5. the run finishes in the fault-free corridor, every rejected update was
   explicitly nacked (never silently dropped) and none ever reached a WAL.

Determinism contract: SDC decisions for enveloped pushes are keyed by the
reliability envelope's sequence number — a pure function of the worker's
step script (pushes are the only enveloped worker→server traffic here;
pulls ride plain) — and retransmits re-derive the same corruption without
re-logging, so the chaos log renders byte-identically across runs
(``tests/test_health.py`` asserts it 3×). The scripted barriers (snapshot
BEFORE poison, worker 1 waiting out the rollback) order the wall-clock
events without touching any faulted channel.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from distributed_ml_pytorch_tpu.coord.coordinator import Coordinator
from distributed_ml_pytorch_tpu.coord.elastic import ElasticShardServer
from distributed_ml_pytorch_tpu.coord.manifest import MANIFEST_NAME
from distributed_ml_pytorch_tpu.coord.member import CoordClient
from distributed_ml_pytorch_tpu.utils.chaos import (
    ChaosLog,
    ChaosPlan,
    FaultyTransport,
    SDCRule,
)
from distributed_ml_pytorch_tpu.utils.health import GradientAdmission
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    ReliableTransport,
)

#: codes that ride PLAIN in health worlds — same reasoning as the drill's
#: DRILL_UNRELIABLE: pulls/replies are periodic, idempotent and
#: cadence-driven, so keeping them out of the envelope keeps the enveloped
#: seq space (which keys the SDC decisions) a pure function of the push
#: script. UpdateNack stays ENVELOPED: a nack is the explicit-reject
#: contract and gets retransmit service.
HEALTH_UNRELIABLE = (
    MessageCode.Heartbeat,
    MessageCode.LeaseRenew,
    MessageCode.ParameterRequest,
    MessageCode.ParameterUpdate,
)


def poisoned_worker_sdc(worker: int, *, scale_after: int, scale_until: int,
                        nan_after: int, nan_until: Optional[int] = None,
                        factor: float = -8.0) -> tuple:
    """The scripted poisoned-worker fault mix for ``worker``'s push channel
    (ISSUE 8): a window of norm-preserving-enough *scale* SDC (slips the
    admission gate; ``factor < 0`` turns descent deltas into ascent — the
    corruption the gate CANNOT see and the rollback watchdog exists for),
    followed by *NaN* SDC (caught + nacked at the gate — the reputation
    driver). ``nan_until`` bounds the episode (a transient fault — the
    overheated part recovers): past it the worker's pushes are clean
    again and the gate readmits them, so the fleet re-converges at full
    throughput even while reputation still has the worker's lease
    revoked (the data plane judges updates, not history). ``skip=6``
    preserves the ShardPush version/range head: the model is a corrupted
    gradient buffer, not a corrupted protocol stamp. Windows are
    envelope-seq indices == push indices."""
    return (
        SDCRule(src=worker, dst=0, code=int(MessageCode.ShardPush), p=1.0,
                kind="scale", factor=factor, skip=6,
                after=scale_after, until=scale_until),
        SDCRule(src=worker, dst=0, code=int(MessageCode.ShardPush), p=1.0,
                kind="nan", skip=6, after=nan_after, until=nan_until),
    )


def _default_fixture(seed: int):
    from distributed_ml_pytorch_tpu.coord.demo import (
        _default_fixture as fixture,
    )

    return fixture(seed)


def _wait_for(predicate, timeout: float, what: str, poll: float = 0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(poll)
    raise TimeoutError(
        f"health: timed out after {timeout:.0f}s waiting for {what}")


def health_scenario(
    *,
    base_dir: str,
    seed: int = 0,
    steps: int = 64,
    n_workers: int = 2,
    n_shards: int = 2,
    poison_worker: Optional[int] = 2,
    snapshot_at: int = 20,
    scale_after: int = 11,
    scale_until: int = 16,
    nan_after: int = 16,
    nan_until: Optional[int] = 22,
    poison_factor: float = -16.0,
    rollback_wait_at: int = 36,
    watchdog_at: Optional[int] = None,
    lease: float = 5.0,
    renew_interval: float = 0.1,
    lr: float = 0.05,
    n_push: int = 2,
    n_pull: int = 2,
    batch: int = 16,
    step_sleep: float = 0.03,
    z_max: float = 6.0,
    warmup: int = 2,
    reputation_nacks: int = 6,
    reputation_cooldown: float = 60.0,
    rollback_loss_factor: float = 1.2,
    rollback_timeout: float = 60.0,
    wal_group_n: int = 4,
    fixture=None,
) -> Dict:
    """Run one pass of the immune-system script (module docstring).

    ``poison_worker=None`` runs the fault-free corridor baseline (no SDC,
    no rollback expected — the snapshot barrier still fires). Step indices
    (``snapshot_at``, ``rollback_wait_at``) are on worker 1's loop;
    ``scale_after``/``scale_until``/``nan_after`` are PUSH indices on the
    poisoned worker's channel (envelope seqs).

    The rollback watchdog starts DISARMED and the poisoned worker arms it
    at step ``watchdog_at`` (default: the step after its last scale-window
    push), after draining its push flusher and waiting for every shard to
    have processed the whole window. That ordering is the scenario's one
    deliberate crutch: a watchdog that fires mid-window restores the
    manifest while gate-slipping scale pushes are still streaming — they
    re-poison the restored params, and the rollback cooldown (correctly)
    refuses an immediate second barrier, so the run ends diverged. Real
    deployments tune ``rollback_cooldown`` against their poison dwell
    time; the acceptance instead pins the deterministic case: window
    drained -> watchdog fires -> restore sticks (stale diverged-gradient
    pushes that arrive after it are z-rejected by the gate — the layers
    cover each other).
    """
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.parallel.sharded_ps import (
        ShardedAsynchronous,
    )
    from distributed_ml_pytorch_tpu.utils.serialization import (
        ravel_model_params,
    )

    if fixture is not None:
        x, y, grad_fn, params0 = fixture
    else:
        x, y, grad_fn, params0 = _default_fixture(seed)
    flat0 = np.asarray(ravel_model_params(params0), np.float32)
    n_params = int(flat0.shape[0])
    poisoned = poison_worker is not None

    plan = ChaosPlan(
        seed=seed,
        sdc=(poisoned_worker_sdc(
            poison_worker, scale_after=scale_after, scale_until=scale_until,
            nan_after=nan_after, nan_until=nan_until,
            factor=poison_factor) if poisoned else ()))

    # --- worlds: plain coordination star + one chaos-wrapped reliable PS
    # star per shard, all sharing one log (drill topology) ----------------
    log = ChaosLog()
    coord_world = InProcessTransport.create_world(1 + n_shards + n_workers)
    star_chaos: List[Dict[int, FaultyTransport]] = []
    for i in range(n_shards):
        world = InProcessTransport.create_world(1 + n_workers)
        hub = FaultyTransport(world[0], plan, log=log)
        star = {0: hub}
        for r in range(1, 1 + n_workers):
            star[r] = hub.sibling(world[r])
        star_chaos.append(star)

    # breaker_grace: the health plan is SDC-ONLY — frames are corrupted in
    # place, never dropped or delayed — so an RTO blowup here can only be
    # scheduler starvation (jit'd grad threads hogging this 1-core host's
    # GIL), not a dead peer. Left at its default (= max_backoff, 0.25 s)
    # the breaker false-opens under load and its exponential cooldown
    # turns a transient stall into a stuck poison-window drain; a long
    # grace keeps retransmits flowing instead.
    def make_server_transport(i: int) -> ReliableTransport:
        return ReliableTransport(
            star_chaos[i][0], ack_timeout=0.05, max_backoff=0.25,
            max_retries=120, unreliable_codes=HEALTH_UNRELIABLE,
            ack_on_delivery=False, breaker_grace=60.0)

    rel_workers: List[Dict[int, ReliableTransport]] = []
    for i in range(n_shards):
        rel_workers.append({
            j: ReliableTransport(
                star_chaos[i][j], ack_timeout=0.05, max_backoff=0.25,
                max_retries=120, unreliable_codes=HEALTH_UNRELIABLE,
                breaker_grace=60.0)
            for j in range(1, 1 + n_workers)})

    manifest_path = os.path.join(base_dir, MANIFEST_NAME)
    if watchdog_at is None:
        watchdog_at = scale_until * n_push  # first step past the window
    coord = Coordinator(
        coord_world[0], n_params, lease=lease, speculation=False,
        manifest_dir=base_dir, auto_rollback=False,  # armed at watchdog_at
        rollback_loss_factor=rollback_loss_factor,
        rollback_cooldown=600.0,  # at most ONE rollback per run: the log's
        # determinism (and the assertion "exactly the scripted barrier")
        # must not depend on how fast post-restore telemetry recovers
        rollback_timeout=rollback_timeout,
        reputation_nacks=reputation_nacks,
        reputation_cooldown=reputation_cooldown)
    # flight recorder (ISSUE 12): the rollback barrier auto-dumps the
    # decision timeline into base_dir/obs — every rollback MTTR ships
    # with its window. Observational only: the 3x byte-identical
    # chaos-log acceptance runs WITH this attached (the recorder-
    # determinism guard for the health scenario).
    from distributed_ml_pytorch_tpu.utils import obs as _obs

    coord.recorder = _obs.SpanRecorder("coord", "coord")
    coord.obs_dir = os.path.join(base_dir, "obs")
    coord_thread = threading.Thread(
        target=coord.run, kwargs={"timeout": 600}, daemon=True)
    coord_thread.start()

    servers: List[ElasticShardServer] = []
    for i in range(n_shards):
        client = CoordClient(coord_world[1 + i], "shard",
                             renew_interval=renew_interval)
        srv = ElasticShardServer(
            server_id=1 + i, n_params=n_params,
            transport=make_server_transport(i), coord=client,
            init_params=flat0, ckpt_dir=os.path.join(base_dir, f"shard{i}"),
            ckpt_every=0, wal=True, wal_group_n=wal_group_n,
            admission=GradientAdmission(z_max=z_max, warmup=warmup),
            manifest_path=manifest_path)
        servers.append(srv)
        threading.Thread(target=srv.run, kwargs={"timeout": 600},
                         daemon=True).start()
    _wait_for(lambda: len(coord.shard_map.entries) == n_shards, 60,
              "all shard servers to join the map")

    losses: Dict[int, list] = {}
    opts: Dict[int, object] = {}
    errors: list = []
    snap_evt = threading.Event()
    timings: Dict[str, float] = {}

    def step_hook(j: int, step: int) -> None:
        if poisoned and j == poison_worker and step == watchdog_at:
            # arm the watchdog only once the scale window is fully THROUGH
            # the shards (docstring: a mid-window rollback gets re-poisoned
            # and the cooldown forbids a second). The flusher drain hands
            # every window push to the in-process wire (instant delivery);
            # the wait below covers the shards' serve loops consuming them.
            opts[j]._flusher.drain()
            _wait_for(lambda: all(
                (servers[i].ps.applied_by_sender.get(j, 0)
                 + servers[i].ps.quarantined_by_sender.get(j, 0))
                >= scale_until for i in range(n_shards)), 180,
                "the scale-poison window to drain through every shard")
            coord.auto_rollback = True
            # hold here until the barrier closes: the watchdog fires off
            # this worker's own diverged telemetry (its renew thread keeps
            # flowing while it waits), and waiting guarantees steps remain
            # to consume the phase-0 drop-and-pull after completion
            _wait_for(lambda: coord.rollbacks_done >= 1, 120,
                      "the watchdog-triggered rollback to complete")
        if j != 1:
            # the poison windows are push indices PAST the snapshot: every
            # other worker barriers just before its first poisonable push
            # so the manifest provably predates the poison (the rollback
            # target must be clean) — this couples only thread timing on
            # unfaulted channels, so the chaos log stays deterministic
            if step == snapshot_at:
                snap_evt.wait(300)
            return
        if step == snapshot_at:
            coord.trigger_snapshot()
            try:
                _wait_for(lambda: os.path.exists(manifest_path)
                          and coord.manifests_written > 0, 60,
                          "the snapshot barrier to publish a manifest")
            finally:
                snap_evt.set()
        if poisoned and step == rollback_wait_at:
            # the acceptance needs >= 1 COMPLETED rollback inside the run,
            # with post-rollback steps left to re-converge: hold the
            # scripting worker here until the watchdog has fired and the
            # barrier closed (its renew thread keeps the diverged telemetry
            # flowing while it waits)
            timings["wait_start"] = time.monotonic()
            _wait_for(lambda: coord.rollbacks_done >= 1, 120,
                      "the coordinator's auto-rollback to complete")
            timings["rollback_seen"] = time.monotonic()

    def run_worker(j: int) -> None:
        try:
            _run_worker(j)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            errors.append((j, repr(e)))
            snap_evt.set()  # never leave the other workers barriered

    def _run_worker(j: int) -> None:
        client = CoordClient(coord_world[n_shards + j], "worker",
                             renew_interval=renew_interval)
        m = client.join(timeout=30)
        assert m is not None and m.entries, "worker never got a shard map"
        factory = lambda entry: rel_workers[entry.server_id - 1][j]
        params = jax.tree.map(jnp.asarray, params0)
        opt = ShardedAsynchronous(
            params, lr=lr, n_push=n_push, n_pull=n_pull,
            transports=[factory(e) for e in m.entries],
            coord=client, transport_factory=factory, shard_map=m)
        opts[j] = opt
        rng = jax.random.key(100 + j)
        my_losses = losses.setdefault(j, [])
        for step in range(steps):
            sel = np.random.default_rng(j * 1000 + step).integers(
                0, len(x), batch)
            loss, grads = grad_fn(params, x[sel], y[sel],
                                  jax.random.fold_in(rng, step))
            loss = float(loss)
            # loss rides into step(): it feeds the lease-renewal telemetry
            # AND gates the worker's own update application (a nonfinite
            # loss means these grads must not touch the params)
            params = opt.step(params, grads, loss=loss)
            my_losses.append(loss)
            if step_sleep > 0:
                time.sleep(step_sleep)
            step_hook(j, step)
        opt.finish()
        client.close()

    worker_threads = [threading.Thread(target=run_worker, args=(j,),
                                       daemon=True)
                      for j in range(1, n_workers + 1)]
    for t in worker_threads:
        t.start()
    for t in worker_threads:
        t.join(timeout=600)
    stuck = [t for t in worker_threads if t.is_alive()]
    for srv in servers:
        srv.stop()
    time.sleep(0.05)
    coord.stop()
    coord_thread.join(timeout=30)

    # ---- the explicit-reject ledger: every quarantined update must have
    # been nacked (never silently dropped), and the sequence accounting
    # must close — acked <= applied + quarantined + rolled-back ----------
    acked: Dict[int, Dict[int, int]] = {}
    applied: Dict[int, Dict[int, int]] = {}
    quarantined: Dict[int, Dict[int, int]] = {}
    for i in range(n_shards):
        acked[i] = {j: (rel_workers[i][j].acked_count(
            0, MessageCode.ShardPush) + rel_workers[i][j].acked_count(
            0, MessageCode.GradientUpdate))
            for j in range(1, 1 + n_workers)}
        applied[i] = {j: servers[i].ps.applied_by_sender.get(j, 0)
                      for j in range(1, 1 + n_workers)}
        quarantined[i] = {j: servers[i].ps.quarantined_by_sender.get(j, 0)
                          for j in range(1, 1 + n_workers)}
    accounting_ok = all(
        acked[i][j] <= (applied[i][j] + quarantined[i][j]
                        + servers[i].ps.rolled_back_updates)
        for i in range(n_shards) for j in range(1, 1 + n_workers))
    nacks_explicit = all(
        srv.ps.quarantined == srv.ps.nacks_sent for srv in servers)
    central_finite = all(
        bool(np.isfinite(srv.central).all()) for srv in servers)

    for star in rel_workers:
        for t in star.values():
            t.close()
    for srv in servers:
        close = getattr(srv.transport, "close", None)
        if close is not None:
            close()
    for t in coord_world.values():
        t.close()

    worker_nacks = {j: getattr(opts.get(j), "nacks", 0)
                    for j in range(1, 1 + n_workers)}
    return {
        "ok": (not stuck and not errors and accounting_ok
               and nacks_explicit and central_finite),
        "errors": errors,
        "stuck_workers": len(stuck),
        "losses": losses,
        "acked": acked,
        "applied": applied,
        "quarantined": quarantined,
        "accounting_ok": accounting_ok,
        "nacks_explicit": nacks_explicit,
        "central_finite": central_finite,
        "worker_nacks": worker_nacks,
        "worker_rollbacks": {j: getattr(opts.get(j), "rollbacks_seen", 0)
                             for j in range(1, 1 + n_workers)},
        "quarantined_total": sum(srv.ps.quarantined for srv in servers),
        "nacks_sent_total": sum(srv.ps.nacks_sent for srv in servers),
        "rollbacks": coord.rollbacks_done,
        "rollbacks_abandoned": coord.rollbacks_abandoned,
        "rollback_mttr_s": (coord.rollback_mttrs[0]
                            if coord.rollback_mttrs else None),
        "revoked_workers": coord.revoked_workers,
        "chaos_lines": log.lines(),
        "chaos_counts": log.counts(),
        "events": list(coord.events),
        "stats": {srv.server_id: dict(srv.stats) for srv in servers},
        "servers": servers,
    }


def health_demo(seed: int = 0, base_dir: Optional[str] = None) -> Dict:
    """One self-contained pass of the acceptance script
    (``coord/cli.py --health``; ``bench_all --only health`` prices it)."""
    import tempfile

    base = base_dir or tempfile.mkdtemp(prefix="health_")
    out = health_scenario(base_dir=base, seed=seed)
    first = {j: round(float(np.mean(l[:4])), 3)
             for j, l in out["losses"].items()}
    last = {j: round(float(np.mean(l[-4:])), 3)
            for j, l in out["losses"].items()}
    return {
        "ok": (out["ok"] and out["rollbacks"] >= 1
               and out["quarantined_total"] > 0
               and out["revoked_workers"] >= 1),
        "rollbacks": out["rollbacks"],
        "rollback_mttr_s": out["rollback_mttr_s"],
        "quarantined": out["quarantined_total"],
        "nacks_sent": out["nacks_sent_total"],
        "worker_nacks": out["worker_nacks"],
        "revoked_workers": out["revoked_workers"],
        "first_losses": first,
        "last_losses": last,
        "chaos": out["chaos_counts"],
        "events": out["events"],
        "state_dir": base,
    }
