"""coord/ — the elastic control plane (ISSUE 3 tentpole).

DistBelief runs on a fleet whose membership and speed vary: DownPour
"tolerates variance in the processing speed of different model replicas, and
even the wholesale failure of model replicas", and Sandblaster adds a
coordinator that load-balances work and schedules backup replicas for
stragglers (PAPER.md). The chaos layer (ISSUE 2) made individual failures
survivable; this package makes the FLEET itself dynamic — every launch-time
decision (ranks, shard map, fleet size) becomes a runtime-negotiated one:

- :mod:`~.coordinator` — lease-based membership over the existing messaging
  transports (codes 13-18), elastic shard-map recomputation, Sandblaster-
  style straggler speculation, and a fleet-state export for the serving
  plane.
- :mod:`~.member` — :class:`CoordClient`, the member-side face: join/leave,
  background lease renewal carrying progress reports, shard-map / fleet /
  speculation delivery.
- :mod:`~.shardmap` — the versioned :class:`ShardMap` and its float32 wire
  encoding.
- :mod:`~.elastic` — :class:`ElasticShardServer` (a ParameterServer whose
  range is coordinator-assigned and resizable mid-run) and the elastic
  worker loop used by the acceptance tests and ``coord/cli.py``.
- :mod:`~.stages` — the MPMD pipeline plane's control side (ISSUE 10):
  the versioned :class:`StagePlacement`, :class:`StageCoordinator`
  (stage death detection, checkpoint-restart assignment with MTTR, stage
  speculation), and the ``mpmd_scenario`` acceptance machinery.
"""

from distributed_ml_pytorch_tpu.coord.shardmap import ShardEntry, ShardMap
from distributed_ml_pytorch_tpu.coord.coordinator import Coordinator, MemberInfo
from distributed_ml_pytorch_tpu.coord.member import CoordClient, FleetView

__all__ = [
    "ShardEntry",
    "ShardMap",
    "Coordinator",
    "MemberInfo",
    "CoordClient",
    "FleetView",
]
