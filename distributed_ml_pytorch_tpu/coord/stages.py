"""Stage placement — the coordinator side of the MPMD pipeline plane
(ISSUE 10 tentpole, with ``parallel/mpmd.py``).

A :class:`StagePlacement` is to pipeline stages what ``ShardMap`` is to PS
shards: the single versioned source of truth for WHICH fleet member serves
which pipeline stage, carrying per-entry the member's rank, its
INCARNATION (the restart detector: a changed incarnation means the
endpoint lost its in-flight state), the stage's contiguous flat-param
range ``[lo, hi)``, and the member's microbatch watermark (the recovery
point its checkpoint promises). It rides the tagged-float32 wire as
``MessageCode.StageAssign``.

:class:`StageCoordinator` extends the base :class:`~.coordinator.Coordinator`
with the stage lifecycle:

- stage members join with kind ``stage`` and announce which stage they
  serve via ``StageReady(stage, watermark)``; the coordinator assigns them
  into the placement, bumps its version, and broadcasts;
- a stage member silent past its lease is VACATED from the placement
  (the pipeline pauses at that stage — neighbors hold their hand-offs);
  when a replacement announces ``StageReady``, the assignment completes
  and the vacancy duration is recorded as the stage-restart MTTR;
- the placement is mirrored into the base ``shard_map`` (entries = stage
  ranges, ``server_id`` = member rank), so the existing snapshot barrier
  (``SnapshotRequest``/``SnapshotDone`` -> ``FleetManifest``) covers stage
  checkpoints without modification — a stage fleet's manifest tiles
  ``[0, n_params)`` exactly like a shard fleet's;
- Sandblaster speculation applied to stages: a straggling stage member
  (step-latency EWMA past ``straggler_factor`` x the fleet median, from
  lease renewals) gets its stage raced by an idle STANDBY member, which
  loads the victim's checkpoint and announces ``StageReady``; the
  placement flip is the first-wins dedup and the victim goes passive.

:func:`mpmd_scenario` is the acceptance machinery (the drill/demo pattern):
one in-process fleet — StageCoordinator + S stage members + a driver, the
data plane under seeded chaos + ReliableTransport — that trains, kills a
middle stage mid-schedule, restarts it from its per-stage checkpoint, and
returns everything the acceptance criteria judge (loss trajectory,
applied-microbatch accounting, chaos log, MTTR, coordinator events).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from distributed_ml_pytorch_tpu.coord.coordinator import (
    KIND_STAGE,
    Coordinator,
)
from distributed_ml_pytorch_tpu.coord.shardmap import ShardEntry, ShardMap
from distributed_ml_pytorch_tpu.utils.messaging import (
    MessageCode,
    _join16,
    _split16,
)


def encode_stage_ready(stage: int, incarnation: int,
                       watermark: int) -> np.ndarray:
    return np.asarray(
        [float(stage), *_split16(incarnation), *_split16(watermark)],
        np.float32)


@dataclasses.dataclass(frozen=True)
class StageEntry:
    """One stage's assignment: the member serving it (rank < 0 = vacant),
    that member's incarnation, the stage's flat-param range, and the
    watermark its checkpoint promises."""

    stage: int
    rank: int = -1
    inc: int = 0
    lo: int = 0
    hi: int = 0
    watermark: int = 0

    @property
    def vacant(self) -> bool:
        return self.rank < 0


@dataclasses.dataclass(frozen=True)
class StagePlacement:
    """An immutable, versioned assignment of pipeline stages to members."""

    version: int
    n_params: int
    entries: Tuple[StageEntry, ...] = ()

    def __init__(self, version: int, n_params: int,
                 entries: Sequence[StageEntry] = ()):
        object.__setattr__(self, "version", int(version))
        object.__setattr__(self, "n_params", int(n_params))
        object.__setattr__(self, "entries", tuple(entries))

    def entry_for_rank(self, rank: int) -> Optional[StageEntry]:
        for e in self.entries:
            if e.rank == int(rank):
                return e
        return None

    @property
    def assigned(self) -> bool:
        return bool(self.entries) and all(not e.vacant for e in self.entries)

    # ------------------------------------------------------------- encoding
    def encode(self) -> np.ndarray:
        head = [*_split16(self.version), float(len(self.entries)),
                *_split16(self.n_params)]
        body: List[float] = []
        for e in self.entries:
            body += [float(e.stage), float(e.rank), *_split16(e.inc),
                     *_split16(e.lo), *_split16(e.hi),
                     *_split16(e.watermark)]
        return np.asarray(head + body, np.float32)

    @classmethod
    def decode(cls, payload: np.ndarray) -> "StagePlacement":
        if payload.size < 5 or not np.isfinite(payload[:5]).all():
            raise ValueError(
                f"malformed StagePlacement frame (size {payload.size})")
        version = _join16(payload[0], payload[1])
        k = int(payload[2])
        n_params = _join16(payload[3], payload[4])
        if k < 0 or payload.size < 5 + 10 * k:
            raise ValueError(
                f"StagePlacement frame declares {k} entries but carries "
                f"{payload.size} floats")
        entries = []
        for i in range(k):
            f = payload[5 + 10 * i: 5 + 10 * (i + 1)]
            if not np.isfinite(f).all():
                raise ValueError("non-finite StagePlacement entry")
            entries.append(StageEntry(
                stage=int(f[0]), rank=int(f[1]),
                inc=_join16(f[2], f[3]), lo=_join16(f[4], f[5]),
                hi=_join16(f[6], f[7]), watermark=_join16(f[8], f[9])))
        entries.sort(key=lambda e: e.stage)
        return cls(version, n_params, entries)


def placement_deltas(old: Optional[StagePlacement], new: StagePlacement,
                     *, inc_only: bool = False) -> List[StageEntry]:
    """The entries of ``new`` whose serving member CHANGED vs ``old`` —
    the one replay-trigger predicate both consumers share
    (``MpmdStage._apply_placement`` re-ships retained hand-offs to these,
    ``MpmdDriver`` its retained data). ``inc_only`` restricts the trigger
    to INCARNATION changes: the driver bursts everything up front and
    never ships into a vacancy, so a vacant->same-life re-admission has
    nothing of its to replay (and a gratuitous re-ship would perturb the
    chaos log's faulted burst channels); stage members DO hold hand-offs
    across a vacancy, so they also fire on rank transitions."""
    if old is None:
        return []  # first sight: nothing retained yet, nothing to replay
    out = []
    for e in new.entries:
        if e.rank < 0 or e.stage >= len(old.entries):
            continue
        oe = old.entries[e.stage]
        if oe.inc == e.inc and (inc_only or oe.rank == e.rank):
            continue
        out.append(e)
    return out


class StageCoordinator(Coordinator):
    """The coordinator of an MPMD pipeline fleet (see module docstring)."""

    def __init__(self, transport, stage_ranges: Sequence[Tuple[int, int]],
                 *, straggler_factor: float = 0.0,
                 straggler_after_steps: int = 3, **kwargs):
        ranges = [(int(lo), int(hi)) for lo, hi in stage_ranges]
        if not ranges:
            raise ValueError("stage_ranges must name at least one stage")
        kwargs.setdefault("speculation", False)  # worker-plane speculation off
        super().__init__(transport, ranges[-1][1], **kwargs)
        self.stage_ranges = ranges
        self.n_stages = len(ranges)
        self.placement = StagePlacement(0, self.shard_map.n_params, [
            StageEntry(stage=s, lo=lo, hi=hi)
            for s, (lo, hi) in enumerate(ranges)])
        self.stage_straggler_factor = float(straggler_factor)
        self.stage_straggler_after = int(straggler_after_steps)
        self.stage_speculated: Dict[int, int] = {}  # victim rank -> task id
        self._vacant_since: Dict[int, float] = {}
        self.stage_mttrs = collections.deque(maxlen=256)  # per-death ring
        self.stage_restarts = 0

    # ------------------------------------------------------------ placement
    def _set_entry(self, entry: StageEntry, why: str) -> None:
        entries = list(self.placement.entries)
        entries[entry.stage] = entry
        self.placement = StagePlacement(
            self.placement.version + 1, self.placement.n_params, entries)
        self._mirror_shard_map()
        if self._snap is not None:
            self._log(
                f"snapshot {self._snap['id']} aborted: stage placement "
                f"moved to v{self.placement.version} mid-barrier")
            self._snap = None
        self._log(
            f"stage placement v{self.placement.version} on {why}: "
            + ", ".join(
                (f"s{e.stage}=r{e.rank}@{e.watermark}" if not e.vacant
                 else f"s{e.stage}=VACANT")
                for e in self.placement.entries))
        self._announce()

    def _mirror_shard_map(self) -> None:
        """The placement IS the stage fleet's shard map: stage ranges keyed
        by member rank, so the base snapshot barrier and FleetManifest
        machinery cover stage checkpoints unchanged."""
        self.shard_map = ShardMap(
            self.placement.version, self.placement.n_params,
            [ShardEntry(e.rank, e.lo, e.hi)
             for e in self.placement.entries if not e.vacant])

    def _announce(self) -> None:
        super()._announce()
        if self.placement.version > 0:
            self._broadcast(MessageCode.StageAssign, self.placement.encode())

    # --------------------------------------------------------------- handle
    def handle(self, sender: int, code: MessageCode, payload) -> None:
        if code == MessageCode.StageReady and payload.size >= 5:
            if not np.isfinite(payload[:5]).all():
                return
            member = self.members.get(sender)
            if member is None or member.kind != KIND_STAGE:
                return  # pre-join chatter: the member's retry self-heals
            member.last_seen = self._clock()
            self._on_stage_ready(
                sender, member,
                stage=int(payload[0]),
                inc=_join16(payload[1], payload[2]),
                watermark=_join16(payload[3], payload[4]))
            return
        super().handle(sender, code, payload)
        if (code == MessageCode.CoordJoin and sender in self.members
                and self.placement.version > 0):
            # joiners (and idempotent re-joins) get the current placement
            # directly — the broadcast in _announce only covers fleet-wide
            # membership events
            self._send(sender, MessageCode.StageAssign,
                       self.placement.encode())

    def _on_stage_ready(self, sender: int, member, *, stage: int, inc: int,
                        watermark: int) -> None:
        if not (0 <= stage < self.n_stages):
            self._log(f"ignored StageReady for unknown stage {stage} "
                      f"from rank {sender}")
            return
        if inc < member.incarnation:
            self._log(f"ignored stale StageReady from rank {sender} "
                      f"(inc {inc} < {member.incarnation})")
            return
        cur = self.placement.entries[stage]
        if cur.rank == sender and cur.inc == member.incarnation:
            # idempotent re-announce from the SAME life: answer the sender
            # alone, no bump — and the entry's watermark stays the life's
            # FIRST announcement (its recovery point: the replay boundary
            # neighbors honor and the accounting cutoff), not the member's
            # advancing progress
            self._send(sender, MessageCode.StageAssign,
                       self.placement.encode())
            return
        lo, hi = self.stage_ranges[stage]
        takeover = not cur.vacant and cur.rank != sender
        same_life = cur.vacant and cur.inc == member.incarnation
        vacated_at = self._vacant_since.pop(stage, None)
        if same_life:
            # transient lease expiry of a life that never died: nothing was
            # lost, neighbors need no replay — re-admit at the entry's
            # ORIGINAL recovery point (not the member's advancing progress)
            # and count no restart
            entry = StageEntry(stage=stage, rank=sender,
                               inc=member.incarnation, lo=lo, hi=hi,
                               watermark=cur.watermark)
            self._set_entry(
                entry, f"re-admission of rank {sender} after transient "
                       "lease expiry (same life)")
            return
        entry = StageEntry(stage=stage, rank=sender,
                           inc=member.incarnation, lo=lo, hi=hi,
                           watermark=watermark)
        why = (f"StageReady from rank {sender} (watermark {watermark})"
               + (" — TAKEOVER" if takeover else ""))
        if vacated_at is not None:
            mttr = self._clock() - vacated_at
            self.stage_mttrs.append(mttr)
            self.stage_restarts += 1
            self._log(
                f"stage {stage} restored by rank {sender} after "
                f"{mttr * 1e3:.0f} ms vacancy (watermark {watermark}: "
                f"neighbors replay in-flight microbatches past it)")
            # stage-death MTTR ships with its timeline (ISSUE 12)
            self._flight_dump(f"stage{stage}-restored")
        self._set_entry(entry, why)

    # ----------------------------------------------------------------- tick
    def tick(self) -> bool:
        changed = super().tick()
        self._sync_placement()
        if self.stage_straggler_factor > 0:
            self.check_stage_stragglers()
        return changed

    def _sync_placement(self) -> None:
        """Vacate placement entries whose member is gone (lease expiry or
        leave) — the stage-death detection path."""
        live = {m.rank for m in self._live(KIND_STAGE)}
        now = self._clock()
        for e in self.placement.entries:
            if e.vacant or e.rank in live:
                continue
            self._vacant_since.setdefault(e.stage, now)
            self.stage_speculated.pop(e.rank, None)
            # inc + watermark survive the vacancy: a SAME-life re-admission
            # (transient lease expiry, nothing lost) is told apart from a
            # replacement by comparing incarnations at the next StageReady
            self._set_entry(
                StageEntry(stage=e.stage, inc=e.inc, lo=e.lo, hi=e.hi,
                           watermark=e.watermark),
                f"death of stage {e.stage} member rank {e.rank}")

    # ------------------------------------------------------ snapshot barrier
    def _start_snapshot(self, now: float) -> None:
        """Stage fleets snapshot like shard fleets, but only a FULLY
        assigned placement can produce a manifest that tiles — a vacancy
        means the barrier cannot complete consistently."""
        if self._snap is not None:
            self._log(
                f"snapshot request ignored: snapshot {self._snap['id']} "
                "still in flight")
            return
        if not self.placement.assigned:
            self._log("snapshot request ignored: stage placement has "
                      "vacancies")
            return
        stages = self._live(KIND_STAGE)
        assigned = {e.rank for e in self.placement.entries}
        members = [m for m in stages if m.rank in assigned]
        if len(members) < self.n_stages:
            self._log("snapshot request ignored: assigned stage members "
                      "not all live")
            return
        self._snap_seq += 1
        self._snap = {
            "id": self._snap_seq,
            "map_version": self.shard_map.version,
            "expected": {m.rank for m in members},
            "got": {},
            "started": now,
        }
        self._log(
            f"snapshot {self._snap_seq} started: placement "
            f"v{self.shard_map.version}, awaiting "
            f"{sorted(self._snap['expected'])}")
        from distributed_ml_pytorch_tpu.coord.coordinator import (
            encode_snapshot_request,
        )

        frame = encode_snapshot_request(self._snap_seq,
                                        self.shard_map.version)
        for m in members:
            self._send(m.rank, MessageCode.SnapshotRequest, frame)

    # distcheck: ignore[DC205] constructor-time durability restore — the
    # base Coordinator contract (which carries the same suppression);
    # overridden HERE so the finding the analyzer anchors on this subclass
    # has a local line to suppress.
    def _init_durable(self) -> None:
        super()._init_durable()

    # distcheck: ignore[DC205] WAL replay is constructor-time and
    # single-threaded; the live paths mutate on the serve thread only,
    # after logging (same contract as the base method).
    def _apply_wal_op(self, op: dict, now: float) -> None:
        super()._apply_wal_op(op, now)

    # ---------------------------------------------------------- speculation
    def check_stage_stragglers(self) -> Optional[int]:
        """Sandblaster speculation for stages: when an assigned stage
        member's step-latency EWMA exceeds ``straggler_factor`` x the
        fleet median, point an idle standby member at it (SpeculateTask);
        the standby loads the victim's checkpoint and races it for the
        stage — the placement flip is the first-wins dedup."""
        assigned = {e.rank: e for e in self.placement.entries if not e.vacant}
        members = [m for m in self._live(KIND_STAGE)
                   if m.rank in assigned and m.ewma_ms > 0
                   and m.step >= self.stage_straggler_after
                   and m.rank not in self.stage_speculated]
        if len(members) < 2:
            return None
        standbys = [m for m in self._live(KIND_STAGE)
                    if m.rank not in assigned]
        if not standbys:
            return None
        by_speed = sorted(members, key=lambda m: m.ewma_ms)
        victim = by_speed[-1]
        median = by_speed[(len(by_speed) - 1) // 2].ewma_ms
        if median <= 0 or victim.ewma_ms < self.stage_straggler_factor * median:
            return None
        backup = standbys[0]
        task_id = self._next_task
        self._next_task += 1
        self.stage_speculated[victim.rank] = task_id
        e = assigned[victim.rank]
        self._log(
            f"stage straggler: stage {e.stage} member rank {victim.rank} "
            f"at {victim.ewma_ms:.1f} ms/step (median {median:.1f}) — "
            f"standby rank {backup.rank} races it as task {task_id}")
        frame = np.asarray(
            [float(task_id), float(victim.rank), float(victim.step)],
            np.float32)
        self._send(backup.rank, MessageCode.SpeculateTask, frame)
        self._send(victim.rank, MessageCode.SpeculateTask, frame)
        return task_id

    # distcheck: ignore[DC205] serve-thread only: the sole caller is
    # GrayHealth._enter_probation, reached from gray.tick() inside this
    # coordinator's own run loop (same thread as check_stragglers); the
    # override anchors the inherited method in this file for distcheck.
    def speculate_victim(self, victim_rank: int) -> Optional[int]:
        return super().speculate_victim(victim_rank)


# ---------------------------------------------------------------- scenario

def _wait_for(predicate, timeout: float, what: str, poll: float = 0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(poll)
    raise TimeoutError(f"mpmd: timed out after {timeout:.0f}s waiting for "
                       f"{what}")


def _load_factor(nominal: float = 0.002, rounds: int = 5) -> float:
    """Measured clock-tick inflation on this host: how much longer a
    nominal sleep actually takes right now. A quiet host returns ~1; a
    1-core host running the rest of the suite returns several-x. Scenario
    barrier timeouts scale by this so a loaded run gets proportionally
    more wall clock instead of flaking — the timeout stays a real bound
    (capped), it just prices the observed scheduling latency in."""
    worst = 1.0
    for _ in range(rounds):
        t0 = time.monotonic()
        time.sleep(nominal)
        worst = max(worst, (time.monotonic() - t0) / nominal)
    return min(worst, 5.0)


def default_mpmd_plan(seed: int = 0, *, weather: bool = True):
    """Seeded drop/dup + network weather on the DRIVER'S burst channels
    (tokens -> stage 0, targets -> last stage, reliability envelope code).

    Determinism contract: the driver ships every step's data up front, so
    these channels' send sequences are pure functions of the dataset; with
    the scenario's RTO floor far above the in-process RTT, retransmissions
    are loss-driven (seeded) rather than timing-driven, and the chaos log
    renders byte-identically across repeats. Channels touching the killed
    stage are deliberately un-faulted — their retry counts during the
    outage are wall-clock-dependent.
    """
    from distributed_ml_pytorch_tpu.utils.chaos import (
        ChaosPlan,
        FaultRule,
        WeatherRule,
    )

    rules = [FaultRule(src=0, code=int(MessageCode.ReliableFrame),
                       drop=0.05, dup=0.05)]
    weather_rules = ()
    if weather:
        weather_rules = (WeatherRule(
            src=0, code=int(MessageCode.ReliableFrame),
            latency=0.005, jitter=0.002),)
    return ChaosPlan(rules, seed=seed, weather=weather_rules)


def mpmd_scenario(
    *,
    base_dir: str,
    seed: int = 0,
    steps: int = 8,
    n_stages: int = 4,
    n_microbatches: int = 4,
    mb: int = 4,
    seq: int = 8,
    lr: float = 0.1,
    lease: float = 0.5,
    kill_stage: Optional[int] = None,
    kill_at_step: Optional[int] = None,
    snapshot_at_step: Optional[int] = None,
    restore_via_manifest: bool = False,
    plan=None,
    throttle_stage: Optional[int] = None,
    throttle: float = 0.0,
    standby: bool = False,
    straggler_factor: float = 0.0,
    cfg=None,
    timeout: float = 240.0,
    act_codec: str = "dense",
) -> Dict:
    """Run one MPMD pipeline fleet script (see module docstring).

    Rank layout: stage ``i`` is rank ``1 + i`` in BOTH the coordination
    star and the data-plane world; an optional standby member is rank
    ``n_stages + 1`` in both (placement-routed members MUST share one
    rank across worlds); the driver is data rank 0 (the hub the chaos
    plan's ``src=0`` rules match) and takes the next free coord rank
    (``n_stages + 1``, or ``n_stages + 2`` with a standby). ``kill_stage``/``kill_at_step`` crash that stage
    member SILENTLY from its own step hook the moment it finishes the
    named update (its checkpoint watermark is then exactly
    ``kill_at_step * M`` — the deterministic replay boundary); the main
    thread restarts it (from its per-stage checkpoint, via the
    FleetManifest when ``restore_via_manifest``) once the coordinator has
    detected the death and vacated the stage.
    """
    import os

    from distributed_ml_pytorch_tpu.coord.manifest import (
        MANIFEST_NAME,
        FleetManifest,
    )
    from distributed_ml_pytorch_tpu.coord.member import CoordClient
    from distributed_ml_pytorch_tpu.parallel.mpmd import (
        MpmdDriver,
        MpmdStage,
        stage_param_ranges,
    )
    from distributed_ml_pytorch_tpu.parallel.pipeline import PipelineLMConfig
    from distributed_ml_pytorch_tpu.parallel.seq_parallel import (
        next_token_targets,
    )
    from distributed_ml_pytorch_tpu.utils.chaos import FaultyTransport
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        ReliableTransport,
    )

    S, M = int(n_stages), int(n_microbatches)
    if cfg is None:
        cfg = PipelineLMConfig(
            vocab_size=32, d_model=16, n_heads=2, n_layers=S, d_ff=32,
            max_len=max(64, seq))
    ranges = stage_param_ranges(cfg, S)
    n_extra = 1 if standby else 0
    # placement-routed members (stages, standby) MUST hold the SAME rank in
    # the coordination star and the data world — the placement's rank is
    # both identities. The driver is never in the placement, so its coord
    # rank floats to whatever is free.
    standby_rank = S + 1  # data AND coord rank
    driver_coord_rank = S + 1 + n_extra

    # --- data: every run of one seed feeds identical microbatches ---------
    rng = np.random.default_rng(seed)
    tokens_steps, targets_steps = [], []
    for _t in range(steps):
        toks = rng.integers(0, cfg.vocab_size, size=(M * mb, seq)).astype(
            np.int32)
        tgts = next_token_targets(toks)
        tokens_steps.append(toks.reshape(M, mb, seq))
        targets_steps.append(np.asarray(tgts).reshape(M, mb, seq))

    # --- worlds: plain coordination star + chaos-wrapped data plane -------
    coord_world = InProcessTransport.create_world(2 + S + n_extra)
    data_world = InProcessTransport.create_world(1 + S + n_extra)
    log = None
    if plan is not None:
        from distributed_ml_pytorch_tpu.utils.chaos import ChaosLog

        log = ChaosLog()
        data_world, _ = FaultyTransport.wrap_world(data_world, plan, log=log)

    #: RTO floor far above the in-process RTT + weather — AND above a jit
    #: compile stall, which starves a stage's serve loop for seconds on a
    #: cold program cache — so retransmits are loss-driven, hence seeded
    #: and deterministic (the byte-identical-log contract; the acceptance
    #: test additionally warms the program cache with its corridor run
    #: first). breaker_grace keeps a compile stall from reading as a dead
    #: peer, the same knob the health world runs.
    rel_opts = dict(ack_timeout=4.0, max_backoff=8.0, max_retries=120,
                    send_window=32, breaker_grace=60.0)

    def rel(rank: int) -> ReliableTransport:
        return ReliableTransport(data_world[rank], **rel_opts)

    # --- flight recorders (ISSUE 12): one per member, dumped into
    # base_dir/obs on stop/death so `analysis timeline` can merge them.
    # Purely observational — the 3x byte-identical chaos-log acceptance
    # runs WITH these on (the recorder-determinism guard).
    from distributed_ml_pytorch_tpu.utils import obs as _obs

    obs_dir = os.path.join(base_dir, "obs")

    def make_recorder(member: str, transport) -> "_obs.SpanRecorder":
        rec = _obs.SpanRecorder(member, "mpmd")
        if hasattr(transport, "recorder"):
            transport.recorder = rec  # wire-blocked / retransmit spans
        return rec

    coord = StageCoordinator(
        coord_world[0], ranges, lease=lease,
        manifest_dir=base_dir, straggler_factor=straggler_factor)
    coord.recorder = _obs.SpanRecorder("coord", "coord")
    coord.obs_dir = obs_dir
    coord_thread = threading.Thread(
        target=coord.run, kwargs={"timeout": timeout + 60}, daemon=True)
    coord_thread.start()

    crash_evt = threading.Event()
    victim_holder: Dict[str, MpmdStage] = {}
    retired: List[MpmdStage] = []
    errors: List[tuple] = []
    timings: Dict[str, float] = {}
    manifest_path = os.path.join(base_dir, MANIFEST_NAME)

    def make_stage(i: int, transport) -> MpmdStage:
        client = CoordClient(coord_world[1 + i], "stage",
                             renew_interval=lease / 4)

        def hook(srv: MpmdStage, new_step: int) -> None:
            if (kill_stage == i and kill_at_step is not None
                    and new_step == kill_at_step and not crash_evt.is_set()):
                timings["killed"] = time.monotonic()
                srv.crash()
                if hasattr(data_world[1 + i], "crash"):
                    data_world[1 + i].crash()
                crash_evt.set()

        return MpmdStage(
            i, cfg, S, M, transport, client,
            mb_size=mb, seq_len=seq, lr=lr, seed=seed,
            ckpt_dir=os.path.join(base_dir, f"stage{i}"),
            throttle=(throttle if throttle_stage == i else 0.0),
            step_hook=hook,
            recorder=make_recorder(f"stage{i}", transport),
            obs_dir=obs_dir, act_codec=act_codec)

    stages: List[MpmdStage] = []
    stage_threads: List[threading.Thread] = []
    for i in range(S):
        srv = make_stage(i, rel(1 + i))
        stages.append(srv)
        t = threading.Thread(target=srv.run, kwargs={"timeout": timeout + 60},
                             daemon=True)
        t.start()
        stage_threads.append(t)

    standby_member = None
    if standby:
        client = CoordClient(coord_world[standby_rank], "stage",
                             renew_interval=lease / 4)
        standby_transport = rel(standby_rank)
        standby_member = MpmdStage(
            None, cfg, S, M, standby_transport, client,
            mb_size=mb, seq_len=seq, lr=lr, seed=seed, ckpt_root=base_dir,
            recorder=make_recorder("standby", standby_transport),
            obs_dir=obs_dir, act_codec=act_codec)
        t = threading.Thread(target=standby_member.run,
                             kwargs={"timeout": timeout + 60}, daemon=True)
        t.start()
        stage_threads.append(t)

    # --- restart watcher: once the coordinator vacates the killed stage,
    # stand the replacement up from its checkpoint --------------------------
    def restart_victim() -> None:
        try:
            crash_evt.wait(timeout)
            if kill_stage is None or not crash_evt.is_set():
                return
            _wait_for(
                lambda: coord.placement.entries[kill_stage].vacant,
                timeout, f"the coordinator to vacate stage {kill_stage}")
            timings["vacated"] = time.monotonic()
            old = stages[kill_stage]
            retired.append(old)
            detach = getattr(old.transport, "detach", None)
            if detach is not None:
                detach()
            if hasattr(data_world[1 + kill_stage], "restart"):
                data_world[1 + kill_stage].restart()
            srv = make_stage(kill_stage, rel(1 + kill_stage))
            manifest = None
            if restore_via_manifest:
                manifest = FleetManifest.load(manifest_path)
            srv.restore(manifest=manifest)
            stages[kill_stage] = srv
            victim_holder["new"] = srv
            timings["restored"] = time.monotonic()
            t = threading.Thread(target=srv.run,
                                 kwargs={"timeout": timeout + 60},
                                 daemon=True)
            t.start()
            stage_threads.append(t)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            errors.append(("restart", repr(e)))
            crash_evt.set()

    restarter = None
    if kill_stage is not None:
        restarter = threading.Thread(target=restart_victim, daemon=True)
        restarter.start()

    # --- driver -----------------------------------------------------------
    driver_client = CoordClient(coord_world[driver_coord_rank], "worker",
                                renew_interval=lease / 4)
    driver_transport = rel(0)
    driver = MpmdDriver(driver_transport, driver_client, S, M,
                        recorder=make_recorder("driver", driver_transport),
                        obs_dir=obs_dir)

    barrier_timeout = 60 * _load_factor()

    def driver_hook(t: int, _loss: float) -> None:
        if snapshot_at_step is not None and t == snapshot_at_step:
            coord.trigger_snapshot()

            def manifest_published() -> bool:
                if coord.manifests_written > 0 \
                        and os.path.exists(manifest_path):
                    return True
                # the trigger flag is consumed even when the barrier
                # can't start (a transient lease vacancy on a loaded
                # host drops the request on the floor) — re-arm it;
                # a barrier already in flight ignores the re-trigger,
                # and snapshot control frames don't traverse the
                # chaos-wrapped burst channels, so the seeded chaos
                # log stays byte-identical
                coord.trigger_snapshot()
                return False

            _wait_for(
                manifest_published, barrier_timeout,
                "the stage snapshot barrier to publish a manifest")

    losses: List[float] = []
    try:
        losses = driver.run(tokens_steps, targets_steps, timeout=timeout,
                            step_hook=driver_hook)
        # the driver has every step's LOSS once the last stage finishes,
        # but earlier stages' backward chains for the final step are still
        # draining — wait for every active member to apply its last update
        # so the accounting below judges a completed schedule

        def drained() -> bool:
            active = [s for s in stages if not s._superseded]
            if standby_member is not None and standby_member.stage is not None:
                active.append(standby_member)
            return all(s.step >= steps for s in active)

        _wait_for(drained, barrier_timeout,
                  "all stages to drain their final backwards")
    except TimeoutError as e:
        errors.append(("driver", repr(e)))
    finally:
        driver_client.close()

    for srv in stages:
        srv.stop()
    if standby_member is not None:
        standby_member.stop()
    coord.stop()
    coord_thread.join(timeout=30)
    if restarter is not None:
        crash_evt.set()
        restarter.join(timeout=10)
    for t in stage_threads:
        t.join(timeout=10)

    # serve-loop crashes are first-class failures (MpmdStage.run records
    # them instead of dying silently)
    for srv in stages + retired \
            + ([standby_member] if standby_member is not None else []):
        if srv.error is not None:
            errors.append((f"stage{srv.stage}", srv.error))

    # --- accounting: every (step, mb) applied exactly once per stage, in
    # the OWNER LINEAGE — prior lives count below the final owner's
    # announced watermark, the owner above it. A speculation loser's
    # racing applications past the takeover watermark are DISCARDED work
    # (Sandblaster's first-wins contract: its ships were suppressed, its
    # params abandoned), counted separately, never double-counted. -------
    import collections

    applied_ok = True
    discarded_applies = 0
    applied: Dict[int, Dict[Tuple[int, int], int]] = {}
    all_members = list(stages) + retired \
        + ([standby_member] if standby_member is not None else [])
    for i in range(S):
        entry = coord.placement.entries[i]
        cutoff = entry.watermark
        if (standby_member is not None and standby_member.stage == i
                and not standby_member._superseded):
            owner = standby_member
        elif not stages[i]._superseded:
            owner = stages[i]
        else:
            owner = None
        counts: collections.Counter = collections.Counter()
        for srv in all_members:
            if srv.stage != i:
                continue
            for key in srv.applied_log:
                g = key[0] * M + key[1]
                if (g >= cutoff) == (srv is owner):
                    counts[key] += 1
                else:
                    discarded_applies += 1
        applied[i] = dict(counts)
        expected = {(t, mbi) for t in range(steps) for mbi in range(M)}
        if set(counts) != expected or any(v != 1 for v in counts.values()):
            applied_ok = False

    stats = {f"stage{i}": dict(stages[i].stats) for i in range(S)}
    for k, srv in enumerate(retired):
        stats[f"retired{k}"] = dict(srv.stats)
    if standby_member is not None:
        stats["standby"] = dict(standby_member.stats)
    busy_s = sum(s.get("busy_s", 0.0) for s in stats.values())
    wall_s = (driver.step_times[-1] - driver.step_times[0]
              if len(driver.step_times) >= 2 else None)

    # close the RELIABLE wrappers too (they own retry threads — a zombie
    # wrapper from a finished run keeps retrying into a closed world and
    # eventually logs spurious breaker opens), then the worlds beneath
    wrappers = [driver.transport] + [srv.transport for srv in stages]
    wrappers += [srv.transport for srv in retired]
    if standby_member is not None:
        wrappers.append(standby_member.transport)
    for t in wrappers:
        close = getattr(t, "close", None)
        if close is not None:
            close()
    for t in data_world.values():
        close = getattr(t, "close", None)
        if close is not None:
            close()
    for t in coord_world.values():
        t.close()

    # final black-box write: the coordinator's decision timeline joins the
    # members' dumps so `analysis timeline` sees the whole fleet
    _obs.flight_dump(coord.recorder, obs_dir, "stop")

    mttr = coord.stage_mttrs[0] if coord.stage_mttrs else None
    return {
        "ok": not errors and len(losses) == steps and applied_ok,
        "obs_dir": obs_dir,
        "errors": errors,
        "losses": losses,
        "step_times": list(driver.step_times),
        "applied_ok": applied_ok,
        "applied": applied,
        "discarded_applies": discarded_applies,
        "stats": stats,
        "driver_stats": dict(driver.stats),
        "events": list(coord.events),
        "placement_version": coord.placement.version,
        "placement": coord.placement,
        "stage_mttr_s": mttr,
        "stage_restarts": coord.stage_restarts,
        #: wall-clock decomposition of the outage: killed -> vacated
        #: (lease expiry detection) -> restored (replacement serving)
        "timings": dict(timings),
        "chaos_lines": log.lines() if log is not None else "",
        "chaos_counts": log.counts() if log is not None else {},
        "busy_s": busy_s,
        "wall_s": wall_s,
        "stages": stages,
        "retired": retired,
        "standby": standby_member,
        "coordinator": coord,
    }


def mpmd_demo(seed: int = 0, base_dir: Optional[str] = None) -> Dict:
    """One self-contained pass of the MPMD acceptance script
    (``coord/cli.py --mpmd``): 4 stages under drop/dup + weather, the
    middle stage killed mid-schedule and restarted from its checkpoint."""
    import tempfile

    base = base_dir or tempfile.mkdtemp(prefix="mpmd_")
    out = mpmd_scenario(
        base_dir=base, seed=seed, steps=8, kill_stage=1, kill_at_step=3,
        snapshot_at_step=1, plan=default_mpmd_plan(seed))
    return {
        "ok": out["ok"] and out["stage_restarts"] >= 1,
        "losses": [round(float(x), 4) for x in out["losses"]],
        "stage_mttr_ms": (None if out["stage_mttr_s"] is None
                          else round(out["stage_mttr_s"] * 1e3, 1)),
        "applied_ok": out["applied_ok"],
        "chaos": out["chaos_counts"],
        "events": out["events"],
        "state_dir": base,
    }
