"""The elastic acceptance scenario as reusable in-process machinery.

One function, :func:`elastic_scenario`, stands up the full control plane in
one process — coordinator + N elastic shard servers + M DownPour workers,
every data-plane world optionally wrapped in the chaos layer — and runs the
ISSUE 3 script: workers train, a late worker may JOIN mid-run, a shard
server may be CRASHED mid-run (silent death: its lease expires, the
coordinator rebalances, the survivors resize and the workers cut over), and
training runs to completion. It returns everything the acceptance criteria
judge: per-worker loss curves, the coordinator's decision log, per-server
stats, and the final shard-map version.

``tests/test_coord.py`` drives it three times with identical seeds for the
fault-free-corridor check; ``coord/cli.py --demo`` runs it once as a
self-contained demo; ``bench_all.py elastic_phase()`` times its steady
state before/during/after the rebalance.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from distributed_ml_pytorch_tpu.coord.coordinator import Coordinator
from distributed_ml_pytorch_tpu.coord.elastic import ElasticShardServer
from distributed_ml_pytorch_tpu.coord.member import CoordClient
from distributed_ml_pytorch_tpu.utils.messaging import InProcessTransport

#: coordinator-world rank layout: rank 0 is the coordinator, shard server i
#: is rank 1+i, worker j (1-based) is rank 1+n_shards+j-1
def _shard_rank(i: int) -> int:
    return 1 + i


def _worker_rank(n_shards: int, j: int) -> int:
    return n_shards + j


class ElasticWorld:
    """All the transports of one in-process elastic fleet.

    Shard server ``i`` owns PS star world ``i`` (it is rank 0 there; worker
    ``j`` is rank ``j``); everyone holds a rank in the coordination world.
    Worlds are sized for ``max_workers`` up front so late joiners have
    mailboxes (and chaos wrappers) from the start — elasticity of the
    MEMBERSHIP, not of the queue allocation.
    """

    def __init__(self, n_shards: int, max_workers: int,
                 plan=None, log=None):
        from distributed_ml_pytorch_tpu.utils.chaos import (
            ChaosLog,
            FaultyTransport,
        )

        self.n_shards = n_shards
        self.max_workers = max_workers
        self.coord_world = InProcessTransport.create_world(
            1 + n_shards + max_workers)
        self.shard_worlds = []
        self.log = log
        if plan is not None and log is None:
            self.log = ChaosLog()
        for _i in range(n_shards):
            world = InProcessTransport.create_world(1 + max_workers)
            if plan is not None:
                world, _ = FaultyTransport.wrap_world(world, plan, log=self.log)
            self.shard_worlds.append(world)

    def worker_factory(self, j: int):
        """The worker-side transport factory: shard-map entries name the
        server's coordinator rank; resolve it to this worker's transport in
        that server's PS world."""
        def factory(entry):
            return self.shard_worlds[entry.server_id - 1][j]

        return factory

    def close(self) -> None:
        for world in self.shard_worlds:
            for t in world.values():
                t.close()
        for t in self.coord_world.values():
            t.close()


def elastic_scenario(
    *,
    seed: int = 0,
    steps: int = 16,
    n_workers: int = 2,
    n_shards: int = 2,
    join_worker_at: Optional[int] = None,
    join_worker_steps: int = 8,
    crash_shard_at: Optional[int] = None,
    plan=None,
    lease: float = 0.6,
    lr: float = 0.05,
    n_push: int = 2,
    n_pull: int = 2,
    batch: int = 16,
    slow_worker: Optional[int] = None,
    slow_factor: float = 0.0,
    step_sleep: float = 0.0,
    speculation: bool = False,
    fixture=None,
    step_hook=None,
) -> Dict:
    """Run the elastic script (see module docstring). Returns a summary
    dict: ``losses`` per worker, ``events`` (coordinator log), ``stats``
    per server, ``map_version``, ``ok``.

    ``join_worker_at`` / ``crash_shard_at`` are step indices of worker 1's
    loop at which the extra worker joins / shard server ``n_shards - 1`` is
    silently crashed. ``fixture`` may supply ``(x, y, grad_fn, params0)``
    (the tests share a module-scoped jitted one); otherwise a LeNet set is
    built here.
    """
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.parallel.sharded_ps import (
        ShardedAsynchronous,
    )
    from distributed_ml_pytorch_tpu.utils.serialization import (
        ravel_model_params,
    )

    if fixture is not None:
        x, y, grad_fn, params0 = fixture
    else:
        x, y, grad_fn, params0 = _default_fixture(seed)
    flat0 = np.asarray(ravel_model_params(params0), np.float32)
    n_params = int(flat0.shape[0])

    max_workers = n_workers + (1 if join_worker_at is not None else 0)
    world = ElasticWorld(n_shards, max_workers, plan=plan)
    coord = Coordinator(
        world.coord_world[0], n_params, lease=lease,
        speculation=speculation)
    coord_thread = threading.Thread(
        target=coord.run, kwargs={"timeout": 300}, daemon=True)
    coord_thread.start()

    servers, server_threads = [], []
    for i in range(n_shards):
        client = CoordClient(
            world.coord_world[_shard_rank(i)], "shard",
            renew_interval=lease / 4)
        srv = ElasticShardServer(
            server_id=_shard_rank(i), n_params=n_params,
            transport=world.shard_worlds[i][0], coord=client,
            init_params=flat0)
        servers.append(srv)
        t = threading.Thread(target=srv.run, kwargs={"timeout": 300},
                             daemon=True)
        t.start()
        server_threads.append(t)

    losses: Dict[int, list] = {}
    final_versions: Dict[int, int] = {}
    spec_tasks: Dict[int, list] = {}
    join_evt = threading.Event()
    crash_evt = threading.Event()
    errors: list = []

    def run_worker(j: int, my_steps: int, rejoin: bool) -> None:
        try:
            _run_worker(j, my_steps, rejoin)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            errors.append((j, repr(e)))

    def _run_worker(j: int, my_steps: int, rejoin: bool) -> None:
        tasks: list = []
        spec_tasks[j] = tasks
        client = CoordClient(
            world.coord_world[_worker_rank(n_shards, j)], "worker",
            renew_interval=lease / 4,
            on_speculate=lambda tid, victim, frm: tasks.append(
                (tid, victim, frm)))
        m = client.join(timeout=30)
        assert m is not None and m.entries, "worker never got a shard map"
        factory = world.worker_factory(j)
        params = jax.tree.map(jnp.asarray, params0)
        opt = ShardedAsynchronous(
            params, lr=lr, n_push=n_push, n_pull=n_pull,
            transports=[factory(e) for e in m.entries],
            coord=client, transport_factory=factory, shard_map=m,
            rejoin=rejoin)
        rng = jax.random.key(100 + j)
        my_losses = losses.setdefault(j, [])
        for step in range(my_steps):
            sel = np.random.default_rng(j * 1000 + step).integers(
                0, len(x), batch)
            loss, grads = grad_fn(params, x[sel], y[sel],
                                  jax.random.fold_in(rng, step))
            # progress (step EWMA incl. the scripted sleep below) reports
            # itself: ShardedAsynchronous.step feeds the coord client
            params = opt.step(params, grads)
            my_losses.append(float(loss))
            if step_sleep > 0:
                # pace the loop so lease-clock events (crash detection,
                # rebalance broadcast) land while training is still RUNNING
                # — the acceptance property is continuation, not survival
                time.sleep(step_sleep)
            if slow_worker == j and slow_factor > 0:
                time.sleep(slow_factor)
            if step_hook is not None:
                step_hook(j, step, opt)
            if j == 1:
                if join_worker_at is not None and step == join_worker_at:
                    join_evt.set()
                if crash_shard_at is not None and step == crash_shard_at:
                    crash_evt.set()
        final_versions[j] = opt.map_version
        opt.finish()
        client.close()

    worker_threads = [
        threading.Thread(target=run_worker, args=(j, steps, False),
                         daemon=True)
        for j in range(1, n_workers + 1)
    ]
    for t in worker_threads:
        t.start()

    if join_worker_at is not None:
        join_evt.wait(timeout=120)
        jt = threading.Thread(
            target=run_worker,
            args=(max_workers, join_worker_steps, True), daemon=True)
        jt.start()
        worker_threads.append(jt)

    if crash_shard_at is not None:
        crash_evt.wait(timeout=120)
        victim = servers[n_shards - 1]
        # a SILENT crash: the serve loop dies and the lease renewals stop,
        # but no CoordLeave is sent — the coordinator must *detect* it
        victim.crash()
        if hasattr(world.shard_worlds[n_shards - 1][0], "crash"):
            world.shard_worlds[n_shards - 1][0].crash()

    for t in worker_threads:
        t.join(timeout=300)
    alive = [t for t in worker_threads if t.is_alive()]
    for srv in servers:
        srv.stop()
    for t in server_threads:
        t.join(timeout=30)
    coord.stop()
    coord_thread.join(timeout=30)
    world.close()

    return {
        "ok": not alive and not errors,
        "errors": errors,
        "stuck_workers": len(alive),
        "losses": losses,
        "worker_map_versions": final_versions,
        "events": list(coord.events),
        "stats": {srv.server_id: dict(srv.stats) for srv in servers},
        "spec_tasks": spec_tasks,
        "map_version": coord.shard_map.version,
        "final_map": coord.shard_map,
        "servers": servers,
        "chaos_counts": world.log.counts() if world.log else {},
    }


def _default_fixture(seed: int):
    """LeNet + synthetic CIFAR + a jitted grad fn (the test suite passes a
    module-scoped equivalent instead, to pay the compile once)."""
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.data import load_cifar10
    from distributed_ml_pytorch_tpu.models import LeNet
    from distributed_ml_pytorch_tpu.training.trainer import cross_entropy_loss

    model = LeNet()
    x, y, *_ = load_cifar10(n_train=256, n_test=32, synthetic=True)

    @jax.jit
    def grad_fn(p, bx, by, rng):
        def loss_fn(q):
            logits = model.apply({"params": q}, bx, train=True,
                                 rngs={"dropout": rng})
            return cross_entropy_loss(logits, by)

        return jax.value_and_grad(loss_fn)(p)

    params0 = model.init(
        jax.random.key(seed), jnp.zeros((1, 32, 32, 3)))["params"]
    return x, y, grad_fn, params0


def elastic_demo(seed: int = 0) -> Dict:
    """One self-contained pass of the acceptance script (``--demo``)."""
    from distributed_ml_pytorch_tpu.utils.chaos import ChaosPlan, FaultRule

    plan = ChaosPlan([FaultRule(drop=0.05, dup=0.02)], seed=seed)
    out = elastic_scenario(
        seed=seed, steps=16, n_workers=2, n_shards=2,
        join_worker_at=6, join_worker_steps=8, crash_shard_at=10,
        plan=plan)
    first = {j: round(float(np.mean(l[:4])), 3)
             for j, l in out["losses"].items()}
    last = {j: round(float(np.mean(l[-4:])), 3)
            for j, l in out["losses"].items()}
    return {
        "ok": out["ok"] and out["map_version"] >= 2,
        "map_version": out["map_version"],
        "first_losses": first,
        "last_losses": last,
        "coordinator_events": out["events"],
        "server_stats": out["stats"],
        "chaos": out["chaos_counts"],
    }
