"""Disaster-recovery drills as first-class machinery (ISSUE 5 tentpole).

A recovery path that is not continuously exercised is a recovery path that
does not exist. :func:`recovery_drill` stands up the full stack in one
process — coordinator + N elastic shard servers (WAL + checkpoints on disk)
+ M DownPour workers, the PS stars under ``FaultyTransport`` chaos and the
``ReliableTransport`` envelope — and runs the ISSUE 5 script:

1. train; at a scripted step, drive a **coordinator-aligned snapshot
   barrier** (``SnapshotRequest``/``SnapshotDone`` → ``FleetManifest``);
2. keep training past the snapshot (so acked updates exist that ONLY the
   write-ahead logs hold);
3. **kill a shard subset — by default all of them — silently** mid-epoch
   (the in-process analog of SIGKILL: serve loops die without checkpoint,
   leave, or WAL flush; their endpoints raise like dead sockets);
4. **restore** from manifest + WAL: fresh server objects re-install their
   ranges from the manifest's shard map, replay their logs past the
   checkpoint, and re-seed their transports' dedup state; workers' pending
   reliable retries and cadence probes reconnect the fleet;
5. run to completion and **prove** the recovery: per-(worker, shard)
   sequence accounting — every acked ``GradientUpdate`` is in the
   restored server's applied counts (``acked <= applied``, zero acked
   loss) — plus convergence into the fault-free corridor and a
   byte-identical chaos log across repeats.

Determinism contract: the injected wire faults are restricted to channels
whose send sequences are pure functions of the (seeded, step-indexed)
training script — worker 1's pull channel, with kill/restore driven
synchronously from worker 1's own step hook — so the fault log renders
byte-identically run after run (``tests/test_drill.py`` asserts it 3x).
``GradientUpdate`` frames ride the reliability envelope and are never
faulted directly: their loss-freedom must come from WAL + deferred acks,
not from luck.

``make drill`` runs the drill suite; ``bench_all.recovery_phase()`` times
MTTR and replayed-update counts on this machinery.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from distributed_ml_pytorch_tpu.coord.coordinator import Coordinator
from distributed_ml_pytorch_tpu.coord.elastic import ElasticShardServer
from distributed_ml_pytorch_tpu.coord.manifest import (
    MANIFEST_NAME,
    FleetManifest,
)
from distributed_ml_pytorch_tpu.coord.member import CoordClient
from distributed_ml_pytorch_tpu.utils.chaos import (
    ChaosLog,
    ChaosPlan,
    FaultRule,
    FaultyTransport,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    ReliableTransport,
)

#: codes that go PLAIN (outside the reliability envelope) in drill worlds.
#: Pulls and replies are periodic, idempotent and cadence-driven — the
#: staleness channel DownPour tolerates by design — which makes them both
#: safe to fault and DETERMINISTIC to fault: their per-channel send indices
#: are a pure function of the step script, so the chaos log is
#: byte-identical across repeats.
DRILL_UNRELIABLE = (
    MessageCode.Heartbeat,
    MessageCode.LeaseRenew,
    MessageCode.ParameterRequest,
    MessageCode.ParameterUpdate,
)


def default_drill_plan(seed: int = 0) -> ChaosPlan:
    """Wire noise on worker 1's pull channel only (src=1 → server rank 0).

    Worker 1 is the thread that drives kill/restore synchronously from its
    own step hook, so its outage window is step-exact and its channel
    indices replay identically; other workers' timing floats free of the
    script, so faulting their channels would make the log race-dependent.
    """
    return ChaosPlan(
        [FaultRule(src=1, dst=0, code=int(MessageCode.ParameterRequest),
                   drop=0.2, dup=0.1)],
        seed=seed)


def _default_fixture(seed: int):
    from distributed_ml_pytorch_tpu.coord.demo import (
        _default_fixture as fixture,
    )

    return fixture(seed)


def _wait_for(predicate, timeout: float, what: str, poll: float = 0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(poll)
    raise TimeoutError(f"drill: timed out after {timeout:.0f}s waiting for "
                       f"{what}")


def recovery_drill(
    *,
    base_dir: str,
    seed: int = 0,
    steps: int = 18,
    snapshot_at: Optional[int] = 6,
    kill_at: Optional[int] = 10,
    outage_steps: int = 2,
    kill_shards: Optional[Sequence[int]] = None,
    n_workers: int = 2,
    n_shards: int = 2,
    plan: Optional[ChaosPlan] = None,
    lease: float = 5.0,
    lr: float = 0.05,
    n_push: int = 2,
    n_pull: int = 2,
    batch: int = 16,
    wal_group_n: int = 4,
    fixture=None,
    compress: str = "",
    server_opt: str = "",
) -> Dict:
    """Run one kill-and-recover drill (see module docstring).

    ``snapshot_at`` / ``kill_at`` / the restore (``kill_at + outage_steps``)
    are step indices of worker 1's loop, driven synchronously from its step
    hook. ``kill_shards`` selects the victim subset (shard indices; default
    = ALL shards). ``kill_at=None`` runs the fault-free corridor baseline.
    Per-shard state (checkpoint + WAL) lives under ``base_dir/shard<i>``,
    the fleet manifest under ``base_dir``.

    ``compress`` (ISSUE 14) runs the workers' pushes over the compressed
    ``CompressedUpdate`` wire (int8/topk + error feedback) — the drill
    then proves restore replays DECODED deltas exactly once and that the
    WAL records carry the codec id. ``server_opt`` gives every shard a
    ZeRO-style sharded optimizer whose per-range state must survive the
    kill + manifest restore + WAL replay.
    """
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.parallel.sharded_ps import (
        ShardedAsynchronous,
    )
    from distributed_ml_pytorch_tpu.utils.serialization import (
        ravel_model_params,
    )

    if fixture is not None:
        x, y, grad_fn, params0 = fixture
    else:
        x, y, grad_fn, params0 = _default_fixture(seed)
    flat0 = np.asarray(ravel_model_params(params0), np.float32)
    n_params = int(flat0.shape[0])
    victims = (list(range(n_shards)) if kill_shards is None
               else sorted(set(int(i) for i in kill_shards)))

    # --- worlds: coordination star (plain) + one chaos-wrapped PS star per
    # shard, all stars sharing one fault log; each star owns its own crash
    # state so a subset kill stays a subset ------------------------------
    log = ChaosLog()
    the_plan = plan if plan is not None else ChaosPlan(seed=seed)
    coord_world = InProcessTransport.create_world(1 + n_shards + n_workers)
    star_chaos: List[Dict[int, FaultyTransport]] = []
    for i in range(n_shards):
        world = InProcessTransport.create_world(1 + n_workers)
        hub = FaultyTransport(world[0], the_plan, log=log)
        star = {0: hub}
        for r in range(1, 1 + n_workers):
            star[r] = hub.sibling(world[r])
        star_chaos.append(star)

    def make_server_transport(i: int) -> ReliableTransport:
        return ReliableTransport(
            star_chaos[i][0], ack_timeout=0.05, max_backoff=0.25,
            max_retries=120, unreliable_codes=DRILL_UNRELIABLE,
            ack_on_delivery=False)

    rel_workers: List[Dict[int, ReliableTransport]] = []
    for i in range(n_shards):
        rel_workers.append({
            j: ReliableTransport(
                star_chaos[i][j], ack_timeout=0.05, max_backoff=0.25,
                max_retries=120, unreliable_codes=DRILL_UNRELIABLE)
            for j in range(1, 1 + n_workers)})

    manifest_path = os.path.join(base_dir, MANIFEST_NAME)
    coord = Coordinator(
        coord_world[0], n_params, lease=lease, speculation=False,
        manifest_dir=base_dir)
    coord_thread = threading.Thread(
        target=coord.run, kwargs={"timeout": 600}, daemon=True)
    coord_thread.start()

    def make_optimizer():
        if not server_opt:
            return None
        from distributed_ml_pytorch_tpu.parallel.optplane import (
            ShardedOptimizer,
        )

        # momentum 0.5: strong enough that lost/duplicated state would
        # visibly change the replayed trajectory, tame enough to converge
        return ShardedOptimizer(server_opt, 0, 0, lr=1.0, momentum=0.5)

    def start_server(i: int) -> ElasticShardServer:
        client = CoordClient(coord_world[1 + i], "shard",
                             renew_interval=lease / 4)
        srv = ElasticShardServer(
            server_id=1 + i, n_params=n_params,
            transport=make_server_transport(i), coord=client,
            init_params=flat0, ckpt_dir=os.path.join(base_dir, f"shard{i}"),
            ckpt_every=0, wal=True, wal_group_n=wal_group_n,
            optimizer=make_optimizer())
        t = threading.Thread(target=srv.run, kwargs={"timeout": 600},
                             daemon=True)
        t.start()
        return srv

    servers: List[ElasticShardServer] = [start_server(i)
                                         for i in range(n_shards)]
    retired_servers: List[ElasticShardServer] = []
    _wait_for(lambda: len(coord.shard_map.entries) == n_shards, 60,
              "all shard servers to join the map")

    timings: Dict[str, float] = {}
    losses: Dict[int, list] = {}
    opts: Dict[int, object] = {}
    errors: list = []
    restored_info = {"replayed": 0, "manifest": None, "replayed_codecs": []}
    restored_evt = threading.Event()
    if kill_at is None:
        restored_evt.set()  # corridor baseline: nothing to wait out

    def kill_fleet() -> None:
        timings["killed"] = time.monotonic()
        for i in victims:
            servers[i].crash()
            star_chaos[i][0].crash()

    def restore_fleet() -> None:
        t0 = time.monotonic()
        manifest = FleetManifest.load(manifest_path)  # refuses bad manifests
        restored_info["manifest"] = manifest.to_dict()
        for i in victims:
            star_chaos[i][0].restart()
            old = servers[i]
            detach = getattr(old.transport, "detach", None)
            if detach is not None:
                detach()  # the dead life's wrapper; its endpoint lives on
            retired_servers.append(old)
            client = CoordClient(coord_world[1 + i], "shard",
                                 renew_interval=lease / 4)
            srv = ElasticShardServer(
                server_id=1 + i, n_params=n_params,
                transport=make_server_transport(i), coord=client,
                init_params=flat0,
                ckpt_dir=os.path.join(base_dir, f"shard{i}"),
                ckpt_every=0, wal=True, wal_group_n=wal_group_n,
                optimizer=make_optimizer())
            srv.restore_from_manifest(manifest)
            restored_info["replayed"] += srv.ps.replayed_updates
            # codec provenance of the surviving log (ISSUE 14): captured
            # at restore time, before any later checkpoint truncates it —
            # a compressed run's replayed records must say they were
            # compressed (the WAL logs decoded deltas + codec ids)
            recs, _stats = srv.ps.wal.replay()
            restored_info["replayed_codecs"].extend(
                r.codec for r in recs)
            servers[i] = srv
            t = threading.Thread(target=srv.run, kwargs={"timeout": 600},
                                 daemon=True)
            t.start()
        timings["restored"] = time.monotonic()
        timings["restore_s"] = timings["restored"] - t0

    def step_hook(j: int, step: int) -> None:
        if j != 1:
            # every other worker pauses at the kill step until the fleet is
            # restored, so the WHOLE fleet (not just the scripting worker)
            # trains across the outage; this couples only thread timing on
            # unfaulted channels, so the chaos log stays deterministic
            if kill_at is not None and step == kill_at:
                restored_evt.wait(300)
            return
        if snapshot_at is not None and step == snapshot_at:
            coord.trigger_snapshot()
            _wait_for(lambda: os.path.exists(manifest_path)
                      and coord.manifests_written > 0, 60,
                      "the snapshot barrier to publish a manifest")
        if kill_at is not None:
            if step == kill_at:
                kill_fleet()
            elif step == kill_at + outage_steps:
                try:
                    restore_fleet()
                finally:
                    restored_evt.set()  # waiting workers resume even if
                    # the restore itself failed (the error surfaces)

    def run_worker(j: int) -> None:
        try:
            _run_worker(j)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            errors.append((j, repr(e)))

    def _run_worker(j: int) -> None:
        client = CoordClient(coord_world[n_shards + j], "worker",
                             renew_interval=lease / 4)
        m = client.join(timeout=30)
        assert m is not None and m.entries, "worker never got a shard map"
        factory = lambda entry: rel_workers[entry.server_id - 1][j]
        params = jax.tree.map(jnp.asarray, params0)
        opt = ShardedAsynchronous(
            params, lr=lr, n_push=n_push, n_pull=n_pull,
            transports=[factory(e) for e in m.entries],
            coord=client, transport_factory=factory, shard_map=m,
            compress=compress or None)
        opts[j] = opt
        rng = jax.random.key(100 + j)
        my_losses = losses.setdefault(j, [])
        for step in range(steps):
            sel = np.random.default_rng(j * 1000 + step).integers(
                0, len(x), batch)
            loss, grads = grad_fn(params, x[sel], y[sel],
                                  jax.random.fold_in(rng, step))
            params = opt.step(params, grads)
            my_losses.append(float(loss))
            step_hook(j, step)
        opt.finish()
        client.close()

    # MTTR watcher: "recovered" = every restored shard has answered a pull
    # again (message_counts starts at 0 on the fresh server objects)
    def watch_recovery() -> None:
        while "killed" not in timings:
            if watch_stop.wait(0.02):
                return
        while not watch_stop.is_set():
            if "restored" in timings and all(
                servers[i].ps.message_counts.get(
                    MessageCode.ParameterRequest, 0) > 0
                for i in victims
            ):
                timings["recovered"] = time.monotonic()
                return
            watch_stop.wait(0.02)

    watch_stop = threading.Event()
    watcher = None
    if kill_at is not None:
        watcher = threading.Thread(target=watch_recovery, daemon=True)
        watcher.start()

    worker_threads = [threading.Thread(target=run_worker, args=(j,),
                                       daemon=True)
                      for j in range(1, n_workers + 1)]
    for t in worker_threads:
        t.start()
    for t in worker_threads:
        t.join(timeout=600)
    stuck = [t for t in worker_threads if t.is_alive()]
    watch_stop.set()
    if watcher is not None:
        watcher.join(timeout=10)
    for srv in servers:
        srv.stop()
    time.sleep(0.05)
    coord.stop()
    coord_thread.join(timeout=30)

    # ---- sequence accounting: every acked push must be in the (restored)
    # server's applied counts. Elastic workers stamp their pushes with the
    # map version (ShardPush, ISSUE 6); legacy GradientUpdate acks are
    # counted too so the invariant is code-agnostic. --------------------
    acked: Dict[int, Dict[int, int]] = {}
    applied: Dict[int, Dict[int, int]] = {}
    for i in range(n_shards):
        acked[i] = {j: (rel_workers[i][j].acked_count(
            0, MessageCode.ShardPush) + rel_workers[i][j].acked_count(
            0, MessageCode.GradientUpdate) + rel_workers[i][j].acked_count(
            0, MessageCode.CompressedUpdate))
            for j in range(1, 1 + n_workers)}
        applied[i] = {j: servers[i].ps.applied_by_sender.get(j, 0)
                      for j in range(1, 1 + n_workers)}
    accounting_ok = all(
        acked[i][j] <= applied[i][j]
        for i in range(n_shards) for j in range(1, 1 + n_workers))

    for star in rel_workers:
        for t in star.values():
            t.close()
    for srv in servers:
        close = getattr(srv.transport, "close", None)
        if close is not None:
            close()
    for t in coord_world.values():
        t.close()

    mttr = (timings["recovered"] - timings["killed"]
            if "recovered" in timings and "killed" in timings else None)
    return {
        "ok": not stuck and not errors and accounting_ok,
        "errors": errors,
        "stuck_workers": len(stuck),
        "losses": losses,
        "acked": acked,
        "applied": applied,
        "accounting_ok": accounting_ok,
        "replayed_updates": restored_info["replayed"],
        "replayed_codecs": restored_info["replayed_codecs"],
        "manifest": restored_info["manifest"],
        "chaos_lines": log.lines(),
        "chaos_counts": log.counts(),
        "events": list(coord.events),
        "stats": {srv.server_id: dict(srv.stats) for srv in servers},
        "mttr_s": mttr,
        "restore_s": timings.get("restore_s"),
        "servers": servers,
    }


def drill_demo(seed: int = 0, base_dir: Optional[str] = None) -> Dict:
    """One self-contained drill pass (``coord/cli.py --drill``)."""
    import tempfile

    base = base_dir or tempfile.mkdtemp(prefix="drill_")
    out = recovery_drill(base_dir=base, seed=seed,
                         plan=default_drill_plan(seed))
    return {
        # > 0: the drill must actually have exercised WAL replay (acked
        # updates that ONLY the logs held), or "ok" proves nothing
        "ok": out["ok"] and out["replayed_updates"] > 0,
        "mttr_s": out["mttr_s"],
        "restore_s": out["restore_s"],
        "replayed_updates": out["replayed_updates"],
        "acked": out["acked"],
        "applied": out["applied"],
        "chaos": out["chaos_counts"],
        "events": out["events"],
        "manifest": out["manifest"],
        "state_dir": base,
    }
