"""Disaster-recovery drills as first-class machinery (ISSUE 5 tentpole).

A recovery path that is not continuously exercised is a recovery path that
does not exist. :func:`recovery_drill` stands up the full stack in one
process — coordinator + N elastic shard servers (WAL + checkpoints on disk)
+ M DownPour workers, the PS stars under ``FaultyTransport`` chaos and the
``ReliableTransport`` envelope — and runs the ISSUE 5 script:

1. train; at a scripted step, drive a **coordinator-aligned snapshot
   barrier** (``SnapshotRequest``/``SnapshotDone`` → ``FleetManifest``);
2. keep training past the snapshot (so acked updates exist that ONLY the
   write-ahead logs hold);
3. **kill a shard subset — by default all of them — silently** mid-epoch
   (the in-process analog of SIGKILL: serve loops die without checkpoint,
   leave, or WAL flush; their endpoints raise like dead sockets);
4. **restore** from manifest + WAL: fresh server objects re-install their
   ranges from the manifest's shard map, replay their logs past the
   checkpoint, and re-seed their transports' dedup state; workers' pending
   reliable retries and cadence probes reconnect the fleet;
5. run to completion and **prove** the recovery: per-(worker, shard)
   sequence accounting — every acked ``GradientUpdate`` is in the
   restored server's applied counts (``acked <= applied``, zero acked
   loss) — plus convergence into the fault-free corridor and a
   byte-identical chaos log across repeats.

Determinism contract: the injected wire faults are restricted to channels
whose send sequences are pure functions of the (seeded, step-indexed)
training script — worker 1's pull channel, with kill/restore driven
synchronously from worker 1's own step hook — so the fault log renders
byte-identically run after run (``tests/test_drill.py`` asserts it 3x).
``GradientUpdate`` frames ride the reliability envelope and are never
faulted directly: their loss-freedom must come from WAL + deferred acks,
not from luck.

``make drill`` runs the drill suite; ``bench_all.recovery_phase()`` times
MTTR and replayed-update counts on this machinery.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from distributed_ml_pytorch_tpu.coord.coordinator import Coordinator
from distributed_ml_pytorch_tpu.coord.elastic import ElasticShardServer
from distributed_ml_pytorch_tpu.coord.manifest import (
    MANIFEST_NAME,
    FleetManifest,
)
from distributed_ml_pytorch_tpu.coord.member import CoordClient
from distributed_ml_pytorch_tpu.utils.chaos import (
    ChaosLog,
    ChaosPlan,
    FaultRule,
    FaultyTransport,
    GrayRule,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    ReliableTransport,
)

#: codes that go PLAIN (outside the reliability envelope) in drill worlds.
#: Pulls and replies are periodic, idempotent and cadence-driven — the
#: staleness channel DownPour tolerates by design — which makes them both
#: safe to fault and DETERMINISTIC to fault: their per-channel send indices
#: are a pure function of the step script, so the chaos log is
#: byte-identical across repeats.
DRILL_UNRELIABLE = (
    MessageCode.Heartbeat,
    MessageCode.LeaseRenew,
    MessageCode.ParameterRequest,
    MessageCode.ParameterUpdate,
)


def default_drill_plan(seed: int = 0) -> ChaosPlan:
    """Wire noise on worker 1's pull channel only (src=1 → server rank 0).

    Worker 1 is the thread that drives kill/restore synchronously from its
    own step hook, so its outage window is step-exact and its channel
    indices replay identically; other workers' timing floats free of the
    script, so faulting their channels would make the log race-dependent.
    """
    return ChaosPlan(
        [FaultRule(src=1, dst=0, code=int(MessageCode.ParameterRequest),
                   drop=0.2, dup=0.1)],
        seed=seed)


def _default_fixture(seed: int):
    from distributed_ml_pytorch_tpu.coord.demo import (
        _default_fixture as fixture,
    )

    return fixture(seed)


def _wait_for(predicate, timeout: float, what: str, poll: float = 0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(poll)
    raise TimeoutError(f"drill: timed out after {timeout:.0f}s waiting for "
                       f"{what}")


def recovery_drill(
    *,
    base_dir: str,
    seed: int = 0,
    steps: int = 18,
    snapshot_at: Optional[int] = 6,
    kill_at: Optional[int] = 10,
    outage_steps: int = 2,
    kill_shards: Optional[Sequence[int]] = None,
    n_workers: int = 2,
    n_shards: int = 2,
    plan: Optional[ChaosPlan] = None,
    lease: float = 5.0,
    lr: float = 0.05,
    n_push: int = 2,
    n_pull: int = 2,
    batch: int = 16,
    wal_group_n: int = 4,
    fixture=None,
    compress: str = "",
    server_opt: str = "",
) -> Dict:
    """Run one kill-and-recover drill (see module docstring).

    ``snapshot_at`` / ``kill_at`` / the restore (``kill_at + outage_steps``)
    are step indices of worker 1's loop, driven synchronously from its step
    hook. ``kill_shards`` selects the victim subset (shard indices; default
    = ALL shards). ``kill_at=None`` runs the fault-free corridor baseline.
    Per-shard state (checkpoint + WAL) lives under ``base_dir/shard<i>``,
    the fleet manifest under ``base_dir``.

    ``compress`` (ISSUE 14) runs the workers' pushes over the compressed
    ``CompressedUpdate`` wire (int8/topk + error feedback) — the drill
    then proves restore replays DECODED deltas exactly once and that the
    WAL records carry the codec id. ``server_opt`` gives every shard a
    ZeRO-style sharded optimizer whose per-range state must survive the
    kill + manifest restore + WAL replay.
    """
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.parallel.sharded_ps import (
        ShardedAsynchronous,
    )
    from distributed_ml_pytorch_tpu.utils.serialization import (
        ravel_model_params,
    )

    if fixture is not None:
        x, y, grad_fn, params0 = fixture
    else:
        x, y, grad_fn, params0 = _default_fixture(seed)
    flat0 = np.asarray(ravel_model_params(params0), np.float32)
    n_params = int(flat0.shape[0])
    victims = (list(range(n_shards)) if kill_shards is None
               else sorted(set(int(i) for i in kill_shards)))

    # --- worlds: coordination star (plain) + one chaos-wrapped PS star per
    # shard, all stars sharing one fault log; each star owns its own crash
    # state so a subset kill stays a subset ------------------------------
    log = ChaosLog()
    the_plan = plan if plan is not None else ChaosPlan(seed=seed)
    coord_world = InProcessTransport.create_world(1 + n_shards + n_workers)
    star_chaos: List[Dict[int, FaultyTransport]] = []
    for i in range(n_shards):
        world = InProcessTransport.create_world(1 + n_workers)
        hub = FaultyTransport(world[0], the_plan, log=log)
        star = {0: hub}
        for r in range(1, 1 + n_workers):
            star[r] = hub.sibling(world[r])
        star_chaos.append(star)

    def make_server_transport(i: int) -> ReliableTransport:
        return ReliableTransport(
            star_chaos[i][0], ack_timeout=0.05, max_backoff=0.25,
            max_retries=120, unreliable_codes=DRILL_UNRELIABLE,
            ack_on_delivery=False)

    rel_workers: List[Dict[int, ReliableTransport]] = []
    for i in range(n_shards):
        rel_workers.append({
            j: ReliableTransport(
                star_chaos[i][j], ack_timeout=0.05, max_backoff=0.25,
                max_retries=120, unreliable_codes=DRILL_UNRELIABLE)
            for j in range(1, 1 + n_workers)})

    manifest_path = os.path.join(base_dir, MANIFEST_NAME)
    coord = Coordinator(
        coord_world[0], n_params, lease=lease, speculation=False,
        manifest_dir=base_dir)
    coord_thread = threading.Thread(
        target=coord.run, kwargs={"timeout": 600}, daemon=True)
    coord_thread.start()

    def make_optimizer():
        if not server_opt:
            return None
        from distributed_ml_pytorch_tpu.parallel.optplane import (
            ShardedOptimizer,
        )

        # momentum 0.5: strong enough that lost/duplicated state would
        # visibly change the replayed trajectory, tame enough to converge
        return ShardedOptimizer(server_opt, 0, 0, lr=1.0, momentum=0.5)

    def start_server(i: int) -> ElasticShardServer:
        client = CoordClient(coord_world[1 + i], "shard",
                             renew_interval=lease / 4)
        srv = ElasticShardServer(
            server_id=1 + i, n_params=n_params,
            transport=make_server_transport(i), coord=client,
            init_params=flat0, ckpt_dir=os.path.join(base_dir, f"shard{i}"),
            ckpt_every=0, wal=True, wal_group_n=wal_group_n,
            optimizer=make_optimizer())
        t = threading.Thread(target=srv.run, kwargs={"timeout": 600},
                             daemon=True)
        t.start()
        return srv

    servers: List[ElasticShardServer] = [start_server(i)
                                         for i in range(n_shards)]
    retired_servers: List[ElasticShardServer] = []
    _wait_for(lambda: len(coord.shard_map.entries) == n_shards, 60,
              "all shard servers to join the map")

    timings: Dict[str, float] = {}
    losses: Dict[int, list] = {}
    opts: Dict[int, object] = {}
    errors: list = []
    restored_info = {"replayed": 0, "manifest": None, "replayed_codecs": []}
    restored_evt = threading.Event()
    if kill_at is None:
        restored_evt.set()  # corridor baseline: nothing to wait out

    def kill_fleet() -> None:
        timings["killed"] = time.monotonic()
        for i in victims:
            servers[i].crash()
            star_chaos[i][0].crash()

    def restore_fleet() -> None:
        t0 = time.monotonic()
        manifest = FleetManifest.load(manifest_path)  # refuses bad manifests
        restored_info["manifest"] = manifest.to_dict()
        for i in victims:
            star_chaos[i][0].restart()
            old = servers[i]
            detach = getattr(old.transport, "detach", None)
            if detach is not None:
                detach()  # the dead life's wrapper; its endpoint lives on
            retired_servers.append(old)
            client = CoordClient(coord_world[1 + i], "shard",
                                 renew_interval=lease / 4)
            srv = ElasticShardServer(
                server_id=1 + i, n_params=n_params,
                transport=make_server_transport(i), coord=client,
                init_params=flat0,
                ckpt_dir=os.path.join(base_dir, f"shard{i}"),
                ckpt_every=0, wal=True, wal_group_n=wal_group_n,
                optimizer=make_optimizer())
            srv.restore_from_manifest(manifest)
            restored_info["replayed"] += srv.ps.replayed_updates
            # codec provenance of the surviving log (ISSUE 14): captured
            # at restore time, before any later checkpoint truncates it —
            # a compressed run's replayed records must say they were
            # compressed (the WAL logs decoded deltas + codec ids)
            recs, _stats = srv.ps.wal.replay()
            restored_info["replayed_codecs"].extend(
                r.codec for r in recs)
            servers[i] = srv
            t = threading.Thread(target=srv.run, kwargs={"timeout": 600},
                                 daemon=True)
            t.start()
        timings["restored"] = time.monotonic()
        timings["restore_s"] = timings["restored"] - t0

    def step_hook(j: int, step: int) -> None:
        if j != 1:
            # every other worker pauses at the kill step until the fleet is
            # restored, so the WHOLE fleet (not just the scripting worker)
            # trains across the outage; this couples only thread timing on
            # unfaulted channels, so the chaos log stays deterministic
            if kill_at is not None and step == kill_at:
                restored_evt.wait(300)
            return
        if snapshot_at is not None and step == snapshot_at:
            coord.trigger_snapshot()
            _wait_for(lambda: os.path.exists(manifest_path)
                      and coord.manifests_written > 0, 60,
                      "the snapshot barrier to publish a manifest")
        if kill_at is not None:
            if step == kill_at:
                kill_fleet()
            elif step == kill_at + outage_steps:
                try:
                    restore_fleet()
                finally:
                    restored_evt.set()  # waiting workers resume even if
                    # the restore itself failed (the error surfaces)

    def run_worker(j: int) -> None:
        try:
            _run_worker(j)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            errors.append((j, repr(e)))

    def _run_worker(j: int) -> None:
        client = CoordClient(coord_world[n_shards + j], "worker",
                             renew_interval=lease / 4)
        m = client.join(timeout=30)
        assert m is not None and m.entries, "worker never got a shard map"
        factory = lambda entry: rel_workers[entry.server_id - 1][j]
        params = jax.tree.map(jnp.asarray, params0)
        opt = ShardedAsynchronous(
            params, lr=lr, n_push=n_push, n_pull=n_pull,
            transports=[factory(e) for e in m.entries],
            coord=client, transport_factory=factory, shard_map=m,
            compress=compress or None)
        opts[j] = opt
        rng = jax.random.key(100 + j)
        my_losses = losses.setdefault(j, [])
        for step in range(steps):
            sel = np.random.default_rng(j * 1000 + step).integers(
                0, len(x), batch)
            loss, grads = grad_fn(params, x[sel], y[sel],
                                  jax.random.fold_in(rng, step))
            params = opt.step(params, grads)
            my_losses.append(float(loss))
            step_hook(j, step)
        opt.finish()
        client.close()

    # MTTR watcher: "recovered" = every restored shard has answered a pull
    # again (message_counts starts at 0 on the fresh server objects)
    def watch_recovery() -> None:
        while "killed" not in timings:
            if watch_stop.wait(0.02):
                return
        while not watch_stop.is_set():
            if "restored" in timings and all(
                servers[i].ps.message_counts.get(
                    MessageCode.ParameterRequest, 0) > 0
                for i in victims
            ):
                timings["recovered"] = time.monotonic()
                return
            watch_stop.wait(0.02)

    watch_stop = threading.Event()
    watcher = None
    if kill_at is not None:
        watcher = threading.Thread(target=watch_recovery, daemon=True)
        watcher.start()

    worker_threads = [threading.Thread(target=run_worker, args=(j,),
                                       daemon=True)
                      for j in range(1, n_workers + 1)]
    for t in worker_threads:
        t.start()
    for t in worker_threads:
        t.join(timeout=600)
    stuck = [t for t in worker_threads if t.is_alive()]
    watch_stop.set()
    if watcher is not None:
        watcher.join(timeout=10)
    for srv in servers:
        srv.stop()
    time.sleep(0.05)
    coord.stop()
    coord_thread.join(timeout=30)

    # ---- sequence accounting: every acked push must be in the (restored)
    # server's applied counts. Elastic workers stamp their pushes with the
    # map version (ShardPush, ISSUE 6); legacy GradientUpdate acks are
    # counted too so the invariant is code-agnostic. --------------------
    acked: Dict[int, Dict[int, int]] = {}
    applied: Dict[int, Dict[int, int]] = {}
    for i in range(n_shards):
        acked[i] = {j: (rel_workers[i][j].acked_count(
            0, MessageCode.ShardPush) + rel_workers[i][j].acked_count(
            0, MessageCode.GradientUpdate) + rel_workers[i][j].acked_count(
            0, MessageCode.CompressedUpdate))
            for j in range(1, 1 + n_workers)}
        applied[i] = {j: servers[i].ps.applied_by_sender.get(j, 0)
                      for j in range(1, 1 + n_workers)}
    accounting_ok = all(
        acked[i][j] <= applied[i][j]
        for i in range(n_shards) for j in range(1, 1 + n_workers))

    for star in rel_workers:
        for t in star.values():
            t.close()
    for srv in servers:
        close = getattr(srv.transport, "close", None)
        if close is not None:
            close()
    for t in coord_world.values():
        t.close()

    mttr = (timings["recovered"] - timings["killed"]
            if "recovered" in timings and "killed" in timings else None)
    return {
        "ok": not stuck and not errors and accounting_ok,
        "errors": errors,
        "stuck_workers": len(stuck),
        "losses": losses,
        "acked": acked,
        "applied": applied,
        "accounting_ok": accounting_ok,
        "replayed_updates": restored_info["replayed"],
        "replayed_codecs": restored_info["replayed_codecs"],
        "manifest": restored_info["manifest"],
        "chaos_lines": log.lines(),
        "chaos_counts": log.counts(),
        "events": list(coord.events),
        "stats": {srv.server_id: dict(srv.stats) for srv in servers},
        "mttr_s": mttr,
        "restore_s": timings.get("restore_s"),
        "servers": servers,
    }


def sched_drill(
    *,
    base_dir: str,
    seed: int = 0,
    steps: int = 56,
    peak_at: int = 6,
    offpeak_at: int = 46,
    require_manifest: bool = True,
    n_workers: int = 2,
    n_shards: int = 2,
    plan: Optional[ChaosPlan] = None,
    lease: float = 2.0,
    lr: float = 0.05,
    n_push: int = 2,
    n_pull: int = 2,
    batch: int = 16,
    wal_group_n: int = 4,
    fixture=None,
    step_sleep: float = 0.05,
) -> Dict:
    """One multi-tenant preempt/park/resume drill (ISSUE 16).

    The full stack of :func:`recovery_drill` — coordinator + elastic WAL'd
    shards + DownPour workers under chaos — plus a :class:`FleetScheduler`
    with a training tenant (owns every shard slot) and a higher-priority
    serving tenant, and an **agent** member that actuates grants/resumes.
    The script, driven from worker 1's step hook like the recovery drill:

    1. at ``peak_at`` the serving tenant's demand spikes; the scheduler
       preempts the training tenant's last slot: snapshot barrier →
       ``PreemptRequest`` → the victim shard commits its WAL and parks
       (workers keep pushing THROUGH the barrier→park window, so acked
       deltas exist that only the WAL holds);
    2. workers observe the park and ``hold_shard`` the victim's range —
       their slice degrades to purely-local SGD (held, not lost);
    3. at ``offpeak_at`` demand drops; the grant is revoked and the agent
       restores the parked member from the manifest + exactly-once WAL
       replay, rejoining as a newer incarnation of the same rank;
    4. workers release the hold and push to the revived shard; the drill
       PROVES the round-trip: restored state bit-identical to the parked
       server's (params, apply_seq, per-sender applied counts), acked <=
       applied per (worker, shard), and a deterministic chaos log.

    ``require_manifest=False`` is the ``park_without_manifest`` mutation's
    real-stack surface: the scheduler parks without driving the barrier,
    and the resume finds no manifest to restore from — the violation the
    ``sched`` model's counterexample predicts. Violations are returned in
    ``out["violations"]`` (empty = the protocol held).
    """
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.coord.sched import FleetScheduler
    from distributed_ml_pytorch_tpu.coord.tenants import (
        TENANT_SERVING,
        TENANT_TRAINING,
        Tenant,
        TenantRegistry,
    )
    from distributed_ml_pytorch_tpu.parallel.sharded_ps import (
        ShardedAsynchronous,
    )
    from distributed_ml_pytorch_tpu.utils.serialization import (
        ravel_model_params,
    )

    assert n_shards >= 2, "sched_drill needs a survivor shard (n_shards >= 2)"
    if fixture is not None:
        x, y, grad_fn, params0 = fixture
    else:
        x, y, grad_fn, params0 = _default_fixture(seed)
    flat0 = np.asarray(ravel_model_params(params0), np.float32)
    n_params = int(flat0.shape[0])
    # the victim is the training tenant's LAST slot (the scheduler's
    # _pick_victim order) — shard n_shards-1, never the chaos-faulted
    # star 0, so the fault log stays a pure function of the step script
    victim = n_shards - 1
    victim_sid = 1 + victim

    TRAIN_ID, SERVE_ID = 1, 2

    log = ChaosLog()
    the_plan = plan if plan is not None else ChaosPlan(seed=seed)
    agent_rank = 1 + n_shards + n_workers
    coord_world = InProcessTransport.create_world(2 + n_shards + n_workers)
    # Chaos rides star 0 ONLY (one shared log). Every star reuses the same
    # rank numbering, so a (src=1, dst=0, ParameterRequest) rule would
    # otherwise fault the VICTIM star's pull channel too — and that
    # channel's send count ends exactly when the worker observes the park,
    # which is coordinator-thread timing, not step script. Scoping the
    # plan to star 0 (whose shard is never parked) keeps the log a pure
    # function of the step script, so repeats are byte-identical.
    star_chaos: List[Dict[int, FaultyTransport]] = []
    for i in range(n_shards):
        world = InProcessTransport.create_world(1 + n_workers)
        hub = FaultyTransport(
            world[0], the_plan if i == 0 else ChaosPlan(seed=seed), log=log)
        star = {0: hub}
        for r in range(1, 1 + n_workers):
            star[r] = hub.sibling(world[r])
        star_chaos.append(star)

    def make_server_transport(i: int) -> ReliableTransport:
        return ReliableTransport(
            star_chaos[i][0], ack_timeout=0.05, max_backoff=0.25,
            max_retries=120, unreliable_codes=DRILL_UNRELIABLE,
            ack_on_delivery=False)

    rel_workers: List[Dict[int, ReliableTransport]] = []
    for i in range(n_shards):
        rel_workers.append({
            j: ReliableTransport(
                star_chaos[i][j], ack_timeout=0.05, max_backoff=0.25,
                max_retries=120, unreliable_codes=DRILL_UNRELIABLE)
            for j in range(1, 1 + n_workers)})

    manifest_path = os.path.join(base_dir, MANIFEST_NAME)
    coord = Coordinator(
        coord_world[0], n_params, lease=lease, speculation=False,
        manifest_dir=base_dir)
    registry = TenantRegistry()
    registry.register(Tenant(TRAIN_ID, "train", kind=TENANT_TRAINING,
                             priority=1, demand=n_shards,
                             min_slots=n_shards - 1))
    registry.register(Tenant(SERVE_ID, "serve", kind=TENANT_SERVING,
                             priority=5, demand=0))
    sched = FleetScheduler(
        coord, registry=registry, require_manifest=require_manifest,
        actuator_rank=agent_rank, preempt_timeout=60.0, resume_timeout=60.0)
    for i in range(n_shards):
        sched.register_member_slot(1 + i, TRAIN_ID)
    coord_thread = threading.Thread(
        target=coord.run, kwargs={"timeout": 600}, daemon=True)
    coord_thread.start()

    def start_server(i: int) -> ElasticShardServer:
        client = CoordClient(coord_world[1 + i], "shard",
                             renew_interval=lease / 4)
        srv = ElasticShardServer(
            server_id=1 + i, n_params=n_params,
            transport=make_server_transport(i), coord=client,
            init_params=flat0, ckpt_dir=os.path.join(base_dir, f"shard{i}"),
            ckpt_every=0, wal=True, wal_group_n=wal_group_n)
        t = threading.Thread(target=srv.run, kwargs={"timeout": 600},
                             daemon=True)
        t.start()
        return srv

    servers: List[ElasticShardServer] = [start_server(i)
                                         for i in range(n_shards)]
    retired_servers: List[ElasticShardServer] = []
    _wait_for(lambda: len(coord.shard_map.entries) == n_shards, 60,
              "all shard servers to join the map")

    # --- the node agent: grants/resumes land here over the wire ---------
    violations: List[str] = []
    grants: List[tuple] = []
    resumed_info = {"replayed": 0, "bit_identical": None,
                    "apply_seq_parked": None, "apply_seq_restored": None}
    resume_failed = threading.Event()
    resume_jobs: List[tuple] = []
    resume_ready = threading.Event()
    agent = CoordClient(coord_world[agent_rank], "agent",
                        renew_interval=lease / 4)

    def on_slot_grant(grant_id, tenant_id, action, slot_id):
        grants.append((grant_id, tenant_id, action, slot_id))

    def on_resume(grant_id, rank, snapshot_id):
        resume_jobs.append((grant_id, rank, snapshot_id))
        resume_ready.set()

    agent.on_slot_grant = on_slot_grant
    agent.on_resume = on_resume
    agent.join(timeout=30)

    def do_resume(grant_id: int, rank: int, snapshot_id: int) -> None:
        i = rank - 1
        old = servers[i]
        try:
            if snapshot_id <= 0 or not os.path.exists(manifest_path):
                raise FileNotFoundError(
                    f"no manifest for snapshot {snapshot_id}")
            manifest = FleetManifest.load(manifest_path)
            detach = getattr(old.transport, "detach", None)
            if detach is not None:
                detach()
            client = CoordClient(coord_world[1 + i], "shard",
                                 renew_interval=lease / 4)
            srv = ElasticShardServer(
                server_id=1 + i, n_params=n_params,
                transport=make_server_transport(i), coord=client,
                init_params=flat0,
                ckpt_dir=os.path.join(base_dir, f"shard{i}"),
                ckpt_every=0, wal=True, wal_group_n=wal_group_n)
            srv.restore_from_manifest(manifest)
            resumed_info["replayed"] += srv.ps.replayed_updates
            # bit-for-bit proof BEFORE any new traffic: the restored
            # range + apply_seq + per-sender counts must equal the parked
            # server's in-memory state (checkpoint + exact WAL replay)
            lo, hi = old.lo, old.hi
            resumed_info["apply_seq_parked"] = old.ps._apply_seq
            resumed_info["apply_seq_restored"] = srv.ps._apply_seq
            identical = (
                np.array_equal(np.asarray(old.ps.central[lo:hi]),
                               np.asarray(srv.ps.central[lo:hi]))
                and srv.ps._apply_seq == old.ps._apply_seq
                and dict(srv.ps.applied_by_sender)
                == dict(old.ps.applied_by_sender))
            resumed_info["bit_identical"] = identical
            if not identical:
                violations.append(
                    f"resume of rank {rank} not bit-identical: parked "
                    f"apply_seq {old.ps._apply_seq} vs restored "
                    f"{srv.ps._apply_seq}")
            retired_servers.append(old)
            servers[i] = srv
            threading.Thread(target=srv.run, kwargs={"timeout": 600},
                             daemon=True).start()
        except Exception as e:  # noqa: BLE001 — the violation IS the result
            violations.append(
                f"resume lost acked state: rank {rank} parked without a "
                f"usable manifest ({e!r})")
            resume_failed.set()

    def agent_loop() -> None:
        while not agent_stop.is_set():
            if not resume_ready.wait(0.05):
                continue
            resume_ready.clear()
            while resume_jobs:
                do_resume(*resume_jobs.pop(0))

    agent_stop = threading.Event()
    agent_thread = threading.Thread(target=agent_loop, daemon=True)
    agent_thread.start()

    timings: Dict[str, float] = {}
    losses: Dict[int, list] = {}
    opts: Dict[int, object] = {}
    errors: list = []
    hold_evt = threading.Event()
    release_evt = threading.Event()
    held = {j: False for j in range(1, 1 + n_workers)}

    def _follow(j: int) -> None:
        # non-blocking per-step reactions every worker applies: hold the
        # victim's range once it parks, release once it is back
        if hold_evt.is_set() and not release_evt.is_set() and not held[j]:
            opts[j].hold_shard(victim_sid)
            held[j] = True
        if release_evt.is_set() and held[j] and not resume_failed.is_set():
            opts[j].release_shard(victim_sid)
            held[j] = False

    def step_hook(j: int, step: int) -> None:
        time.sleep(step_sleep)  # pace ALL workers so wall-clock scheduler
        # decisions land inside the step script, not after it
        if j != 1:
            if step == offpeak_at:
                release_evt.wait(300)
            _follow(j)
            return
        if step == peak_at:
            timings["peak"] = time.monotonic()
            registry.set_demand(SERVE_ID, 1)
        if peak_at < step < offpeak_at and not hold_evt.is_set() \
                and sched.preempts_done > 0:
            hold_evt.set()
        if step == offpeak_at:
            _wait_for(lambda: sched.preempts_done > 0
                      or sched.preempts_aborted > 0, 120,
                      "the preempt to park the victim")
            hold_evt.set()
            _follow(1)
            timings["offpeak"] = time.monotonic()
            registry.set_demand(SERVE_ID, 0)
            _wait_for(lambda: sched.resumes_done > 0
                      or resume_failed.is_set(), 120,
                      "the resume to settle")
            release_evt.set()
        _follow(1)

    def run_worker(j: int) -> None:
        try:
            _run_worker(j)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            errors.append((j, repr(e)))
            release_evt.set()  # never strand the other workers

    def _run_worker(j: int) -> None:
        client = CoordClient(coord_world[n_shards + j], "worker",
                             renew_interval=lease / 4)
        m = client.join(timeout=30)
        assert m is not None and m.entries, "worker never got a shard map"
        factory = lambda entry: rel_workers[entry.server_id - 1][j]
        params = jax.tree.map(jnp.asarray, params0)
        opt = ShardedAsynchronous(
            params, lr=lr, n_push=n_push, n_pull=n_pull,
            transports=[factory(e) for e in m.entries],
            coord=client, transport_factory=factory, shard_map=m)
        opts[j] = opt
        rng = jax.random.key(100 + j)
        my_losses = losses.setdefault(j, [])
        for step in range(steps):
            sel = np.random.default_rng(j * 1000 + step).integers(
                0, len(x), batch)
            loss, grads = grad_fn(params, x[sel], y[sel],
                                  jax.random.fold_in(rng, step))
            params = opt.step(params, grads)
            my_losses.append(float(loss))
            step_hook(j, step)
        opt.finish()
        client.close()

    worker_threads = [threading.Thread(target=run_worker, args=(j,),
                                       daemon=True)
                      for j in range(1, n_workers + 1)]
    timings["day_start"] = time.monotonic()
    for t in worker_threads:
        t.start()
    for t in worker_threads:
        t.join(timeout=600)
    timings["day_end"] = time.monotonic()
    stuck = [t for t in worker_threads if t.is_alive()]
    agent_stop.set()
    agent_thread.join(timeout=10)
    for srv in servers:
        srv.stop()
    time.sleep(0.05)
    agent.close()
    coord.stop()
    coord_thread.join(timeout=30)

    # ---- per-(worker, shard) sequence accounting: every acked push is in
    # the (possibly parked-and-resumed) server's applied counts ----------
    acked: Dict[int, Dict[int, int]] = {}
    applied: Dict[int, Dict[int, int]] = {}
    for i in range(n_shards):
        acked[i] = {j: (rel_workers[i][j].acked_count(
            0, MessageCode.ShardPush) + rel_workers[i][j].acked_count(
            0, MessageCode.GradientUpdate) + rel_workers[i][j].acked_count(
            0, MessageCode.CompressedUpdate))
            for j in range(1, 1 + n_workers)}
        applied[i] = {j: servers[i].ps.applied_by_sender.get(j, 0)
                      for j in range(1, 1 + n_workers)}
        for j in range(1, 1 + n_workers):
            if acked[i][j] > applied[i][j]:
                violations.append(
                    f"acked delta lost: shard {i} worker {j}: acked "
                    f"{acked[i][j]} > applied {applied[i][j]}")
    violations.extend(sched.ledger.audit())

    for star in rel_workers:
        for t in star.values():
            t.close()
    for srv in servers:
        close = getattr(srv.transport, "close", None)
        if close is not None:
            close()
    for t in coord_world.values():
        t.close()

    return {
        "ok": (not stuck and not errors and not violations
               and sched.preempts_done > 0),
        "violations": violations,
        "errors": errors,
        "stuck_workers": len(stuck),
        "losses": losses,
        "acked": acked,
        "applied": applied,
        "replayed_updates": resumed_info["replayed"],
        "bit_identical": resumed_info["bit_identical"],
        "grants": grants,
        "sched": sched.summary(),
        "events": list(coord.events),
        "chaos_lines": log.lines(),
        "chaos_counts": log.counts(),
        "held_pushes": {j: getattr(opts.get(j), "held_pushes", 0)
                        for j in sorted(opts)},
        # day geometry for the bench's goodput accounting: total day
        # wall-clock and the measured peak window (demand-spike -> demand
        # drop, i.e. the seconds the borrowed slot served)
        "wall_s": timings["day_end"] - timings["day_start"],
        "peak_window_s": (timings["offpeak"] - timings["peak"]
                          if "peak" in timings and "offpeak" in timings
                          else None),
        "servers": servers,
    }


def default_gray_plan(seed: int = 0, n_workers: int = 2,
                      gray_from: int = 30, gray_until: int = 58) -> ChaosPlan:
    """A windowed ONE-WAY partition on every worker's pull channel toward
    shard server 0 (the gray victim): requests with per-channel send
    indices in ``[gray_from, gray_until)`` vanish; replies were never
    provoked, renewals never touched. Because every rule is INDEX-windowed
    and pulls are cadence-driven, the chaos log is a pure function of the
    window — byte-identical across repeats no matter how detection and
    containment timing float."""
    rules = [GrayRule(kind="partition", src=j, dst=0,
                      code=int(MessageCode.ParameterRequest),
                      after=gray_from, until=gray_until)
             for j in range(1, 1 + n_workers)]
    return ChaosPlan(seed=seed, gray=tuple(rules))


def gray_drill(
    *,
    base_dir: str,
    seed: int = 0,
    steps: int = 170,
    gray_from: int = 30,
    gray_until: int = 58,
    n_workers: int = 2,
    n_shards: int = 2,
    plan: Optional[ChaosPlan] = None,
    lease: float = 1.0,
    lr: float = 0.05,
    n_push: int = 2,
    n_pull: int = 2,
    batch: int = 16,
    wal_group_n: int = 4,
    fixture=None,
    step_sleep: float = 0.05,
    extra_steps: int = 400,
    gray_knobs: Optional[dict] = None,
    contain: bool = True,
) -> Dict:
    """One gray-failure containment drill (ISSUE 20).

    Mid-training, shard server 0 goes GRAY, not dead: a scheduled one-way
    partition eats the workers' pull requests toward it while its own
    lease renewals (separate star) keep flowing. The coordinator must
    tell "slow/cut-off" from "dead" and contain WITHOUT killing:

    1. both workers' renew tails carry per-link evidence (windowed pull
       requests-vs-replies) naming the victim — the asymmetric-partition
       witness its own clean tail can never be;
    2. :class:`GrayHealth` confirms suspicion over ``confirm_ticks`` and
       puts the victim on PROBATION (detection latency measured);
    3. still suspect after ``quarantine_after`` ticks, it checkpoint-parks
       the victim through the scheduler's park machinery — snapshot
       barrier, gray-granted ``PreemptRequest``, WAL'd park ticket, lease
       exempt (containment MTTR measured). The victim NEVER lease-expires
       and is NEVER revoked;
    4. the partition heals, the cooldown expires, the node agent restores
       the parked range from manifest + exact WAL replay (bit-identical
       proof, same as :func:`sched_drill`), and the resumed life re-enters
       the ladder at PROBATION, clearing to OK as clean windows accumulate.

    Workers run at least ``steps`` steps and then keep stepping (bounded
    by ``extra_steps``) until the ladder clears — chaos rules are all
    index-windowed, so the flexible tail cannot perturb the log.
    ``gray_knobs`` forwards extra :class:`GrayHealth` kwargs (the distmodel
    mutations' real-stack surface: ``hysteresis=False``,
    ``asymmetric=False``, ``evict_on_first_suspicion=True``).

    ``contain=False`` is the bench comparison leg: suspicion is disabled
    (``raise_threshold`` pinned unreachably high), the workers run the
    fixed script only, and the ladder contract is not asserted — the run
    measures what the SAME gray episode costs when nobody contains it.
    The gray rules are index-windowed, so the episode eventually drains
    through retransmits either way; only the goodput differs."""
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.coord.grayhealth import GrayHealth
    from distributed_ml_pytorch_tpu.parallel.sharded_ps import (
        ShardedAsynchronous,
    )
    from distributed_ml_pytorch_tpu.utils.serialization import (
        ravel_model_params,
    )

    assert n_shards >= 2, "gray_drill needs a healthy shard (n_shards >= 2)"
    if fixture is not None:
        x, y, grad_fn, params0 = fixture
    else:
        x, y, grad_fn, params0 = _default_fixture(seed)
    flat0 = np.asarray(ravel_model_params(params0), np.float32)
    n_params = int(flat0.shape[0])
    # the victim is shard server 0 — the one star that carries the chaos
    # plan, so the windowed gray rules land on ITS pull channels
    victim_rank = 1

    log = ChaosLog()
    the_plan = plan if plan is not None else default_gray_plan(
        seed, n_workers=n_workers, gray_from=gray_from,
        gray_until=gray_until)
    agent_rank = 1 + n_shards + n_workers
    coord_world = InProcessTransport.create_world(2 + n_shards + n_workers)
    star_chaos: List[Dict[int, FaultyTransport]] = []
    for i in range(n_shards):
        world = InProcessTransport.create_world(1 + n_workers)
        hub = FaultyTransport(
            world[0], the_plan if i == 0 else ChaosPlan(seed=seed), log=log)
        star = {0: hub}
        for r in range(1, 1 + n_workers):
            star[r] = hub.sibling(world[r])
        star_chaos.append(star)

    def make_server_transport(i: int) -> ReliableTransport:
        return ReliableTransport(
            star_chaos[i][0], ack_timeout=0.05, max_backoff=0.25,
            max_retries=120, unreliable_codes=DRILL_UNRELIABLE,
            ack_on_delivery=False)

    rel_workers: List[Dict[int, ReliableTransport]] = []
    for i in range(n_shards):
        rel_workers.append({
            j: ReliableTransport(
                star_chaos[i][j], ack_timeout=0.05, max_backoff=0.25,
                max_retries=120, unreliable_codes=DRILL_UNRELIABLE)
            for j in range(1, 1 + n_workers)})

    manifest_path = os.path.join(base_dir, MANIFEST_NAME)
    coord = Coordinator(
        coord_world[0], n_params, lease=lease, speculation=False,
        manifest_dir=base_dir)
    knobs = dict(gray_knobs or {})
    if not contain:
        # the comparison leg: evidence still flows on the renew tails,
        # but the detector can never fire — the episode runs unmanaged
        knobs["raise_threshold"] = 1e9
    gray = GrayHealth(
        coord, actuator_rank=agent_rank,
        confirm_ticks=2, clear_ticks=2, quarantine_after=8,
        quarantine_cooldown=3.0, evict_after_quarantines=2,
        **knobs)
    coord_thread = threading.Thread(
        target=coord.run, kwargs={"timeout": 600}, daemon=True)
    coord_thread.start()

    def start_server(i: int) -> ElasticShardServer:
        client = CoordClient(coord_world[1 + i], "shard",
                             renew_interval=lease / 4)
        srv = ElasticShardServer(
            server_id=1 + i, n_params=n_params,
            transport=make_server_transport(i), coord=client,
            init_params=flat0, ckpt_dir=os.path.join(base_dir, f"shard{i}"),
            ckpt_every=0, wal=True, wal_group_n=wal_group_n)
        t = threading.Thread(target=srv.run, kwargs={"timeout": 600},
                             daemon=True)
        t.start()
        return srv

    servers: List[ElasticShardServer] = [start_server(i)
                                         for i in range(n_shards)]
    retired_servers: List[ElasticShardServer] = []
    _wait_for(lambda: len(coord.shard_map.entries) == n_shards, 60,
              "all shard servers to join the map")

    # --- the node agent: gray quarantine resumes land here --------------
    violations: List[str] = []
    resumed_info = {"replayed": 0, "bit_identical": None}
    resume_failed = threading.Event()
    resume_jobs: List[tuple] = []
    resume_ready = threading.Event()
    agent = CoordClient(coord_world[agent_rank], "agent",
                        renew_interval=lease / 4)

    def on_resume(grant_id, rank, snapshot_id):
        resume_jobs.append((grant_id, rank, snapshot_id))
        resume_ready.set()

    agent.on_resume = on_resume
    agent.join(timeout=30)

    def do_resume(grant_id: int, rank: int, snapshot_id: int) -> None:
        i = rank - 1
        old = servers[i]
        try:
            if snapshot_id <= 0 or not os.path.exists(manifest_path):
                raise FileNotFoundError(
                    f"no manifest for snapshot {snapshot_id}")
            manifest = FleetManifest.load(manifest_path)
            detach = getattr(old.transport, "detach", None)
            if detach is not None:
                detach()
            client = CoordClient(coord_world[1 + i], "shard",
                                 renew_interval=lease / 4)
            srv = ElasticShardServer(
                server_id=1 + i, n_params=n_params,
                transport=make_server_transport(i), coord=client,
                init_params=flat0,
                ckpt_dir=os.path.join(base_dir, f"shard{i}"),
                ckpt_every=0, wal=True, wal_group_n=wal_group_n)
            srv.restore_from_manifest(manifest)
            resumed_info["replayed"] += srv.ps.replayed_updates
            lo, hi = old.lo, old.hi
            identical = (
                np.array_equal(np.asarray(old.ps.central[lo:hi]),
                               np.asarray(srv.ps.central[lo:hi]))
                and srv.ps._apply_seq == old.ps._apply_seq
                and dict(srv.ps.applied_by_sender)
                == dict(old.ps.applied_by_sender))
            resumed_info["bit_identical"] = identical
            if not identical:
                violations.append(
                    f"gray resume of rank {rank} not bit-identical: parked "
                    f"apply_seq {old.ps._apply_seq} vs restored "
                    f"{srv.ps._apply_seq}")
            retired_servers.append(old)
            servers[i] = srv
            threading.Thread(target=srv.run, kwargs={"timeout": 600},
                             daemon=True).start()
        except Exception as e:  # noqa: BLE001 — the violation IS the result
            violations.append(
                f"gray resume lost acked state: rank {rank} parked without "
                f"a usable manifest ({e!r})")
            resume_failed.set()

    def agent_loop() -> None:
        while not agent_stop.is_set():
            if not resume_ready.wait(0.05):
                continue
            resume_ready.clear()
            while resume_jobs:
                do_resume(*resume_jobs.pop(0))

    agent_stop = threading.Event()
    agent_thread = threading.Thread(target=agent_loop, daemon=True)
    agent_thread.start()

    timings: Dict[str, float] = {}
    losses: Dict[int, list] = {}
    errors: list = []

    def recovered() -> bool:
        from distributed_ml_pytorch_tpu.coord.grayhealth import OK as G_OK

        return ((gray.recoveries >= 1
                 and gray.state_of(victim_rank) == G_OK)
                or gray.evictions >= 1 or resume_failed.is_set())

    def run_worker(j: int) -> None:
        try:
            _run_worker(j)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            errors.append((j, repr(e)))

    def _run_worker(j: int) -> None:
        client = CoordClient(coord_world[n_shards + j], "worker",
                             renew_interval=lease / 4)
        m = client.join(timeout=30)
        assert m is not None and m.entries, "worker never got a shard map"
        factory = lambda entry: rel_workers[entry.server_id - 1][j]
        params = jax.tree.map(jnp.asarray, params0)
        opt = ShardedAsynchronous(
            params, lr=lr, n_push=n_push, n_pull=n_pull,
            transports=[factory(e) for e in m.entries],
            coord=client, transport_factory=factory, shard_map=m)
        rng = jax.random.key(100 + j)
        my_losses = losses.setdefault(j, [])
        step = 0
        # fixed script, then a bounded flexible tail: keep the renew /
        # pull / evidence cadence alive until the ladder clears (every
        # chaos rule is index-windowed, so the tail cannot touch the log)
        while step < steps or (contain and step < steps + extra_steps
                               and not recovered()):
            sel = np.random.default_rng(j * 1000 + step).integers(
                0, len(x), batch)
            loss, grads = grad_fn(params, x[sel], y[sel],
                                  jax.random.fold_in(rng, step))
            params = opt.step(params, grads)
            my_losses.append(float(loss))
            time.sleep(step_sleep)
            step += 1
            if step == steps:
                # the fixed script is the same work on every leg; its
                # completion time is the goodput denominator the bench
                # compares containment-on vs -off with (the flexible
                # recovery tail would otherwise pad the ratio)
                timings[f"fixed_done_w{j}"] = time.monotonic()
        opt.finish()
        client.close()

    worker_threads = [threading.Thread(target=run_worker, args=(j,),
                                       daemon=True)
                      for j in range(1, n_workers + 1)]
    timings["day_start"] = time.monotonic()
    for t in worker_threads:
        t.start()
    for t in worker_threads:
        t.join(timeout=600)
    timings["day_end"] = time.monotonic()
    stuck = [t for t in worker_threads if t.is_alive()]
    agent_stop.set()
    agent_thread.join(timeout=10)
    for srv in servers:
        srv.stop()
    time.sleep(0.05)
    agent.close()
    coord.stop()
    coord_thread.join(timeout=30)

    # ---- the gray contract: contained, never killed --------------------
    if contain:
        if gray.probations < 1:
            violations.append(
                "gray victim was never detected (no probation)")
        if gray.quarantines < 1:
            violations.append(
                "gray victim was never contained (no quarantine)")
        if gray.evictions > 0:
            violations.append(
                f"gray plane EVICTED {gray.evictions} member(s) — "
                "containment must degrade, not kill")
        if gray.recoveries < 1 and not resume_failed.is_set():
            violations.append(
                "quarantined victim never earned its way back")
    expiry = [e for e in coord.events
              if "lease expired" in e and f" {victim_rank} " in e]
    if expiry:
        violations.append(
            f"renewing-but-gray victim lease-expired: {expiry[0]!r}")

    # ---- per-(worker, shard) accounting: every acked push applied ------
    acked: Dict[int, Dict[int, int]] = {}
    applied: Dict[int, Dict[int, int]] = {}
    for i in range(n_shards):
        acked[i] = {j: (rel_workers[i][j].acked_count(
            0, MessageCode.ShardPush) + rel_workers[i][j].acked_count(
            0, MessageCode.GradientUpdate) + rel_workers[i][j].acked_count(
            0, MessageCode.CompressedUpdate))
            for j in range(1, 1 + n_workers)}
        applied[i] = {j: servers[i].ps.applied_by_sender.get(j, 0)
                      for j in range(1, 1 + n_workers)}
        for j in range(1, 1 + n_workers):
            if acked[i][j] > applied[i][j]:
                violations.append(
                    f"acked delta lost: shard {i} worker {j}: acked "
                    f"{acked[i][j]} > applied {applied[i][j]}")

    for star in rel_workers:
        for t in star.values():
            t.close()
    for srv in servers:
        close = getattr(srv.transport, "close", None)
        if close is not None:
            close()
    for t in coord_world.values():
        t.close()

    gstats = gray.stats()
    return {
        "ok": not stuck and not errors and not violations,
        "violations": violations,
        "errors": errors,
        "stuck_workers": len(stuck),
        "losses": losses,
        "acked": acked,
        "applied": applied,
        "replayed_updates": resumed_info["replayed"],
        "bit_identical": resumed_info["bit_identical"],
        "gray": gstats,
        "detect_latency_s": (gstats["detection_latencies"][0]
                             if gstats["detection_latencies"] else None),
        "containment_mttr_s": (gstats["containment_mttrs"][0]
                               if gstats["containment_mttrs"] else None),
        "events": list(coord.events),
        "chaos_lines": log.lines(),
        "chaos_counts": log.counts(),
        "wall_s": timings["day_end"] - timings["day_start"],
        "fixed_wall_s": (max(timings[k] for k in timings
                             if k.startswith("fixed_done_w"))
                         - timings["day_start"]
                         if any(k.startswith("fixed_done_w")
                                for k in timings) else None),
        "servers": servers,
    }


def coordfail_drill(
    *,
    base_dir: str,
    seed: int = 0,
    steps: int = 20,
    snapshot_at: Optional[int] = 4,
    kill_at: Optional[int] = 8,
    outage_steps: int = 3,
    verify_at: Optional[int] = None,
    kill_during: str = "snapshot",
    n_workers: int = 2,
    n_shards: int = 2,
    plan: Optional[ChaosPlan] = None,
    lease: float = 2.0,
    grace: Optional[float] = None,
    lr: float = 0.05,
    n_push: int = 2,
    n_pull: int = 2,
    batch: int = 16,
    wal_group_n: int = 4,
    fixture=None,
    step_sleep: float = 0.05,
) -> Dict:
    """Kill the COORDINATOR mid-flight and prove the fleet survives it
    (ISSUE 17 tentpole acceptance).

    The control plane finally becomes a crashable rank: the coordinator's
    transport is chaos-wrapped (``FaultyTransport`` sharing the drill's
    ``ChaosLog``), and worker 1's step script crashes it silently — serve
    loop dead, members' control frames raising like dead sockets — while
    the data plane keeps training fail-open on the last shard map.

    ``kill_during="snapshot"`` crashes the hub right after it broadcasts a
    snapshot barrier (``SnapshotRequest`` in flight, ``SnapshotDone``
    frames landing on a dead socket); the restarted life must drive a NEW
    barrier to a published manifest. ``kill_during="preempt"`` spikes a
    serving tenant first and crashes the hub with one preemption in
    flight — the victim shard parked (WAL'd park table), its slot granted
    away — and the restarted life must neither strand the parked member
    nor double-grant its slot, then resume it when demand drops.

    Restart = a fresh ``Coordinator`` over the same ``durable_dir``:
    epoch bumped (every outbound frame of the old life is now
    stale-fenced), member table / map version / scheduler ledger / park
    table replayed from checkpoint + WAL, and a restart grace window that
    suspends lease expiry until join-retry traffic re-populates liveness
    — the drill asserts NO member is evicted across the outage.

    Determinism: chaos rides star 0's pull channel only (the
    ``sched_drill`` scoping argument) and the coordinator world carries
    no fault rules — its death is the step-scripted ``crash()``, and
    sends to a crashed rank raise BEFORE any channel draw or log record,
    so outage-window retry traffic cannot perturb the log. The
    acceptance test asserts byte-identical chaos lines 3x.

    Control-plane MTTR = crash → every live member re-attached to the
    new life (the grace window closed by traffic, not timeout).
    """
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.parallel.sharded_ps import (
        ShardedAsynchronous,
    )
    from distributed_ml_pytorch_tpu.utils.serialization import (
        ravel_model_params,
    )

    assert kill_during in ("snapshot", "preempt"), kill_during
    with_sched = kill_during == "preempt"
    if with_sched:
        assert n_shards >= 2, "preempt variant needs a survivor shard"
    if verify_at is None and kill_at is not None:
        verify_at = kill_at + outage_steps + 3
    if fixture is not None:
        x, y, grad_fn, params0 = fixture
    else:
        x, y, grad_fn, params0 = _default_fixture(seed)
    flat0 = np.asarray(ravel_model_params(params0), np.float32)
    n_params = int(flat0.shape[0])
    victim = n_shards - 1          # the scheduler's _pick_victim order
    victim_rank = 1 + victim

    TRAIN_ID, SERVE_ID = 1, 2

    log = ChaosLog()
    the_plan = plan if plan is not None else default_drill_plan(seed)
    agent_rank = 1 + n_shards + n_workers
    coord_world = InProcessTransport.create_world(
        (2 if with_sched else 1) + n_shards + n_workers)
    # the tentpole wiring: the COORDINATOR is a crashable chaos rank now,
    # sharing the drill's fault log; members reach it through siblings of
    # the same wrapper, so its scripted death is a dead socket fleet-wide
    coord_hub = FaultyTransport(coord_world[0], ChaosPlan(seed=seed),
                                log=log)
    coord_star: Dict[int, FaultyTransport] = {0: coord_hub}
    for r in coord_world:
        if r != 0:
            coord_star[r] = coord_hub.sibling(coord_world[r])

    # data-plane stars: chaos scoped to star 0 only (whose shard is never
    # parked) so the log stays a pure function of the step script
    star_chaos: List[Dict[int, FaultyTransport]] = []
    for i in range(n_shards):
        world = InProcessTransport.create_world(1 + n_workers)
        hub = FaultyTransport(
            world[0], the_plan if i == 0 else ChaosPlan(seed=seed), log=log)
        star = {0: hub}
        for r in range(1, 1 + n_workers):
            star[r] = hub.sibling(world[r])
        star_chaos.append(star)

    def make_server_transport(i: int) -> ReliableTransport:
        return ReliableTransport(
            star_chaos[i][0], ack_timeout=0.05, max_backoff=0.25,
            max_retries=120, unreliable_codes=DRILL_UNRELIABLE,
            ack_on_delivery=False)

    rel_workers: List[Dict[int, ReliableTransport]] = []
    for i in range(n_shards):
        rel_workers.append({
            j: ReliableTransport(
                star_chaos[i][j], ack_timeout=0.05, max_backoff=0.25,
                max_retries=120, unreliable_codes=DRILL_UNRELIABLE)
            for j in range(1, 1 + n_workers)})

    manifest_path = os.path.join(base_dir, MANIFEST_NAME)
    coord_dir = os.path.join(base_dir, "coord")

    def make_coordinator() -> Coordinator:
        return Coordinator(
            coord_hub, n_params, lease=lease, speculation=False,
            manifest_dir=base_dir, durable_dir=coord_dir, grace=grace)

    def make_scheduler(c: Coordinator):
        from distributed_ml_pytorch_tpu.coord.sched import FleetScheduler

        return FleetScheduler(
            c, registry=registry, require_manifest=True,
            actuator_rank=agent_rank, preempt_timeout=60.0,
            resume_timeout=60.0)

    registry = None
    coord = make_coordinator()
    life: Dict[str, object] = {"coord": coord}
    if with_sched:
        from distributed_ml_pytorch_tpu.coord.tenants import (
            TENANT_SERVING,
            TENANT_TRAINING,
            Tenant,
            TenantRegistry,
        )

        registry = TenantRegistry()
        registry.register(Tenant(TRAIN_ID, "train", kind=TENANT_TRAINING,
                                 priority=1, demand=n_shards,
                                 min_slots=n_shards - 1))
        registry.register(Tenant(SERVE_ID, "serve", kind=TENANT_SERVING,
                                 priority=5, demand=0))
        life["sched"] = make_scheduler(coord)
        for i in range(n_shards):
            life["sched"].register_member_slot(1 + i, TRAIN_ID)
    coord_thread = threading.Thread(
        target=coord.run, kwargs={"timeout": 600}, daemon=True)
    coord_thread.start()
    life["thread"] = coord_thread

    def start_server(i: int) -> ElasticShardServer:
        client = CoordClient(coord_star[1 + i], "shard",
                             renew_interval=lease / 4)
        srv = ElasticShardServer(
            server_id=1 + i, n_params=n_params,
            transport=make_server_transport(i), coord=client,
            init_params=flat0, ckpt_dir=os.path.join(base_dir, f"shard{i}"),
            ckpt_every=0, wal=True, wal_group_n=wal_group_n)
        t = threading.Thread(target=srv.run, kwargs={"timeout": 600},
                             daemon=True)
        t.start()
        return srv

    servers: List[ElasticShardServer] = [start_server(i)
                                         for i in range(n_shards)]
    retired_servers: List[ElasticShardServer] = []
    _wait_for(lambda: len(coord.shard_map.entries) == n_shards, 60,
              "all shard servers to join the map")

    # the live ranks that must RE-ATTACH to the restarted life (a parked
    # victim is durable-park-exempt, not re-attaching)
    expected_live = set(range(1, 1 + n_shards + n_workers))
    if with_sched:
        expected_live.add(agent_rank)
        expected_live.discard(victim_rank)

    timings: Dict[str, float] = {}
    losses: Dict[int, list] = {}
    opts: Dict[int, object] = {}
    errors: list = []
    violations: List[str] = []
    grants: List[tuple] = []
    member_epochs: Dict[int, int] = {}
    stale_drops: Dict[int, int] = {}
    resumed_info = {"replayed": 0, "bit_identical": None}
    resume_failed = threading.Event()
    restored_evt = threading.Event()
    verify_done = threading.Event()
    hold_evt = threading.Event()
    release_evt = threading.Event()
    held = {j: False for j in range(1, 1 + n_workers)}
    if kill_at is None:
        restored_evt.set()
        verify_done.set()

    # --- the agent (preempt variant): grants/resumes land here ----------
    agent = None
    agent_stop = threading.Event()
    if with_sched:
        resume_jobs: List[tuple] = []
        resume_ready = threading.Event()
        agent = CoordClient(coord_star[agent_rank], "agent",
                            renew_interval=lease / 4)

        def on_slot_grant(grant_id, tenant_id, action, slot_id):
            grants.append((grant_id, tenant_id, action, slot_id))

        def on_resume(grant_id, rank, snapshot_id):
            resume_jobs.append((grant_id, rank, snapshot_id))
            resume_ready.set()

        agent.on_slot_grant = on_slot_grant
        agent.on_resume = on_resume
        agent.join(timeout=30)

        def do_resume(grant_id: int, rank: int, snapshot_id: int) -> None:
            i = rank - 1
            old = servers[i]
            try:
                if snapshot_id <= 0 or not os.path.exists(manifest_path):
                    raise FileNotFoundError(
                        f"no manifest for snapshot {snapshot_id}")
                manifest = FleetManifest.load(manifest_path)
                detach = getattr(old.transport, "detach", None)
                if detach is not None:
                    detach()
                client = CoordClient(coord_star[1 + i], "shard",
                                     renew_interval=lease / 4)
                srv = ElasticShardServer(
                    server_id=1 + i, n_params=n_params,
                    transport=make_server_transport(i), coord=client,
                    init_params=flat0,
                    ckpt_dir=os.path.join(base_dir, f"shard{i}"),
                    ckpt_every=0, wal=True, wal_group_n=wal_group_n)
                srv.restore_from_manifest(manifest)
                resumed_info["replayed"] += srv.ps.replayed_updates
                lo, hi = old.lo, old.hi
                identical = (
                    np.array_equal(np.asarray(old.ps.central[lo:hi]),
                                   np.asarray(srv.ps.central[lo:hi]))
                    and srv.ps._apply_seq == old.ps._apply_seq
                    and dict(srv.ps.applied_by_sender)
                    == dict(old.ps.applied_by_sender))
                resumed_info["bit_identical"] = identical
                if not identical:
                    violations.append(
                        f"resume of rank {rank} not bit-identical across "
                        f"the coordinator restart")
                retired_servers.append(old)
                servers[i] = srv
                threading.Thread(target=srv.run, kwargs={"timeout": 600},
                                 daemon=True).start()
            except Exception as e:  # noqa: BLE001 — the violation IS the result
                violations.append(
                    f"resume lost the parked member: rank {rank} ({e!r})")
                resume_failed.set()

        def agent_loop() -> None:
            while not agent_stop.is_set():
                if not resume_ready.wait(0.05):
                    continue
                resume_ready.clear()
                while resume_jobs:
                    do_resume(*resume_jobs.pop(0))

        agent_thread = threading.Thread(target=agent_loop, daemon=True)
        agent_thread.start()

    # --- coordinator life management ------------------------------------
    def kill_coordinator() -> None:
        # reap the serve loop FIRST (stop() sends nothing — a silent
        # death), then crash the endpoint so every member's control
        # frames raise like a dead socket; the tiny stop->crash gap only
        # queues frames nobody will read
        life["coord"].stop()
        life["thread"].join(timeout=30)
        coord_hub.crash()
        timings["killed"] = time.monotonic()
        timings["map_version_at_kill"] = life["coord"].shard_map.version

    def restore_coordinator() -> None:
        t0 = time.monotonic()
        coord_hub.restart()
        c2 = make_coordinator()
        if with_sched:
            life["sched2"] = make_scheduler(c2)
        t = threading.Thread(target=c2.run, kwargs={"timeout": 600},
                             daemon=True)
        life["coord2"], life["thread2"] = c2, t
        t.start()
        timings["restored"] = time.monotonic()
        timings["restore_s"] = timings["restored"] - t0

    # MTTR watcher: re-attached = the restarted life's grace window was
    # closed by join-retry TRAFFIC (grace_pending drained) and every
    # expected live rank is in its member table
    def watch_reattach() -> None:
        while "killed" not in timings:
            if watch_stop.wait(0.02):
                return
        while not watch_stop.is_set():
            c2 = life.get("coord2")
            if (c2 is not None and not c2._grace_pending
                    and expected_live <= set(c2.members)):
                timings["reattached"] = time.monotonic()
                return
            watch_stop.wait(0.02)

    watch_stop = threading.Event()
    watcher = None
    if kill_at is not None:
        watcher = threading.Thread(target=watch_reattach, daemon=True)
        watcher.start()

    def _follow(j: int) -> None:
        if hold_evt.is_set() and not release_evt.is_set() and not held[j]:
            opts[j].hold_shard(1 + victim)
            held[j] = True
        if release_evt.is_set() and held[j] and not resume_failed.is_set():
            opts[j].release_shard(1 + victim)
            held[j] = False

    def step_hook(j: int, step: int) -> None:
        time.sleep(step_sleep)
        sched = life.get("sched")
        if j != 1:
            if kill_at is not None and step == verify_at:
                # the fleet must OUTLIVE the verify window (a finished
                # worker leaves, and "everyone re-attached" needs everyone)
                verify_done.wait(300)
                if with_sched:
                    release_evt.wait(300)
            if with_sched:
                _follow(j)
            return
        if not with_sched and snapshot_at is not None and step == snapshot_at:
            life["coord"].trigger_snapshot()
            _wait_for(lambda: os.path.exists(manifest_path)
                      and life["coord"].manifests_written > 0, 60,
                      "the pre-kill snapshot barrier to publish")
        if with_sched and kill_at is not None and step == snapshot_at:
            timings["peak"] = time.monotonic()
            registry.set_demand(SERVE_ID, 1)
        if with_sched and sched is not None and snapshot_at < step \
                and not hold_evt.is_set() and sched.preempts_done > 0:
            hold_evt.set()
        if kill_at is not None:
            if step == kill_at:
                if with_sched:
                    # mid-preemption: the victim is parked (its park WAL'd
                    # by the doomed life), the serving grant outstanding
                    _wait_for(lambda: sched.preempts_done > 0
                              or sched.preempts_aborted > 0, 120,
                              "the preempt to park the victim")
                    hold_evt.set()
                    _follow(1)
                else:
                    # mid-barrier: SnapshotRequest broadcast, then death —
                    # every SnapshotDone lands on a dead socket
                    life["coord"].trigger_snapshot()
                kill_coordinator()
            elif step == kill_at + outage_steps:
                try:
                    restore_coordinator()
                finally:
                    restored_evt.set()
            elif step == verify_at:
                try:
                    _wait_for(lambda: "reattached" in timings, 120,
                              "the fleet to re-attach to the new life")
                    if with_sched:
                        timings["offpeak"] = time.monotonic()
                        registry.set_demand(SERVE_ID, 0)
                        _wait_for(
                            lambda: life["sched2"].resumes_done > 0
                            or resume_failed.is_set(), 120,
                            "the restarted life to resume the parked rank")
                        release_evt.set()
                    else:
                        # the restarted life must drive a barrier of its
                        # OWN to a published manifest
                        life["coord2"].trigger_snapshot()
                        _wait_for(
                            lambda: life["coord2"].manifests_written > 0,
                            60, "a post-restart snapshot to publish")
                finally:
                    verify_done.set()
                    if with_sched:
                        release_evt.set()
        if with_sched:
            _follow(1)

    def run_worker(j: int) -> None:
        try:
            _run_worker(j)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            errors.append((j, repr(e)))
            verify_done.set()
            release_evt.set()

    def _run_worker(j: int) -> None:
        client = CoordClient(coord_star[n_shards + j], "worker",
                             renew_interval=lease / 4)
        m = client.join(timeout=30)
        assert m is not None and m.entries, "worker never got a shard map"
        factory = lambda entry: rel_workers[entry.server_id - 1][j]
        params = jax.tree.map(jnp.asarray, params0)
        opt = ShardedAsynchronous(
            params, lr=lr, n_push=n_push, n_pull=n_pull,
            transports=[factory(e) for e in m.entries],
            coord=client, transport_factory=factory, shard_map=m)
        opts[j] = opt
        rng = jax.random.key(100 + j)
        my_losses = losses.setdefault(j, [])
        for step in range(steps):
            sel = np.random.default_rng(j * 1000 + step).integers(
                0, len(x), batch)
            loss, grads = grad_fn(params, x[sel], y[sel],
                                  jax.random.fold_in(rng, step))
            params = opt.step(params, grads)
            my_losses.append(float(loss))
            step_hook(j, step)
        opt.finish()
        member_epochs[n_shards + j] = client.coord_epoch
        stale_drops[n_shards + j] = client.stale_epoch_dropped
        client.close()

    worker_threads = [threading.Thread(target=run_worker, args=(j,),
                                       daemon=True)
                      for j in range(1, n_workers + 1)]
    for t in worker_threads:
        t.start()
    for t in worker_threads:
        t.join(timeout=600)
    stuck = [t for t in worker_threads if t.is_alive()]
    watch_stop.set()
    if watcher is not None:
        watcher.join(timeout=10)
    if with_sched:
        agent_stop.set()
        member_epochs[agent_rank] = agent.coord_epoch
        stale_drops[agent_rank] = agent.stale_epoch_dropped
        agent.close()
    for srv in servers:
        c = getattr(srv, "coord", None)
        if isinstance(c, CoordClient):
            member_epochs[srv.server_id] = c.coord_epoch
            stale_drops[srv.server_id] = c.stale_epoch_dropped
        srv.stop()
    time.sleep(0.05)
    final = life.get("coord2") or life["coord"]
    final.stop()
    for key in ("thread", "thread2"):
        t = life.get(key)
        if t is not None:
            t.join(timeout=30)

    # ---- sequence accounting (unchanged contract: acked <= applied) ----
    acked: Dict[int, Dict[int, int]] = {}
    applied: Dict[int, Dict[int, int]] = {}
    for i in range(n_shards):
        acked[i] = {j: (rel_workers[i][j].acked_count(
            0, MessageCode.ShardPush) + rel_workers[i][j].acked_count(
            0, MessageCode.GradientUpdate) + rel_workers[i][j].acked_count(
            0, MessageCode.CompressedUpdate))
            for j in range(1, 1 + n_workers)}
        applied[i] = {j: servers[i].ps.applied_by_sender.get(j, 0)
                      for j in range(1, 1 + n_workers)}
        for j in range(1, 1 + n_workers):
            if acked[i][j] > applied[i][j]:
                violations.append(
                    f"acked delta lost: shard {i} worker {j}: acked "
                    f"{acked[i][j]} > applied {applied[i][j]}")
    accounting_ok = not any(v.startswith("acked delta") for v in violations)
    if with_sched and "sched2" in life:
        violations.extend(life["sched2"].ledger.audit())

    for star in rel_workers:
        for t in star.values():
            t.close()
    for srv in servers:
        close = getattr(srv.transport, "close", None)
        if close is not None:
            close()
    for t in coord_world.values():
        t.close()

    coord2 = life.get("coord2")
    events2 = list(coord2.events) if coord2 is not None else []
    evictions = [e for e in list(life["coord"].events) + events2
                 if "lease expired" in e]
    mttr = (timings["reattached"] - timings["killed"]
            if "reattached" in timings and "killed" in timings else None)
    ok = (not stuck and not errors and not violations and accounting_ok
          and not evictions)
    if kill_at is not None:
        ok = ok and coord2 is not None and mttr is not None \
            and coord2.epoch == life["coord"].epoch + 1 \
            and coord2.shard_map.version >= timings["map_version_at_kill"]
        if with_sched:
            ok = ok and life["sched2"].resumes_done > 0 \
                and bool(resumed_info["bit_identical"])
        else:
            ok = ok and coord2.manifests_written > 0
    return {
        "ok": ok,
        "errors": errors,
        "stuck_workers": len(stuck),
        "violations": violations,
        "losses": losses,
        "acked": acked,
        "applied": applied,
        "accounting_ok": accounting_ok,
        "evictions": evictions,
        "epochs": (life["coord"].epoch,
                   coord2.epoch if coord2 is not None else None),
        "map_versions": (timings.get("map_version_at_kill"),
                         (coord2 or life["coord"]).shard_map.version),
        "restored_members": (coord2.restored_members
                             if coord2 is not None else 0),
        "member_epochs": member_epochs,
        "stale_epoch_dropped": sum(stale_drops.values()),
        "manifests_written": (life["coord"].manifests_written,
                              coord2.manifests_written
                              if coord2 is not None else None),
        "grants": grants,
        "resumes_done": (life["sched2"].resumes_done
                         if with_sched and "sched2" in life else None),
        "bit_identical": resumed_info["bit_identical"],
        "replayed_updates": resumed_info["replayed"],
        "chaos_lines": log.lines(),
        "chaos_counts": log.counts(),
        "events": list(life["coord"].events),
        "events2": events2,
        "mttr_s": mttr,
        "outage_s": (timings["restored"] - timings["killed"]
                     if "restored" in timings and "killed" in timings
                     else None),
        "restore_s": timings.get("restore_s"),
        "servers": servers,
    }


def sched_demo(seed: int = 0, base_dir: Optional[str] = None) -> Dict:
    """One self-contained scheduler pass (``coord/cli.py --sched-demo``)."""
    import tempfile

    base = base_dir or tempfile.mkdtemp(prefix="sched_")
    out = sched_drill(base_dir=base, seed=seed,
                      plan=default_drill_plan(seed))
    return {
        "ok": out["ok"] and out["replayed_updates"] > 0,
        "violations": out["violations"],
        "preempt_mttr_s": out["sched"]["preempt_mttr_s"],
        "resume_mttr_s": out["sched"]["resume_mttr_s"],
        "replayed_updates": out["replayed_updates"],
        "bit_identical": out["bit_identical"],
        "acked": out["acked"],
        "applied": out["applied"],
        "held_pushes": out["held_pushes"],
        "grants": out["grants"],
        "events": out["events"],
        "chaos": out["chaos_counts"],
        "state_dir": base,
    }


def drill_demo(seed: int = 0, base_dir: Optional[str] = None) -> Dict:
    """One self-contained drill pass (``coord/cli.py --drill``)."""
    import tempfile

    base = base_dir or tempfile.mkdtemp(prefix="drill_")
    out = recovery_drill(base_dir=base, seed=seed,
                         plan=default_drill_plan(seed))
    return {
        # > 0: the drill must actually have exercised WAL replay (acked
        # updates that ONLY the logs held), or "ok" proves nothing
        "ok": out["ok"] and out["replayed_updates"] > 0,
        "mttr_s": out["mttr_s"],
        "restore_s": out["restore_s"],
        "replayed_updates": out["replayed_updates"],
        "acked": out["acked"],
        "applied": out["applied"],
        "chaos": out["chaos_counts"],
        "events": out["events"],
        "manifest": out["manifest"],
        "state_dir": base,
    }
