"""Fleet snapshot manifests — WHAT a coordinator-aligned snapshot proves
(ISSUE 5 tentpole).

Per-shard checkpoints alone cannot restore a fleet: each shard used to
checkpoint on its own clock, so a multi-shard crash restored shard A at
version 900 next to shard B at version 400 with nothing even detecting the
skew. The snapshot barrier (``Coordinator.trigger_snapshot`` →
``SnapshotRequest``/``SnapshotDone``) stamps one snapshot id, has every live
shard checkpoint at its next version boundary, and assembles the reports
into a :class:`FleetManifest` — the single file that says "these shard
checkpoints, at these ranges, under this shard-map version, form one
consistent fleet state".

Restore goes through :meth:`FleetManifest.load`, which REFUSES bad
manifests loudly:

- ``incomplete`` — the barrier never finished (``complete`` is false), or
  the recorded ranges do not tile ``[0, n_params)`` exactly;
- ``mixed`` — a shard record stamped with a different shard-map version
  than the manifest's (exactly the version-900-next-to-version-400 state
  the barrier exists to prevent).

``ElasticShardServer.restore_from_manifest`` re-installs its range from the
manifest's shard map and then restores checkpoint + WAL; a missing or
range-mismatched checkpoint raises rather than serving zeros as central
params.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Tuple

from distributed_ml_pytorch_tpu.coord.shardmap import ShardEntry, ShardMap
from distributed_ml_pytorch_tpu.utils.durability import atomic_write

MANIFEST_NAME = "fleet_manifest.json"


class ManifestError(ValueError):
    """A manifest that must not be restored from (incomplete / mixed /
    malformed) — always raised loudly, never degraded around."""


@dataclasses.dataclass(frozen=True)
class ShardRecord:
    """One shard's report into the barrier: its range under the snapshot's
    map version, and the checkpoint clock it persisted."""

    server_id: int
    lo: int
    hi: int
    map_version: int
    apply_seq: int
    push_count: int


@dataclasses.dataclass(frozen=True)
class FleetManifest:
    """A complete, mutually-consistent fleet snapshot."""

    snapshot_id: int
    map_version: int
    n_params: int
    shards: Tuple[ShardRecord, ...]
    complete: bool = True

    def validate(self) -> "FleetManifest":
        if not self.complete:
            raise ManifestError(
                f"manifest for snapshot {self.snapshot_id} is incomplete — "
                "the barrier never finished; refusing to restore from it")
        if not self.shards:
            raise ManifestError(
                f"manifest for snapshot {self.snapshot_id} records no "
                "shards")
        ids = [s.server_id for s in self.shards]
        if len(set(ids)) != len(ids):
            raise ManifestError(
                f"manifest for snapshot {self.snapshot_id} records server "
                f"ids more than once: {sorted(ids)}")
        mixed = {s.server_id: s.map_version for s in self.shards
                 if s.map_version != self.map_version}
        if mixed:
            raise ManifestError(
                f"MIXED manifest for snapshot {self.snapshot_id}: map "
                f"version {self.map_version} but shard records at {mixed} "
                "— a cross-version restore would resurrect exactly the "
                "inconsistent fleet the barrier exists to prevent")
        spans = sorted((s.lo, s.hi) for s in self.shards)
        cursor = 0
        for lo, hi in spans:
            if lo != cursor or hi <= lo:
                raise ManifestError(
                    f"manifest for snapshot {self.snapshot_id} does not "
                    f"tile [0, {self.n_params}): gap/overlap at "
                    f"[{lo}, {hi}) vs cursor {cursor}")
            cursor = hi
        if cursor != self.n_params:
            raise ManifestError(
                f"manifest for snapshot {self.snapshot_id} covers "
                f"[0, {cursor}) of {self.n_params} params — incomplete")
        return self

    def entry_for(self, server_id: int) -> ShardRecord:
        for s in self.shards:
            if s.server_id == int(server_id):
                return s
        raise ManifestError(
            f"manifest for snapshot {self.snapshot_id} has no record for "
            f"server {server_id} — this shard is not part of the restored "
            "fleet")

    @property
    def shard_map(self) -> ShardMap:
        """The shard map this snapshot was taken under (ranges only — the
        fresh/install bookkeeping belongs to live rebalances)."""
        return ShardMap(
            self.map_version, self.n_params,
            [ShardEntry(s.server_id, s.lo, s.hi)
             for s in sorted(self.shards, key=lambda s: s.lo)])

    # ------------------------------------------------------------------ io
    def to_dict(self) -> Dict:
        return {
            "snapshot_id": self.snapshot_id,
            "map_version": self.map_version,
            "n_params": self.n_params,
            "complete": self.complete,
            "shards": [dataclasses.asdict(s) for s in self.shards],
        }

    def write(self, path: str) -> None:
        """Atomically + durably publish this manifest (validated first —
        the coordinator must never publish what restore would refuse)."""
        self.validate()
        atomic_write(path, json.dumps(self.to_dict(), indent=1).encode())

    @classmethod
    def from_dict(cls, d: Dict) -> "FleetManifest":
        try:
            shards = tuple(
                ShardRecord(
                    server_id=int(s["server_id"]), lo=int(s["lo"]),
                    hi=int(s["hi"]), map_version=int(s["map_version"]),
                    apply_seq=int(s["apply_seq"]),
                    push_count=int(s["push_count"]))
                for s in d["shards"])
            return cls(
                snapshot_id=int(d["snapshot_id"]),
                map_version=int(d["map_version"]),
                n_params=int(d["n_params"]),
                shards=shards,
                complete=bool(d.get("complete", False)),
            )
        except (KeyError, TypeError, ValueError) as e:
            raise ManifestError(f"malformed manifest: {e!r}") from e

    @classmethod
    def load(cls, path: str) -> "FleetManifest":
        """Read + validate; raises :class:`ManifestError` on anything a
        restore must not trust."""
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ManifestError(f"unreadable manifest at {path}: {e!r}") from e
        return cls.from_dict(d).validate()
