"""Versioned shard maps — WHAT the coordinator renegotiates at runtime.

A :class:`ShardMap` is the single source of truth for how the central flat
parameter vector splits across the live shard servers. It is immutable and
versioned: every membership change that affects shard servers produces a new
map with ``version + 1``, and every consumer (workers' ``ShardedAsynchronous``
clients, the shard servers themselves) cuts over atomically at a step
boundary when it sees a newer version. Every elastic push, pull reply, and
speculative update now carries a stamp — the sender's map version plus the
ABSOLUTE ``[lo,hi)`` the slice was cut for (``MessageCode.ShardPush`` /
``ShardParams`` / the stamped ``SpeculativeUpdate`` head — ISSUE 6's
wire-format upgrade) — and the receiver applies only traffic cut for the
range it currently serves, dropping+counting the rest; slice length
remains a second-line check. In particular the one case a length check
could not see — two versions assigning a server equal-sized ranges at
different offsets (same shard count, moved boundaries: a join and a death
landing in one rebalance) — is now dropped like any other stale traffic,
while a benign version bump whose ranges stayed put (a restore-rejoin)
remains compatible in flight (both regression-tested in
``tests/test_coord.py``).

Each entry also carries the subrange its owner NEWLY acquired in this
version (``fresh_lo``/``fresh_hi``): the handover protocol. A server that
gains parameter range it never held has no authoritative values for it;
whichever worker cuts over first installs its local values for exactly that
subrange (``MessageCode.RangeInstall``, first install wins), and the world
continues from there — the same single-install bootstrap the DownPour
construction path uses, scoped to the moved range.

The map rides the tagged-float32 wire (``MessageCode.ShardMapUpdate``):
every field is split into float32-exact uint16 halves, so Python, TCP and
native endpoints all carry it unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np

from distributed_ml_pytorch_tpu.parallel.sharded_ps import shard_ranges
from distributed_ml_pytorch_tpu.utils.messaging import _join16, _split16


@dataclasses.dataclass(frozen=True)
class ShardEntry:
    """One shard server's assignment in a map version.

    ``server_id`` is the member's stable coordinator-world rank — the handle
    transport factories resolve to a concrete endpoint (in-process: the
    shard's world; TCP: ``base_port + server_id``).
    """

    server_id: int
    lo: int
    hi: int
    fresh_lo: int = 0   # subrange newly acquired in this version ([fresh_lo,
    fresh_hi: int = 0   # fresh_hi) empty when the owner already held it all)

    @property
    def size(self) -> int:
        return self.hi - self.lo

    @property
    def needs_install(self) -> bool:
        return self.fresh_hi > self.fresh_lo


@dataclasses.dataclass(frozen=True)
class ShardMap:
    """An immutable, versioned assignment of the flat vector to servers."""

    version: int
    n_params: int
    entries: Tuple[ShardEntry, ...] = ()

    def __init__(self, version: int, n_params: int,
                 entries: Sequence[ShardEntry] = ()):
        object.__setattr__(self, "version", int(version))
        object.__setattr__(self, "n_params", int(n_params))
        object.__setattr__(self, "entries", tuple(entries))

    @property
    def ranges(self) -> List[Tuple[int, int]]:
        return [(e.lo, e.hi) for e in self.entries]

    def entry_for(self, server_id: int) -> ShardEntry | None:
        for e in self.entries:
            if e.server_id == server_id:
                return e
        return None

    # ------------------------------------------------------------- encoding
    def encode(self) -> np.ndarray:
        head = [float(len(self.entries)), *_split16(self.version),
                *_split16(self.n_params)]
        body: List[float] = []
        for e in self.entries:
            body += [float(e.server_id), *_split16(e.lo), *_split16(e.hi),
                     *_split16(e.fresh_lo), *_split16(e.fresh_hi)]
        return np.asarray(head + body, np.float32)

    @classmethod
    def decode(cls, payload: np.ndarray) -> "ShardMap":
        if payload.size < 5 or not np.isfinite(payload[:5]).all():
            raise ValueError(f"malformed ShardMap frame (size {payload.size})")
        k = int(payload[0])
        version = _join16(payload[1], payload[2])
        n_params = _join16(payload[3], payload[4])
        if k < 0 or payload.size < 5 + 9 * k:
            raise ValueError(
                f"ShardMap frame declares {k} entries but carries "
                f"{payload.size} floats")
        entries = []
        for i in range(k):
            f = payload[5 + 9 * i: 5 + 9 * (i + 1)]
            if not np.isfinite(f).all():
                raise ValueError("non-finite ShardMap entry")
            entries.append(ShardEntry(
                server_id=int(f[0]),
                lo=_join16(f[1], f[2]), hi=_join16(f[3], f[4]),
                fresh_lo=_join16(f[5], f[6]), fresh_hi=_join16(f[7], f[8]),
            ))
        return cls(version, n_params, entries)


def rebalance(prev: ShardMap, live_server_ids: Sequence[int]) -> ShardMap:
    """The next map version: contiguous near-equal ranges over the live
    servers (sorted by id, so the assignment is a pure function of the
    membership set), with each entry's ``fresh`` subrange = the part of its
    new range the server did not already hold — the slice a worker must
    install on cutover.
    """
    ids = sorted(set(int(s) for s in live_server_ids))
    if not ids:
        return ShardMap(prev.version + 1, prev.n_params, ())
    ranges = shard_ranges(prev.n_params, len(ids))
    prev_by_id = {e.server_id: e for e in prev.entries}
    entries = []
    for sid, (lo, hi) in zip(ids, ranges):
        held = prev_by_id.get(sid)
        if held is None:
            fresh = (lo, hi)  # brand-new server: everything is new to it
        else:
            # the overlap [max(lo, held.lo), min(hi, held.hi)) keeps its
            # authoritative server-side values; ONE new contiguous flank is
            # the common case (contiguous ranges over a sorted id set can
            # grow on both flanks only when neighbors vanish on both sides
            # — then the larger flank is installed and the smaller rides
            # the same install frame, see ElasticShardServer.resize)
            o_lo, o_hi = max(lo, held.lo), min(hi, held.hi)
            if o_lo >= o_hi:
                fresh = (lo, hi)  # ranges moved entirely: all new
            elif lo < o_lo:
                fresh = (lo, o_lo) if hi == o_hi else (lo, hi)
            elif hi > o_hi:
                fresh = (o_hi, hi)
            else:
                fresh = (0, 0)
        entries.append(ShardEntry(sid, lo, hi, fresh[0], fresh[1]))
    return ShardMap(prev.version + 1, prev.n_params, entries)
