"""Tenants: the scheduler's unit of ownership (ISSUE 16).

DistBelief ran on a shared cluster: training jobs, pipelines and serving
fleets competed for the same machines. A *tenant* here is one such job —
a named demand for slots at a priority. The registry is the scheduler's
bounded directory of who may own capacity; the CapacityLedger in
``coord/sched.py`` records who currently does.

The registry is deliberately small and synchronous: tenants are
registered by the operator (or a demo/bench harness) before or during
the run, and the scheduler reads them under its own lock. Nothing here
touches the wire.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional

# Tenant kinds — what member kind a granted slot turns into.
TENANT_TRAINING = 0  # a shard/worker pair of an elastic training job
TENANT_SERVING = 1   # an EngineMember of a serving fleet
TENANT_MPMD = 2      # a pipeline stage member

_KIND_NAMES = {
    TENANT_TRAINING: "training",
    TENANT_SERVING: "serving",
    TENANT_MPMD: "mpmd",
}


@dataclasses.dataclass
class Tenant:
    """One job's standing claim on fleet capacity.

    ``priority`` orders preemption: a higher-priority tenant's unmet
    demand may park a lower-priority tenant's member (never the other
    way round, and never below ``min_slots`` — the floor that keeps a
    preempted training job ALIVE in degraded local-SGD mode instead of
    evicted).  ``demand`` is the tenant's current want, updated by the
    diurnal load signal (serving) or left static (training).
    """

    tenant_id: int
    name: str
    kind: int = TENANT_TRAINING
    priority: int = 0
    demand: int = 0
    min_slots: int = 0

    @property
    def kind_name(self) -> str:
        return _KIND_NAMES.get(self.kind, str(self.kind))


class TenantRegistry:
    """Bounded directory of tenants, keyed by small integer id.

    Ids ride the wire in SlotGrant frames, so they must stay exact in
    float32 — the registry enforces ``0 <= tenant_id < 2**16``.
    """

    MAX_TENANTS = 64

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._tenants: Dict[int, Tenant] = {}

    def register(self, tenant: Tenant) -> Tenant:
        if not (0 <= tenant.tenant_id < (1 << 16)):
            raise ValueError(f"tenant_id {tenant.tenant_id} not wire-exact")
        with self._mu:
            if tenant.tenant_id not in self._tenants \
                    and len(self._tenants) >= self.MAX_TENANTS:
                raise ValueError(
                    f"tenant registry full ({self.MAX_TENANTS})")
            self._tenants[tenant.tenant_id] = tenant
        return tenant

    def get(self, tenant_id: int) -> Optional[Tenant]:
        with self._mu:
            return self._tenants.get(tenant_id)

    def set_demand(self, tenant_id: int, demand: int) -> None:
        with self._mu:
            t = self._tenants.get(tenant_id)
            if t is None:
                raise KeyError(f"unknown tenant {tenant_id}")
            t.demand = int(demand)

    def all(self) -> List[Tenant]:
        with self._mu:
            return sorted(self._tenants.values(),
                          key=lambda t: (-t.priority, t.tenant_id))

    def by_priority_asc(self) -> List[Tenant]:
        """Preemption-victim order: lowest priority first."""
        with self._mu:
            return sorted(self._tenants.values(),
                          key=lambda t: (t.priority, t.tenant_id))

    def __len__(self) -> int:
        with self._mu:
            return len(self._tenants)
