"""Elastic PS-plane members: the resizable shard server and the worker-side
speculation helpers (ISSUE 3 tentpole).

:class:`ElasticShardServer` wraps a plain
:class:`~distributed_ml_pytorch_tpu.parallel.async_ps.ParameterServer` so
the range it owns is COORDINATOR-ASSIGNED instead of launch-time fixed:

- it joins the coordination star as a ``shard`` member (which itself
  triggers the rebalance that assigns it a range) and renews its lease with
  its push count;
- on a newer shard map it resizes: the overlap of old and new range keeps
  its authoritative server-side values, and the freshly-acquired subrange
  waits for a worker's ``RangeInstall`` (first install wins; pulls are
  parked until the range is whole, so a worker can never adopt
  uninitialized zeros as central params);
- stale-map traffic is dropped and counted, never applied (the worker's
  next cadence under the agreed map is correct): elastic pushes and pull
  replies carry the sender's map version AND the absolute range they were
  cut for (``ShardPush`` / ``ShardParams``, ISSUE 6), and the range is
  the gate — equal-size ranges at moved offsets (the join+death
  same-count rebalance) are detected, while a version bump whose ranges
  stayed put stays compatible;
- ``SpeculativeUpdate`` frames (Sandblaster backup-task results) apply
  exactly once per task id: the victim's late result and the backup's fast
  one race, first wins, the duplicate is counted and dropped — this is what
  makes replicating a straggler's work SAFE under DownPour (the duplicate
  would otherwise double-apply a whole tail of lr-scaled deltas).

The worker half of speculation lives in
:meth:`~distributed_ml_pytorch_tpu.parallel.sharded_ps.ShardedAsynchronous.push_speculative`
plus the harness in ``coord/cli.py`` / ``tests/test_coord.py``: the
coordinator names a (task id, victim, from_step); BOTH the victim and the
backup compute the victim's remaining batches and push the resulting
accumulated update under that task id.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional

import numpy as np

from distributed_ml_pytorch_tpu.coord.member import CoordClient
from distributed_ml_pytorch_tpu.coord.shardmap import ShardMap
from distributed_ml_pytorch_tpu.utils.chaos import gray_injector
from distributed_ml_pytorch_tpu.parallel.async_ps import ParameterServer
from distributed_ml_pytorch_tpu.utils.messaging import (
    MessageCode,
    Transport,
    _join16,
    _split16,
)


class ElasticShardServer:
    """A ParameterServer whose range follows the coordinator's shard map."""

    def __init__(
        self,
        server_id: int,
        n_params: int,
        transport: Transport,
        coord: CoordClient,
        *,
        init_params: Optional[np.ndarray] = None,
        staleness_damping: float = 0.0,
        ckpt_dir: Optional[str] = None,
        ckpt_every: int = 500,
        wal: bool = False,
        wal_group_n: int = 8,
        admission=None,
        manifest_path: Optional[str] = None,
        combine: str = "add",
        optimizer=None,
    ):
        self.server_id = int(server_id)
        self.n_params = int(n_params)
        self.transport = transport
        self.coord = coord
        self._init_flat = (
            np.asarray(init_params, np.float32)
            if init_params is not None else None)
        if self._init_flat is not None and self._init_flat.shape[0] != n_params:
            raise ValueError(
                f"init_params has {self._init_flat.shape[0]} params, "
                f"expected {n_params}")
        self.lo = self.hi = 0
        self.map_version = -1
        #: absolute [lo, hi) subrange awaiting a worker RangeInstall; pulls
        #: are parked while it is non-empty
        self.pending_install: Optional[tuple] = None
        self.ps = ParameterServer(
            params=np.zeros(1, np.float32), transport=transport,
            ckpt_dir=ckpt_dir, ckpt_every=ckpt_every,
            staleness_damping=staleness_damping, wal=wal,
            wal_group_n=wal_group_n, admission=admission,
            combine=combine)
        #: ZeRO-style sharded optimizer (ISSUE 14): owns momentum/Adam
        #: state for EXACTLY this server's assigned range — resized with
        #: the central slice on every map change (overlap state kept,
        #: fresh subranges start neutral), persisted/replayed through the
        #: wrapped ParameterServer's checkpoint + WAL machinery. Attached
        #: post-construction because the range is coordinator-assigned,
        #: not known at build time.
        if optimizer is not None:
            optimizer.resize(self.lo, self.hi)
            self.ps.optimizer = optimizer
        #: where the coordinator publishes its FleetManifest — the rollback
        #: barrier (ISSUE 8) needs it to restore the last good snapshot
        self.manifest_path = manifest_path
        self._seen_tasks: set = set()
        #: snapshot-barrier mailbox: the coord listener thread deposits the
        #: (snapshot_id, map_version) request here; the serve loop takes it
        #: at its next version boundary (between applied updates) — the
        #: barrier's "checkpoint at your next boundary" semantics
        self._snap_mu = threading.Lock()
        self._snap_req: Optional[tuple] = None
        #: rollback-barrier mailbox (ISSUE 8), same discipline as the
        #: snapshot mailbox: the coord listener parks the request, the
        #: serve loop executes it at its next version boundary
        self._roll_req: Optional[int] = None
        #: park mailbox (ISSUE 16), same discipline: the scheduler's
        #: PreemptRequest is parked by the coord listener and executed by
        #: the serve loop at its next version boundary — commit the WAL
        #: group, report PreemptDone, stop serving WITHOUT a CoordLeave
        self._preempt_req: Optional[tuple] = None
        self._parked = False
        if getattr(coord, "on_snapshot", None) is None:
            coord.on_snapshot = self._note_snapshot
        if getattr(coord, "on_rollback", None) is None:
            coord.on_rollback = self._note_rollback
        if getattr(coord, "on_preempt", None) is None:
            coord.on_preempt = self._note_preempt
        self.stats = {
            "stale_dropped": 0, "parked_pulls": 0, "installs": 0,
            "dup_installs": 0, "spec_applied": 0, "spec_dropped": 0,
            "resizes": 0, "rollbacks": 0, "rolled_back_updates": 0,
        }
        #: guards the served state (range bounds, ps.central, stats) —
        #: the serve loop resizes and applies on its thread while demos,
        #: benchmarks and the chaos scripts read ``central``/``snapshot()``
        #: from theirs; an unguarded reader could otherwise observe a
        #: mid-resize (lo, hi) paired with the previous central vector
        #: (distcheck DC205)
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._crashed = False
        #: gray plane (ISSUE 20): if a FaultyTransport sits anywhere under
        #: this transport, scheduled gray stall rules can slow the serve
        #: loop and the WAL-commit path — and the SAME tail that renews the
        #: lease ships the evidence (retransmit rate, blocked-send seconds,
        #: fsync p95, busy-vs-wall ratio) so the coordinator can tell
        #: "slow" from "dead" without a second probe channel
        self._gray = gray_injector(transport)
        self._fsync_spans: list = []
        self._busy_s = 0.0
        self._win_start = 0.0
        self._gray_report_at = 0.0
        self._wire_base = (0, 0, 0.0)

    def crash(self) -> None:
        """Chaos-script hook: die SILENTLY — the serve loop exits, lease
        renewals stop, and NO CoordLeave is sent, so the coordinator must
        *detect* the death by lease expiry (the path the acceptance
        scenario exercises). A clean shutdown is ``stop()``."""
        self._crashed = True
        self.coord.stop()
        self._stop.set()

    # ------------------------------------------------------------------ map
    def _apply_map(self, m: ShardMap) -> None:
        with self._mu:
            self._apply_map_locked(m)

    def _restamp_reply_head(self) -> None:
        """Pull replies go out as ``ShardParams`` stamped with the map
        version AND the absolute range served — the worker's offset gate."""
        self.ps.pull_reply_head = np.asarray(
            [*_split16(max(0, self.map_version)), *_split16(self.lo),
             *_split16(self.hi)], np.float32)
        # codec plane (ISSUE 18): a resize/rebalance re-fences the delta
        # reply plane too — tracked worker bases may describe a different
        # range, so the next delta-opted pull gets a full dense install
        self.ps.reset_pull_bases()

    def _apply_map_locked(self, m: ShardMap) -> None:
        if m.version <= self.map_version:
            return
        self.map_version = m.version
        try:
            self._apply_entry(m)
        finally:
            # every exit path re-stamps — including the unchanged-range
            # case, where only the version moves
            self._restamp_reply_head()

    def _apply_entry(self, m: ShardMap) -> None:
        e = m.entry_for(self.server_id)
        if e is None:
            # dropped from the map while alive (e.g. coordinator restarted
            # without us): keep serving the old range; our join retry or
            # lease renewal re-adds us
            print(f"shard {self.server_id}: not in map v{m.version} — "
                  "keeping current range", file=sys.stderr)
            return
        if (e.lo, e.hi) == (self.lo, self.hi):
            return
        if self.ps.wal is not None and self.hi > self.lo:
            # WAL records are sized for the range they were applied under —
            # they must never straddle a resize. Checkpoint (which truncates
            # the log) so on-disk state always describes ONE range.
            self.ps.save_checkpoint()
        new_central = np.zeros(e.size, np.float32)
        if self._init_flat is not None:
            # a known init seeds the whole range; worker installs refine it
            new_central[:] = self._init_flat[e.lo:e.hi]
        o_lo, o_hi = max(self.lo, e.lo), min(self.hi, e.hi)
        if o_lo < o_hi and self.hi > self.lo:
            new_central[o_lo - e.lo:o_hi - e.lo] = (
                self.ps.central[o_lo - self.lo:o_hi - self.lo])
        fresh = (e.fresh_lo, e.fresh_hi) if e.needs_install else None
        if fresh is not None and self._init_flat is not None and self.lo == self.hi:
            # first assignment of a seeded server: the init IS the value set
            # the construction-install flow will refine — no need to park
            fresh = None
        self.pending_install = fresh
        print(
            f"shard {self.server_id}: map v{m.version} resize "
            f"[{self.lo},{self.hi}) -> [{e.lo},{e.hi})"
            + (f", awaiting install of [{fresh[0]},{fresh[1]})"
               if fresh else ""),
            file=sys.stderr,
        )
        self.lo, self.hi = e.lo, e.hi
        self.ps.central = new_central
        if self.ps.optimizer is not None:
            # the optimizer range follows the central slice: overlap
            # state survives, freshly-acquired subranges start neutral
            self.ps.optimizer.resize(e.lo, e.hi)
        self.stats["resizes"] += 1

    # ---------------------------------------------------------- snapshots
    def _note_snapshot(self, snapshot_id: int, map_version: int) -> None:
        """Coord-listener-thread callback: park the barrier request for the
        serve loop (newest request wins — re-requests are idempotent)."""
        with self._snap_mu:
            self._snap_req = (int(snapshot_id), int(map_version))

    def _take_snapshot_request(self) -> Optional[tuple]:
        with self._snap_mu:
            req, self._snap_req = self._snap_req, None
            return req

    def _do_snapshot(self, snapshot_id: int, map_version: int) -> None:
        """The shard half of the barrier: at this version boundary (the
        serve loop sits between applied updates here), commit the WAL
        group, checkpoint, and report. A request stamped for another map
        version still checkpoints (never harmful) but reports THIS
        server's version — the coordinator refuses the mixed barrier."""
        with self._mu:
            if map_version != self.map_version:
                print(
                    f"shard {self.server_id}: snapshot {snapshot_id} asks "
                    f"map v{map_version} but this server serves "
                    f"v{self.map_version} — reporting the truth",
                    file=sys.stderr)
            self.ps.commit()
            self.ps.save_checkpoint()
            mv, lo, hi = self.map_version, self.lo, self.hi
            apply_seq = self.ps._apply_seq
            push_count = self.ps._push_count
        self.coord.snapshot_done(
            snapshot_id, mv, lo, hi, apply_seq, push_count)

    def _note_rollback(self, rollback_id: int, phase: int) -> None:
        """Coord-listener-thread callback: park a phase-0 barrier request
        for the serve loop (newest wins; phase 1 is informational here —
        this server either restored and reported, or deliberately did
        not)."""
        if phase != 0:
            return
        with self._snap_mu:
            self._roll_req = int(rollback_id)

    def _take_rollback_request(self) -> Optional[int]:
        with self._snap_mu:
            req, self._roll_req = self._roll_req, None
            return req

    def _do_rollback(self, rollback_id: int) -> None:
        """The shard half of the rollback barrier (ISSUE 8): load the last
        good FleetManifest, restore this range to its snapshot IN PLACE
        (checkpoint + WAL replay capped at the promised apply seq, WAL tail
        dropped), and report. Mismatches and missing prerequisites are
        LOUD no-ops — the coordinator's barrier timeout owns abandoning a
        rollback this server cannot honor."""
        from distributed_ml_pytorch_tpu.coord.manifest import (
            FleetManifest,
            ManifestError,
        )

        if not self.manifest_path or not os.path.exists(self.manifest_path):
            print(
                f"shard {self.server_id}: rollback {rollback_id} refused — "
                f"no manifest at {self.manifest_path!r}", file=sys.stderr)
            return
        try:
            manifest = FleetManifest.load(self.manifest_path)
        except (ManifestError, ValueError, OSError) as e:
            print(
                f"shard {self.server_id}: rollback {rollback_id} refused — "
                f"manifest unusable: {e}", file=sys.stderr)
            return
        with self._mu:
            entry = manifest.entry_for(self.server_id)
            if entry is None:
                print(
                    f"shard {self.server_id}: rollback {rollback_id} "
                    "refused — manifest has no entry for this server",
                    file=sys.stderr)
                return
            if (manifest.map_version != self.map_version
                    or (entry.lo, entry.hi) != (self.lo, self.hi)):
                print(
                    f"shard {self.server_id}: rollback {rollback_id} "
                    f"refused — manifest is map v{manifest.map_version} "
                    f"[{entry.lo},{entry.hi}), this server serves "
                    f"v{self.map_version} [{self.lo},{self.hi})",
                    file=sys.stderr)
                return
            try:
                discarded = self.ps.rollback_restore(entry.apply_seq)
            except (ValueError, OSError) as e:
                print(
                    f"shard {self.server_id}: rollback {rollback_id} "
                    f"FAILED: {e}", file=sys.stderr)
                return
            self.stats["rollbacks"] += 1
            self.stats["rolled_back_updates"] += discarded
            # a rollback is authoritative like a manifest restore: nothing
            # awaits install, and a stale RangeInstall must not stomp it
            self.pending_install = None
            mv, lo, hi = self.map_version, self.lo, self.hi
            apply_seq = self.ps._apply_seq
        print(
            f"shard {self.server_id}: rolled back [{lo},{hi}) to snapshot "
            f"{manifest.snapshot_id} (apply seq {apply_seq}, {discarded} "
            "update(s) discarded)", file=sys.stderr)
        self.coord.rollback_done(rollback_id, mv, lo, hi, apply_seq)

    def _note_preempt(self, grant_id: int, snapshot_id: int) -> None:
        """Coord-listener-thread callback (ISSUE 16): park the scheduler's
        preempt request for the serve loop (newest wins; redelivery of the
        same grant is idempotent — the server parks once)."""
        with self._snap_mu:
            self._preempt_req = (int(grant_id), int(snapshot_id))

    def _take_preempt_request(self) -> Optional[tuple]:
        with self._snap_mu:
            req, self._preempt_req = self._preempt_req, None
            return req

    def _do_park(self, grant_id: int, snapshot_id: int) -> None:
        """The member half of a preempt (ISSUE 16): at this version
        boundary, commit the open WAL group — every ACKED delta is now
        durable (log-before-ack + this fsync), so the parked state is
        manifest checkpoint + exactly-once WAL replay away from bit-for-
        bit — report PreemptDone, and stop serving. Deliberately NO
        checkpoint (the WAL tail past the barrier snapshot is the replay
        the resume proves) and NO CoordLeave (a parked life keeps its
        rank, range and membership; the scheduler exempts its lease)."""
        with self._mu:
            self.ps.commit()
            lo, hi = self.lo, self.hi
            apply_seq = self.ps._apply_seq
        self.coord.preempt_done(grant_id, snapshot_id, lo, hi, apply_seq)
        self._parked = True
        self._stop.set()
        print(
            f"shard {self.server_id}: PARKED [{lo},{hi}) at apply seq "
            f"{apply_seq} under snapshot {snapshot_id} (grant {grant_id})",
            file=sys.stderr)

    def restore_from_manifest(self, manifest) -> None:
        """Disaster recovery (ISSUE 5): re-install this shard's range from
        the manifest's shard map, then restore checkpoint + WAL replay.

        Refuses LOUDLY when the manifest is invalid/mixed/incomplete
        (``FleetManifest.validate``), omits this server, or the on-disk
        state cannot reproduce at least the apply sequence the manifest
        promises — serving zeros (or a stale clock) as restored central
        params is the silent corruption this plane exists to prevent."""
        from distributed_ml_pytorch_tpu.coord.manifest import ManifestError

        manifest.validate()
        entry = manifest.entry_for(self.server_id)
        with self._mu:
            self.lo, self.hi = entry.lo, entry.hi
            self.map_version = manifest.map_version
            central = np.zeros(entry.hi - entry.lo, np.float32)
            if self._init_flat is not None:
                central[:] = self._init_flat[entry.lo:entry.hi]
            self.ps.central = central
            if self.ps.optimizer is not None:
                # size the optimizer to the manifest range BEFORE the
                # restore loads its persisted state (which is validated
                # against exactly this size)
                self.ps.optimizer.resize(entry.lo, entry.hi)
            if not self.ps.maybe_restore():
                raise ManifestError(
                    f"shard {self.server_id}: manifest promises a "
                    f"checkpoint for [{entry.lo},{entry.hi}) but nothing "
                    f"restorable exists under {self.ps.ckpt_dir!r}")
            if self.ps._apply_seq < entry.apply_seq:
                raise ManifestError(
                    f"shard {self.server_id}: restored apply seq "
                    f"{self.ps._apply_seq} is BEHIND the manifest's "
                    f"{entry.apply_seq} — checkpoint/WAL lost acked state")
            # a manifest restore is authoritative: nothing awaits install,
            # and a worker's stale RangeInstall must not stomp it
            self.pending_install = None
            self._restamp_reply_head()
        print(
            f"shard {self.server_id}: restored [{entry.lo},{entry.hi}) at "
            f"apply seq {self.ps._apply_seq} "
            f"({self.ps.replayed_updates} WAL record(s) replayed)",
            file=sys.stderr)

    # --------------------------------------------------------------- handle
    def handle(self, sender: int, code: MessageCode,
               payload: np.ndarray, envelope: Optional[tuple] = None) -> None:
        with self._mu:
            self.ps._envelope = envelope
            self._handle_locked(sender, code, payload)

    def _handle_locked(self, sender: int, code: MessageCode,
                       payload: np.ndarray) -> None:
        size = self.hi - self.lo
        if code == MessageCode.ShardPush and payload.size >= 7:
            # the stamped elastic push: the ABSOLUTE RANGE is the
            # correctness gate — a slice cut for other offsets can never
            # apply, even when two maps hand this server equal-size ranges
            # at different offsets (the old size-only check's blind spot,
            # coord/shardmap.py), while a benign version bump that left
            # the range in place stays compatible (no dropped gradients
            # across a restore-rejoin)
            lo = _join16(payload[2], payload[3])
            hi = _join16(payload[4], payload[5])
            values = payload[6:]
            if (lo, hi) != (self.lo, self.hi) or values.shape[0] != size:
                self.stats["stale_dropped"] += 1
                return
            self.ps.handle(sender, MessageCode.GradientUpdate, values)
            self.coord.report(self.ps._push_count, 0, 0.0)
        elif code == MessageCode.CompressedUpdate and payload.size >= 13:
            # 13 == compress.HEAD_LEN + 1 (a literal for the distcheck
            # size-guard extraction, like ShardPush's 7 above)
            # the compressed elastic push (ISSUE 14): the RANGE stamp is
            # checked BEFORE paying for a decode — same gate as ShardPush,
            # codec-agnostic; an unstamped compressed frame on the elastic
            # plane is dropped like an unstamped GradientUpdate below
            from distributed_ml_pytorch_tpu.utils.compress import peek_stamp

            stamp = peek_stamp(payload)
            if stamp is None or (stamp[1], stamp[2]) != (self.lo, self.hi):
                self.stats["stale_dropped"] += 1
                return
            self.ps.handle(sender, MessageCode.CompressedUpdate, payload)
            self.coord.report(self.ps._push_count, 0, 0.0)
        elif code == MessageCode.CompressedUpdate:
            # truncated below head+1: unroutable, counted like any other
            # undeliverable elastic push (never a silent fall-through)
            self.stats["stale_dropped"] += 1
        elif code == MessageCode.GradientUpdate:
            # unversioned pushes no longer exist on the elastic plane
            # (every elastic client stamps ShardPush) — one arriving means
            # a sender that skipped the wire upgrade: drop it loudly-in-
            # stats rather than risk the offset blind spot
            self.stats["stale_dropped"] += 1
        elif code == MessageCode.ParameterRequest:
            if self.pending_install is not None:
                # parking, not answering: a reply now would hand the worker
                # zeros for the uninstalled subrange; its next cadence pull
                # after the install answers correctly
                self.stats["parked_pulls"] += 1
                return
            self.ps.handle(sender, code, payload)
        elif code == MessageCode.ParameterUpdate:
            if payload.shape[0] != size:
                self.stats["stale_dropped"] += 1
                return
            self.ps.handle(sender, code, payload)
            if self.pending_install is not None:
                # a full-range construction install covers any pending
                # subrange by definition
                self.pending_install = None
                self.stats["installs"] += 1
        elif code == MessageCode.RangeInstall and payload.size >= 4:
            lo = _join16(payload[0], payload[1])
            hi = _join16(payload[2], payload[3])
            values = payload[4:]
            if values.shape[0] != hi - lo:
                self.stats["stale_dropped"] += 1
                return
            if self.pending_install is None or (lo, hi) != self.pending_install:
                self.stats["dup_installs"] += 1  # first install won already
                return
            self.ps.central[lo - self.lo:hi - self.lo] = values
            self.pending_install = None
            self.stats["installs"] += 1
            print(f"shard {self.server_id}: range [{lo},{hi}) installed by "
                  f"worker {sender}", file=sys.stderr)
        elif code == MessageCode.SpeculativeUpdate and payload.size >= 8:
            task_id = _join16(payload[0], payload[1])
            lo = _join16(payload[4], payload[5])
            hi = _join16(payload[6], payload[7])
            values = payload[8:]
            if (lo, hi) != (self.lo, self.hi) or values.shape[0] != size:
                self.stats["stale_dropped"] += 1
                return
            if task_id in self._seen_tasks:
                # the race's loser (victim's late tail, or a wire dup): the
                # dedup that makes Sandblaster-style duplication safe
                self.stats["spec_dropped"] += 1
                return
            self._seen_tasks.add(task_id)
            self.ps.handle(sender, MessageCode.GradientUpdate, values)
            self.stats["spec_applied"] += 1

    # ----------------------------------------------------------------- gray
    def _commit_timed(self) -> None:
        """Close the open WAL group, absorbing any scheduled gray fsync
        stall INTO the measured span — an injected slow disk must show up
        in the fsync p95 the renew tail reports, exactly like a real
        one."""
        t0 = time.monotonic()
        if self._gray is not None:
            d = self._gray.gray_stall("fsync")
            if d > 0.0:
                time.sleep(d)
        with self._mu:
            self.ps.commit()
        span = time.monotonic() - t0
        self._fsync_spans.append(span)
        if len(self._fsync_spans) > 64:
            del self._fsync_spans[:-64]
        self._busy_s += span

    def _report_gray(self, now: float) -> None:
        """Fold wire-stats deltas + fsync spans + serve-loop busy ratio
        into the next lease renewal (:meth:`CoordClient.report_gray_health`).
        Rates are per-report-window deltas, not lifetime totals, so the
        coordinator's adaptive baseline sees CURRENT weather."""
        if now < self._gray_report_at:
            return
        wall = now - self._win_start if self._win_start else 0.0
        self._gray_report_at = now + 0.25
        self._win_start = now
        st = getattr(self.transport, "stats", None)
        retrans = blocked = 0.0
        if isinstance(st, dict):
            sent = int(st.get("sent", 0))
            retries = int(st.get("retries", 0))
            blk = float(st.get("window_blocked_s", 0.0))
            b_sent, b_retries, b_blk = self._wire_base
            retrans = (retries - b_retries) / max(1, sent - b_sent)
            blocked = max(0.0, blk - b_blk)
            self._wire_base = (sent, retries, blk)
        spans = sorted(self._fsync_spans)
        p95_ms = (spans[int(0.95 * (len(spans) - 1))] * 1000.0
                  if spans else 0.0)
        busy = (min(1.0, self._busy_s / wall) if wall > 0.05 else 0.0)
        self._busy_s = 0.0
        report = getattr(self.coord, "report_gray_health", None)
        if report is not None:
            report(retrans_rate=retrans, blocked_s=blocked,
                   fsync_p95_ms=p95_ms, busy_ratio=busy)

    # ------------------------------------------------------------------ run
    def stop(self) -> None:
        self._stop.set()

    def run(self, timeout: Optional[float] = None) -> None:
        """Join, then serve until ``stop()``, fleet-done, or ``timeout``."""
        m = self.coord.join()
        if m is not None:
            self._apply_map(m)
        deadline = None if timeout is None else time.monotonic() + timeout
        self._win_start = time.monotonic()
        while not self._stop.is_set():
            if deadline is not None and time.monotonic() >= deadline:
                break
            if self._gray is not None:
                d = self._gray.gray_stall("serve")
                if d > 0.0:
                    time.sleep(d)  # gray weather: slow, NOT dead
            self._report_gray(time.monotonic())
            m = self.coord.take_shard_map()
            if m is not None:
                self._apply_map(m)
            roll = self._take_rollback_request()
            if roll is not None:
                # a parked snapshot loses to a parked rollback — the shard
                # half of the coordinator's supersede rule ("snapshot
                # aborted: rollback supersedes"). Running the snapshot
                # first would checkpoint the very state being discarded
                # at an apply seq AHEAD of the rollback target, and
                # rollback_restore would (correctly) refuse — the barrier
                # could then never complete on this shard.
                self._take_snapshot_request()
                self._do_rollback(roll)
            snap = self._take_snapshot_request()
            if snap is not None:
                self._do_snapshot(*snap)
            park = self._take_preempt_request()
            if park is not None:
                self._do_park(*park)
                break  # parked: state is durable on disk; serve no more
            if self.coord.fleet.workers_done():
                break
            msg = self.transport.recv(timeout=0.1)
            if msg is None:
                # idle: close the open WAL group so deferred delivery acks
                # never wait longer than one recv timeout
                self._commit_timed()
                continue
            sender, code, payload = msg
            envelope = getattr(self.transport, "last_delivery", None)
            if code in (MessageCode.Heartbeat, MessageCode.WorkerDone):
                # worker lifecycle is the coordinator's job here, but an
                # enveloped WorkerDone still owes its (deferred) ack
                self._commit_timed()
                continue
            t0 = time.monotonic()
            try:
                self.handle(sender, code, payload, envelope)
            except (ValueError, IndexError, OverflowError):
                self._busy_s += time.monotonic() - t0
                continue  # malformed frame: drop, never die
            self._busy_s += time.monotonic() - t0
            if (self.ps.wal is None
                    or code not in (MessageCode.GradientUpdate,
                                    MessageCode.ShardPush,
                                    MessageCode.CompressedUpdate)
                    or self.ps.wal.pending >= self.ps.wal_group_n):
                self._commit_timed()
        if self._crashed:
            return  # scripted silent death: no checkpoint, no leave
        if self._parked:
            # a parked life: renewals stop but NO CoordLeave — the
            # coordinator keeps the membership (lease exempted by the
            # scheduler) and the resume rejoins the same rank/range
            self.coord.stop()
            return
        with self._mu:
            self.ps.save_checkpoint()
            self.ps.commit()
        self.coord.close()

    @property
    def central(self) -> np.ndarray:
        """A COPY of the served values, taken under the serve mutex — the
        live buffer is mutated in place by the serve thread (installs,
        gradient adds), so handing it out would let a reader observe a
        half-applied update no matter what the lock proved."""
        with self._mu:
            return np.array(self.ps.central, copy=True)

    def snapshot(self) -> dict:
        """A consistent mid-run view for demos/benchmarks: the range
        bounds, a COPY of the served values, and the counters — all read
        under the same lock the serve loop mutates them under, so a
        concurrent resize can never be observed halfway."""
        with self._mu:
            return {
                "lo": self.lo, "hi": self.hi,
                "map_version": self.map_version,
                "central": np.array(self.ps.central, copy=True),
                "stats": dict(self.stats),
            }
