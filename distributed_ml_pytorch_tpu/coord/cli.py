"""``coord`` — the coordinator's CLI (counterpart of ``training/cli.py`` and
``serving/cli.py``).

Run a coordinator process for an elastic PS fleet over TCP::

    # the control-plane hub: members dial in whenever they start
    python -m distributed_ml_pytorch_tpu.coord.cli --port 29700 --model alexnet

    # training ranks attach with --coord (training/cli.py):
    python -m distributed_ml_pytorch_tpu.training.cli --mode ps --rank 0 \
        --n-servers 2 --coord localhost:29700 ...

    # self-contained elastic demo: in-process coordinator + 2 shard servers
    # + 2 workers; a 3rd worker joins mid-run, a shard server is crashed,
    # the map rebalances, training completes — the acceptance scenario as a
    # one-command script (siblings: --drill runs the ISSUE 5 disaster-
    # recovery drill, --health the ISSUE 8 immune-system scenario, and
    # --mpmd the ISSUE 10 MPMD pipeline scenario: a 4-stage pipeline under
    # drop/dup + weather whose middle stage is killed mid-schedule and
    # restarted from its per-stage checkpoint)
    python -m distributed_ml_pytorch_tpu.coord.cli --demo

The coordinator's TCP hub is ELASTIC: it binds and serves immediately
(``TCPTransport(wait_for=0)``) instead of blocking on a fixed rendezvous —
members are whoever dials in, which is the whole point of the subsystem.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Elastic control plane: membership, leases, shard "
                    "rebalancing, straggler speculation")
    p.add_argument("--port", type=str, default="29700",
                   help="TCP port the coordination hub binds")
    p.add_argument("--master", type=str, default="localhost")
    p.add_argument("--max-members", type=int, default=64,
                   help="upper bound on member ranks (sizes the hub's rank "
                        "space; members may come and go freely below it)")
    p.add_argument("--model", type=str, default="alexnet",
                   choices=["alexnet", "lenet", "resnet18", "resnet50"],
                   help="model whose raveled size defines the shard-map "
                        "parameter space (must match the training ranks)")
    p.add_argument("--n-params", type=int, default=0,
                   help="override the parameter-space size directly "
                        "(0 = derive from --model)")
    p.add_argument("--lease", type=float, default=3.0,
                   help="seconds of silence before a member is removed; "
                        "members renew at lease/6 by default")
    p.add_argument("--straggler-factor", type=float, default=3.0,
                   help="speculate a worker whose step-latency EWMA exceeds "
                        "this multiple of the fleet median")
    p.add_argument("--no-speculation", action="store_true",
                   help="disable Sandblaster-style backup tasks")
    p.add_argument("--timeout", type=float, default=0.0,
                   help="exit after this many seconds (0 = serve forever)")
    p.add_argument("--demo", action="store_true",
                   help="run the in-process elastic demo (join + shard "
                        "crash + rebalance) and exit")
    p.add_argument("--drill", action="store_true",
                   help="run the in-process disaster-recovery drill "
                        "(snapshot barrier, kill ALL shards, restore from "
                        "manifest + WAL, sequence-accounted) and exit")
    p.add_argument("--health", action="store_true",
                   help="run the in-process numerical-health scenario "
                        "(ISSUE 8: admission gate + nacks, seeded SDC "
                        "poisoned worker, reputation revocation, "
                        "coordinator auto-rollback) and exit")
    p.add_argument("--mpmd", action="store_true",
                   help="run the in-process MPMD pipeline scenario "
                        "(ISSUE 10: 4 stage fleet members under drop/dup "
                        "+ weather, middle stage killed mid-schedule, "
                        "checkpoint restart + watermark replay, MTTR "
                        "reported) and exit")
    p.add_argument("--sched-demo", action="store_true",
                   help="run the in-process multi-tenant scheduler "
                        "scenario (ISSUE 16: serving demand spike "
                        "preempts a live training shard — snapshot "
                        "barrier, park under the FleetManifest — then "
                        "resumes it bit-for-bit off-peak via checkpoint "
                        "+ exactly-once WAL replay; prints preempt/"
                        "resume MTTR and the restore proof) and exit")
    p.add_argument("--auto-rollback", action="store_true",
                   help="TCP hub mode: watch the fleet's loss telemetry "
                        "and drive RollbackRequest barriers to the last "
                        "good manifest on divergence/nonfinite losses")
    p.add_argument("--rollback-loss-factor", type=float, default=2.0,
                   help="auto-rollback: trigger when the fleet-mean loss "
                        "EWMA exceeds this multiple of its best")
    p.add_argument("--reputation-nacks", type=int, default=0,
                   help="revoke a worker's lease after this many admission "
                        "nacks since it (re)joined (0 = off)")
    p.add_argument("--manifest-dir", type=str, default="",
                   help="directory for fleet snapshot manifests (TCP hub "
                        "mode; empty = snapshots stay in memory)")
    p.add_argument("--snapshot-interval", type=float, default=0.0,
                   help="seconds between automatic fleet snapshot barriers "
                        "(0 = only on demand)")
    p.add_argument("--metrics-dump", type=str, default="", metavar="PATH",
                   help="write the metrics-registry snapshot JSON "
                        "(utils/metrics.get_registry, ISSUE 12) to PATH at "
                        "exit — decision-log totals, fleet telemetry, "
                        "attached component stats; '-' prints to stdout")
    p.add_argument("--seed", type=int, default=0)
    return p


def dump_metrics(path: str) -> None:
    """Shared ``--metrics-dump`` tail for the three CLIs (ISSUE 12)."""
    if not path:
        return
    from distributed_ml_pytorch_tpu.utils.metrics import get_registry

    reg = get_registry()
    if path == "-":
        print(reg.dump_json())
    else:
        reg.dump_json(path)
        print(f"metrics snapshot -> {path}")


def _n_params(args) -> int:
    if args.n_params:
        return int(args.n_params)
    import jax
    import jax.numpy as jnp

    from distributed_ml_pytorch_tpu.models import get_model
    from distributed_ml_pytorch_tpu.utils.serialization import (
        ravel_model_params,
    )

    model = get_model(args.model)
    params = model.init(
        jax.random.key(args.seed), jnp.zeros((1, 32, 32, 3)))["params"]
    return int(ravel_model_params(params).shape[0])


def run_demo(args) -> int:
    """The acceptance scenario as a one-command in-process script: 2 shard
    servers + 2 workers; a 3rd worker joins mid-run; shard server 1 is
    crashed; the coordinator rebalances and training completes."""
    from distributed_ml_pytorch_tpu.coord.demo import elastic_demo

    summary = elastic_demo(seed=args.seed)
    print("elastic demo:", summary)
    return 0 if summary.get("ok") else 1


def run_drill(args) -> int:
    """The ISSUE 5 recovery drill as a one-command in-process script."""
    from distributed_ml_pytorch_tpu.coord.drill import drill_demo

    summary = drill_demo(seed=args.seed)
    print("recovery drill:", summary)
    return 0 if summary.get("ok") else 1


def run_health(args) -> int:
    """The ISSUE 8 immune-system scenario as a one-command script."""
    from distributed_ml_pytorch_tpu.coord.health import health_demo

    summary = health_demo(seed=args.seed)
    print("health scenario:", summary)
    return 0 if summary.get("ok") else 1


def run_mpmd(args) -> int:
    """The ISSUE 10 MPMD pipeline scenario as a one-command script."""
    from distributed_ml_pytorch_tpu.coord.stages import mpmd_demo

    summary = mpmd_demo(seed=args.seed)
    print("mpmd scenario:", summary)
    return 0 if summary.get("ok") else 1


def run_sched(args) -> int:
    """The ISSUE 16 multi-tenant scheduler scenario as a one-command
    script: peak preempt -> park -> borrowed slot -> off-peak resume."""
    from distributed_ml_pytorch_tpu.coord.drill import sched_demo

    summary = sched_demo(seed=args.seed)
    print("sched scenario:", summary)
    return 0 if summary.get("ok") else 1


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    print(args)
    if args.demo:
        return run_demo(args)
    if args.drill:
        return run_drill(args)
    if args.health:
        return run_health(args)
    if args.mpmd:
        return run_mpmd(args)
    if args.sched_demo:
        return run_sched(args)

    from distributed_ml_pytorch_tpu.coord.coordinator import Coordinator
    from distributed_ml_pytorch_tpu.utils.messaging import TCPTransport

    n_params = _n_params(args)
    transport = TCPTransport(
        rank=0, world_size=int(args.max_members), master=args.master,
        port=int(args.port), wait_for=0)
    coord = Coordinator(
        transport, n_params, lease=args.lease,
        straggler_factor=args.straggler_factor,
        speculation=not args.no_speculation,
        manifest_dir=args.manifest_dir or None,
        snapshot_interval=args.snapshot_interval,
        auto_rollback=args.auto_rollback,
        rollback_loss_factor=args.rollback_loss_factor,
        reputation_nacks=args.reputation_nacks)
    if args.metrics_dump:
        from distributed_ml_pytorch_tpu.coord.coordinator import (
            FLEET_METRICS_FIELDS,
        )
        from distributed_ml_pytorch_tpu.utils.metrics import get_registry

        get_registry().attach(
            "coord", lambda: {
                "events_total": coord.events.total,
                "events_dropped": coord.events.dropped,
                "rollbacks_done": coord.rollbacks_done,
                "manifests_written": coord.manifests_written,
                **{f"fleet.{k}": v for k, v in zip(
                    FLEET_METRICS_FIELDS,
                    coord.fleet_state()["fleet_metrics"])},
            })
    print(f"coordinator on {args.master}:{args.port} "
          f"({n_params} params, lease {args.lease:.1f}s)")
    try:
        coord.run(timeout=args.timeout or None)
    except KeyboardInterrupt:
        pass
    finally:
        transport.close()
        for line in coord.events[-20:]:
            print("event:", line)
        if coord.events.dropped:
            print(f"({coord.events.total} decisions total, "
                  f"{coord.events.dropped} aged out of the ring)")
        print("fleet at exit:", coord.fleet_state())
        dump_metrics(args.metrics_dump)
    return 0


if __name__ == "__main__":
    sys.exit(main())
