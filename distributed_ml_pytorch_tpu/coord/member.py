"""Member-side face of the control plane: :class:`CoordClient`.

A member (training worker, PS shard server, or serving engine) owns one
transport into the coordination star and a :class:`CoordClient` over it.
The client:

- **joins** with its kind and an incarnation stamp (the same second-stamped
  monotonic counter the reliability layer uses, so a restarted member on the
  same rank reads as a NEWER life), retrying the join frame until the
  coordinator answers with a shard map — join is idempotent on the
  coordinator, so chaos-dropped joins self-heal;
- **renews its lease** from a background thread every ``renew_interval``
  seconds, piggybacking the member's latest progress report (push count,
  step, step-latency EWMA) — the coordinator's straggler detector runs on
  exactly these numbers;
- **receives** ``ShardMapUpdate`` / ``FleetState`` / ``SpeculateTask``
  frames on a listener thread, depositing the newest map in a mailbox
  (consumers cut over at their own step boundaries — the async-PS
  between-steps-swap discipline) and invoking optional callbacks;
- **leaves** explicitly on ``finish()``, carrying its incarnation so a
  parting WorkerDone/leave racing a replacement's join on the same rank can
  never evict the newer life (the coordinator compares stamps).

:class:`FleetView` is the consumable snapshot of the latest fleet state —
``serving/frontend.py`` polls ``engine_up`` to reject-or-queue on engine
loss and re-admit on recovery.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

from distributed_ml_pytorch_tpu.coord.coordinator import (
    KIND_AGENT,
    KIND_ENGINE,
    KIND_SHARD,
    KIND_STAGE,
    KIND_WORKER,
    decode_fleet,
    encode_join,
    encode_leave,
    encode_preempt_done,
    encode_renew,
    encode_rollback_done,
    encode_snapshot_done,
)
from distributed_ml_pytorch_tpu.coord.shardmap import ShardMap
from distributed_ml_pytorch_tpu.utils.messaging import (
    MessageCode,
    Transport,
    _join16,
    _next_incarnation,
    strip_epoch,
)

_KINDS = {"worker": KIND_WORKER, "shard": KIND_SHARD, "engine": KIND_ENGINE,
          "stage": KIND_STAGE, "agent": KIND_AGENT}


class FleetView:
    """Thread-safe snapshot of the coordinator's latest fleet broadcast."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state: Optional[dict] = None
        #: monotonic instant until which a rollback barrier holds (ISSUE 8):
        #: set on RollbackRequest phase 0, cleared on phase 1 — and bounded
        #: by a TTL either way, so a LOST completion broadcast fails OPEN
        #: (admission resumes) instead of wedging the frontend forever
        self._rollback_until = 0.0

    def update(self, state: dict) -> None:
        with self._lock:
            self._state = dict(state)

    @property
    def state(self) -> Optional[dict]:
        with self._lock:
            return None if self._state is None else dict(self._state)

    def engine_up(self) -> bool:
        """False only when a fleet report EXISTS and shows no live engine —
        with no coordinator (or before the first report) the serving plane
        must keep admitting, not fail closed."""
        s = self.state
        return s is None or s["n_engines"] > 0

    def live_engine_ranks(self):
        """The live engine coord-ranks from the latest FleetState tail, or
        ``None`` before the first report (fail open, like engine_up) — the
        fleet router's per-engine lease-expiry signal."""
        s = self.state
        if s is None:
            return None
        ranks = s.get("engine_ranks")
        return None if ranks is None else set(ranks)

    def workers_done(self) -> bool:
        s = self.state
        return s is not None and s["workers_done"]

    def fleet_metrics(self) -> dict:
        """The coordinator's registry summary from the latest FleetState
        tail (ISSUE 12) — ``{}`` before the first report or from a
        pre-metrics coordinator (fail open, like the rank view)."""
        s = self.state
        return {} if s is None else dict(s.get("fleet_metrics") or {})

    def note_rollback(self, active: bool, ttl: float = 15.0) -> None:
        """Record a rollback-barrier phase transition (ISSUE 8). ``active``
        holds admission for at most ``ttl`` seconds — the fail-open bound
        for a completion frame that never arrives."""
        with self._lock:
            self._rollback_until = (time.monotonic() + float(ttl)
                                    if active else 0.0)

    def rollback_active(self) -> bool:
        """True while a PS-fleet rollback barrier is in flight — serving
        frontends hold new submits through the same hold-and-readmit path
        they use for engine loss (``serving/frontend.py``)."""
        with self._lock:
            return time.monotonic() < self._rollback_until


class CoordClient:
    """One member's connection to the coordinator (see module docstring)."""

    def __init__(
        self,
        transport: Transport,
        kind: str,
        *,
        renew_interval: float = 0.5,
        incarnation: Optional[int] = None,
        on_shard_map: Optional[Callable[[ShardMap], None]] = None,
        on_speculate: Optional[Callable[[int, int, int], None]] = None,
        on_snapshot: Optional[Callable[[int, int], None]] = None,
        on_rollback: Optional[Callable[[int, int], None]] = None,
        on_stage_assign: Optional[Callable[[object], None]] = None,
        rollback_hold_ttl: float = 15.0,
        epoch_fence: bool = True,
    ):
        if kind not in _KINDS:
            raise ValueError(f"kind must be one of {sorted(_KINDS)}, got {kind!r}")
        self.transport = transport
        self.kind = kind
        self.renew_interval = float(renew_interval)
        #: reuse the reliability layer's stamp discipline: strictly
        #: increasing in-process, so a replacement client on the same rank
        #: always reads as the newer life
        self.incarnation = (
            int(incarnation) if incarnation is not None else _next_incarnation())
        self.fleet = FleetView()
        self.coord_down = False
        self._on_shard_map = on_shard_map
        self._on_speculate = on_speculate
        #: PUBLIC and mutable: shard servers are usually constructed AFTER
        #: their coord client, so ElasticShardServer wires its snapshot
        #: mailbox in by assignment (``client.on_snapshot = cb``); called
        #: with ``(snapshot_id, map_version)`` on the listener thread,
        #: outside any client lock
        self.on_snapshot = on_snapshot
        #: PUBLIC and mutable like on_snapshot: the data-plane consumer
        #: (ShardedAsynchronous, ElasticShardServer) wires its rollback
        #: mailbox in by assignment; called with ``(rollback_id, phase)``
        #: on the listener thread (phase 0 = start, 1 = complete/abandoned)
        self.on_rollback = on_rollback
        #: PUBLIC and mutable like on_snapshot: the MPMD stage member /
        #: driver (parallel/mpmd.py) wires its placement mailbox in by
        #: assignment; called with the decoded ``StagePlacement`` on the
        #: listener thread (ISSUE 10)
        self.on_stage_assign = on_stage_assign
        #: PUBLIC and mutable like on_snapshot (ISSUE 16): the shard server
        #: wires its park mailbox in by assignment; called with
        #: ``(grant_id, snapshot_id)`` on the listener thread — the member
        #: commits, reports ``preempt_done`` and stops serving
        self.on_preempt = None
        #: PUBLIC and mutable (ISSUE 16): a NODE AGENT member wires its
        #: actuators in by assignment — ``on_slot_grant(grant_id,
        #: tenant_id, action, slot_id)`` spawns/retires the tenant's member
        #: kind, ``on_resume(grant_id, rank, snapshot_id)`` restores the
        #: parked member from the FleetManifest (+ exactly-once WAL replay)
        self.on_slot_grant = None
        self.on_resume = None
        self.rollback_hold_ttl = float(rollback_hold_ttl)
        #: ISSUE 17 fencing: highest coordinator epoch witnessed so far.
        #: A frame stamped with a LOWER epoch comes from a zombie pre-crash
        #: coordinator (or a delayed frame from its life) and is dropped
        #: before dispatch — it must not rebalance/preempt/roll back a fleet
        #: the successor already owns. ``epoch_fence=False`` is the
        #: distmodel ``no_epoch_fence`` mutation knob, never production.
        self.epoch_fence = bool(epoch_fence)
        self.coord_epoch = -1
        self.stale_epoch_dropped = 0
        self._lock = threading.Lock()
        self._latest_map: Optional[ShardMap] = None
        self._current_version = -1
        self._latest_placement = None
        self._placement_version = -1
        self._got_map = threading.Event()
        #: (push_count, step, ewma_ms, wire_open, nacks, bad_loss,
        #: loss_ewma, gnorm_ewma) — wire_open is the member's open-circuit-
        #: breaker count (ISSUE 7); the last four are the numerical-health
        #: telemetry (ISSUE 8)
        self._progress = (0, 0, 0.0, 0, 0, 0, 0.0, 0.0)
        #: gray-health tail (ISSUE 20): (retrans_rate, nack_rate,
        #: blocked_s, fsync_p95_ms, busy_ratio) + per-link evidence
        #: triples — shipped behind the numerical tail on every renew
        self._gray_health = (0.0, 0.0, 0.0, 0.0, 0.0)
        self._gray_links: tuple = ()
        self._stop = threading.Event()
        self._listener = threading.Thread(
            target=self._pump, name="coord-listener", daemon=True)
        self._listener.start()
        self._renewer = threading.Thread(
            target=self._renew_loop, name="coord-renew", daemon=True)
        self._renewer.start()

    # ----------------------------------------------------------------- wire
    def _send(self, code: MessageCode, payload: np.ndarray) -> None:
        try:
            self.transport.send(code, payload)
            self.coord_down = False
        except (OSError, ConnectionError, KeyError):
            # a dead coordinator must never take the member down: training
            # continues on the last map it negotiated (static-fleet mode)
            self.coord_down = True

    def _pump(self) -> None:
        while not self._stop.is_set():
            msg = self.transport.recv(timeout=0.1)
            if msg is None:
                continue
            _sender, code, payload = msg
            try:
                self._handle(code, payload)
            except (ValueError, IndexError, OverflowError):
                continue  # malformed frame: drop, never die

    def _handle(self, code: MessageCode, payload: np.ndarray) -> None:
        # the ONE strip point for the coordinator epoch fence trailer
        # (ISSUE 17): every stamped control frame passes here — shard,
        # stage, and engine serve-loops all consume via their CoordClient
        # callbacks, so rejecting stale epochs HERE fences every command
        # path (rebalance, preempt, rollback, ...). Unstamped frames are
        # pre-fencing peers: accepted unchanged.
        payload, epoch = strip_epoch(payload)
        if epoch is not None:
            if self.epoch_fence and epoch < self.coord_epoch:
                self.stale_epoch_dropped += 1
                return
            self.coord_epoch = max(self.coord_epoch, epoch)
        if code == MessageCode.ShardMapUpdate:
            m = ShardMap.decode(payload)
            with self._lock:
                if m.version > self._current_version:
                    self._current_version = m.version
                    self._latest_map = m
                else:
                    return  # stale rebroadcast: never roll a consumer back
            self._got_map.set()
            if self._on_shard_map is not None:
                self._on_shard_map(m)
        elif code == MessageCode.FleetState:
            self.fleet.update(decode_fleet(payload))
        elif code == MessageCode.SpeculateTask and payload.size >= 3:
            if self._on_speculate is not None and np.isfinite(payload[:3]).all():
                self._on_speculate(
                    int(payload[0]), int(payload[1]), int(payload[2]))
        elif code == MessageCode.SnapshotRequest and payload.size >= 4:
            if self.on_snapshot is not None and np.isfinite(payload[:4]).all():
                self.on_snapshot(
                    _join16(payload[0], payload[1]),
                    _join16(payload[2], payload[3]))
        elif code == MessageCode.StageAssign and payload.size >= 5:
            from distributed_ml_pytorch_tpu.coord.stages import StagePlacement

            p = StagePlacement.decode(payload)
            with self._lock:
                if p.version <= self._placement_version:
                    return  # stale rebroadcast: never roll a consumer back
                self._placement_version = p.version
                self._latest_placement = p
            if self.on_stage_assign is not None:
                self.on_stage_assign(p)
        elif code == MessageCode.RollbackRequest and payload.size >= 7:
            if not np.isfinite(payload[:7]).all():
                return
            rollback_id = _join16(payload[0], payload[1])
            phase = int(payload[6])
            # the fleet view carries the hold for serving frontends; the
            # data-plane consumer (shard server / worker) reacts via its
            # own mailbox callback
            self.fleet.note_rollback(phase == 0, ttl=self.rollback_hold_ttl)
            if self.on_rollback is not None:
                self.on_rollback(rollback_id, phase)
        elif code == MessageCode.PreemptRequest and payload.size >= 4:
            if self.on_preempt is not None and np.isfinite(payload[:4]).all():
                self.on_preempt(
                    _join16(payload[0], payload[1]),
                    _join16(payload[2], payload[3]))
        elif code == MessageCode.SlotGrant and payload.size >= 5:
            if (self.on_slot_grant is not None
                    and np.isfinite(payload[:5]).all()):
                self.on_slot_grant(
                    _join16(payload[0], payload[1]), int(payload[2]),
                    int(payload[3]), int(payload[4]))
        elif code == MessageCode.ResumeRequest and payload.size >= 5:
            if self.on_resume is not None and np.isfinite(payload[:5]).all():
                self.on_resume(
                    _join16(payload[0], payload[1]), int(payload[2]),
                    _join16(payload[3], payload[4]))

    def _renew_loop(self) -> None:
        tick = 0
        while not self._stop.wait(self.renew_interval):
            with self._lock:
                (push_count, step, ewma_ms, wire_open, nacks, bad_loss,
                 loss_ewma, gnorm_ewma) = self._progress
                gray_health = self._gray_health
                gray_links = self._gray_links
            self._send(MessageCode.LeaseRenew, encode_renew(
                self.incarnation, push_count, step, ewma_ms, wire_open,
                nacks, bad_loss, loss_ewma, gnorm_ewma, *gray_health,
                links=gray_links))
            tick += 1
            if tick % 4 == 0:
                # periodic re-JOIN: the coordinator ignores frames from
                # unknown ranks, so a member whose lease expired during a
                # transient stall would otherwise renew into a void forever
                # — the idempotent join re-admits it (and, for a shard,
                # re-triggers the rebalance that restores its range)
                self._send(MessageCode.CoordJoin, encode_join(
                    _KINDS[self.kind], self.incarnation))

    # ------------------------------------------------------------------ api
    def join(self, timeout: float = 10.0) -> Optional[ShardMap]:
        """Announce membership; block until the coordinator's map arrives
        (retrying the join — it may be chaos-dropped). Returns the map, or
        ``None`` on timeout (the caller decides whether that is fatal)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self._send(MessageCode.CoordJoin, encode_join(
                _KINDS[self.kind], self.incarnation))
            if self._got_map.wait(min(0.25, self.renew_interval)):
                return self.current_map()
        return self.current_map()

    def report(self, push_count: int, step: int, ewma_ms: float,
               wire_open: int = 0, nacks: int = 0, bad_loss: int = 0,
               loss_ewma: float = 0.0, gnorm_ewma: float = 0.0) -> None:
        """Stash this member's latest progress; the renew thread ships it
        (written under the client lock so the renew thread never reads a
        torn tuple — distcheck DC205). ``wire_open`` is the member's open
        circuit-breaker count (``ReliableTransport.open_breakers()``): the
        coordinator's lease view then shows WHOSE wire is degraded. The
        numerical-health tail (ISSUE 8): cumulative admission ``nacks``
        received, ``bad_loss`` nonfinite-loss observations, and the loss /
        grad-norm EWMAs — the reputation + rollback-watchdog inputs."""
        with self._lock:
            self._progress = (int(push_count), int(step), float(ewma_ms),
                              int(wire_open), int(nacks), int(bad_loss),
                              float(loss_ewma), float(gnorm_ewma))

    def report_gray_health(self, retrans_rate: float = 0.0,
                           nack_rate: float = 0.0, blocked_s: float = 0.0,
                           fsync_p95_ms: float = 0.0,
                           busy_ratio: float = 0.0, links=()) -> None:
        """Stash this member's data-plane weather (ISSUE 20); the renew
        thread ships it behind the numerical tail. ``links`` is a sequence
        of ``(peer_rank, link_retrans_rate, link_blocked_s)`` triples —
        per-DIRECTED-LINK evidence, so the coordinator can suspect a
        one-way partition on one link while both endpoints stay live.
        Typical sources: ``ReliableTransport.stats()`` retries/sent per
        window, window_blocked_s deltas, WAL fsync spans, serve-loop
        busy-vs-wall ratios."""
        with self._lock:
            self._gray_health = (
                float(retrans_rate), float(nack_rate), float(blocked_s),
                float(fsync_p95_ms), float(busy_ratio))
            self._gray_links = tuple(
                (int(p), float(r), float(b)) for p, r, b in links)

    def current_map(self) -> Optional[ShardMap]:
        with self._lock:
            return self._latest_map

    def take_shard_map(self) -> Optional[ShardMap]:
        """The newest unconsumed map, once (None until a newer one lands)."""
        with self._lock:
            m, self._latest_map = self._latest_map, None
            return m

    def snapshot_done(self, snapshot_id: int, map_version: int, lo: int,
                      hi: int, apply_seq: int, push_count: int) -> None:
        """Report this shard's completed checkpoint into the barrier."""
        self._send(MessageCode.SnapshotDone, encode_snapshot_done(
            snapshot_id, map_version, lo, hi, apply_seq, push_count))

    def rollback_done(self, rollback_id: int, map_version: int, lo: int,
                      hi: int, apply_seq: int) -> None:
        """Report this shard's completed in-place rollback (ISSUE 8)."""
        self._send(MessageCode.RollbackDone, encode_rollback_done(
            rollback_id, map_version, lo, hi, apply_seq))

    def preempt_done(self, grant_id: int, snapshot_id: int, lo: int,
                     hi: int, apply_seq: int) -> None:
        """Report this member parked under ``grant_id`` (ISSUE 16): range
        [lo,hi) durable at ``apply_seq`` under the named snapshot — the
        scheduler may only now re-grant the slot."""
        self._send(MessageCode.PreemptDone, encode_preempt_done(
            grant_id, snapshot_id, lo, hi, apply_seq))

    def stage_ready(self, stage: int, watermark: int) -> None:
        """Announce this member serves pipeline stage ``stage`` at the
        given microbatch watermark (ISSUE 10); the coordinator assigns it
        into the StagePlacement and broadcasts StageAssign."""
        from distributed_ml_pytorch_tpu.coord.stages import encode_stage_ready

        self._send(MessageCode.StageReady, encode_stage_ready(
            stage, self.incarnation, watermark))

    def current_placement(self):
        """The newest StagePlacement seen (None before the first)."""
        with self._lock:
            return self._latest_placement

    def leave(self) -> None:
        self._send(MessageCode.CoordLeave, encode_leave(self.incarnation))

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        self.leave()
        self.stop()
