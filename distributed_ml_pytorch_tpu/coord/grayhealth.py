"""Gray-failure plane (ISSUE 20): adaptive suspicion + a containment
ladder that degrades instead of killing.

Every failure the coordinator could see before this module is fail-stop: a
member dies, its lease expires, drills restore it. The production
pathology DistBelief actually fights is the GRAY member — it renews its
lease on time while its data plane rots (lossy NIC driving retransmit
storms, a one-way partition, fsync stalls), silently dragging fleet
goodput. The evidence already exists on every plane (ReliableTransport
retransmit/nack/blocked-send stats, WAL fsync spans, serve-loop busy
ratios); since ISSUE 20 it ships on the LeaseRenew tail
(``encode_renew``'s gray-health fields + per-link triples) and this module
consumes it as a failure signal.

Detection — :class:`GrayHealth` keeps, per member AND per directed link:

- a phi-accrual-style inter-arrival history of lease renewals (adaptive:
  the suspicion grows with how surprising the current silence is against
  THAT member's own arrival distribution, not a fixed timeout), and
- an adaptive baseline (EW mean/std) of the reported data-plane evidence,
  FROZEN while the member is under suspicion so the anomaly cannot train
  its own baseline. The suspicion score is the evidence z-score against
  that baseline.

Per-link evidence is the asymmetry detector: a one-way partition's victim
reports a clean tail (its inbound works; it may not even see the loss),
but every peer whose pulls die reports a suspect link NAMING it — the
coordinator indicts the member from third-party link reports its own
report cannot launder. The ``symmetric_probe_only`` distmodel mutation
removes exactly this and misses one-way partitions.

Hysteresis — raising takes ``confirm_ticks`` consecutive over-threshold
ticks; clearing takes ``clear_ticks`` consecutive ticks BELOW a separate,
lower ``clear_threshold``. A slow-but-honest member hovers without
flapping; the ``no_hysteresis`` mutation (equal thresholds, one-tick
confirm/clear) is the flap machine the model check catches.

Containment ladder (probation -> quarantine -> evict), reusing existing
actuators instead of inventing new ones:

- PROBATION routes around the suspect: the ``on_probation`` callback feeds
  the FleetRouter's pressure penalty / MPMD standby speculation / PS pull
  retarget, and the decision log announces it. Traffic bends; nobody dies.
- QUARANTINE checkpoint-parks the suspect through the scheduler's
  park/resume machinery: a ``PreemptRequest`` whose grant id lives in the
  gray plane's RESERVED space (``GRAY_GRANT_BASE``), the member's own
  ``_do_park`` path, a WAL'd ``note_parked`` ticket so lease expiry stays
  disarmed, and a ``ResumeRequest`` to the node agent after the cooldown.
- EVICT fires ONLY on confirmed gray (a member that re-offends after
  ``evict_after_quarantines`` quarantine cycles), through the reputation
  revoke machinery (``Coordinator.revoke_member``): cooldown, refused
  joins, fresh-params rejoin. The ``evict_on_first_suspicion`` mutation
  collapses the whole ladder onto this rung and evicts live members on
  transient weather.

A recovered member earns its way back DOWN the same ladder: quarantine
resumes into probation, probation clears into OK — never straight to
trusted.
"""

from __future__ import annotations

import collections
import math
from typing import Callable, Dict, Optional, Tuple

#: gray-plane PreemptRequest grant ids live at and above this base so the
#: coordinator's PreemptDone dispatch can route them here and never to the
#: multi-tenant scheduler's grant bookkeeping (coord/sched.py starts at 1
#: and counts up; 2^24 leaves it ~16M grants of headroom)
GRAY_GRANT_BASE = 1 << 24

#: ladder states
OK = "ok"
PROBATION = "probation"
QUARANTINED = "quarantined"
EVICTED = "evicted"


def member_evidence(retrans_rate: float, nack_rate: float, blocked_s: float,
                    fsync_p95_ms: float, busy_ratio: float) -> float:
    """Collapse a member's gray-health tail into one evidence scalar.
    Weights put every source on a roughly common scale (a 10% retransmit
    rate ~ 0.5s of blocked sends ~ a 50ms fsync p95 ~ one unit); the
    ADAPTIVE part is the per-member baseline, not these constants. A
    busy_ratio of 0 means "not reported" (neutral), below 1 means the
    serve loop spent wall-clock NOT serving — the stall signature."""
    stall = (1.0 - busy_ratio) if busy_ratio > 0.0 else 0.0
    return (10.0 * retrans_rate + 10.0 * nack_rate + 2.0 * blocked_s
            + fsync_p95_ms / 50.0 + stall)


def link_evidence(retrans_rate: float, blocked_s: float) -> float:
    return 10.0 * retrans_rate + 2.0 * blocked_s


class _Baseline:
    """Exponentially-weighted mean/std with a floor — the adaptive 'normal'
    a member's evidence is judged against. Updated only while the member
    is unsuspected (the caller gates), so an anomaly cannot train itself
    into the baseline.

    The first ``warmup`` samples always train and never score: a fresh
    baseline sits at mu=0, so the very first honest report would z-spike,
    freeze the baseline (the anti-self-training gate), and leave it
    frozen at a 'normal' it never actually learned — permanent suspicion
    from startup noise. Abstaining until the baseline has seen enough of
    THIS member's weather breaks that deadlock."""

    __slots__ = ("mu", "var", "alpha", "floor", "latest", "seen", "warmup")

    def __init__(self, alpha: float = 0.1, floor: float = 0.25,
                 warmup: int = 8):
        self.mu = 0.0
        self.var = 0.0
        self.alpha = alpha
        self.floor = floor
        self.latest = 0.0
        self.seen = 0
        self.warmup = int(warmup)

    def update(self, x: float) -> None:
        d = x - self.mu
        self.mu += self.alpha * d
        self.var = (1 - self.alpha) * (self.var + self.alpha * d * d)
        self.seen += 1

    def z(self) -> float:
        if self.seen < self.warmup:
            return 0.0
        sd = max(math.sqrt(self.var), self.floor)
        return (self.latest - self.mu) / sd


class _Track:
    """Per-member suspicion state."""

    __slots__ = ("gaps", "last_at", "base", "state", "raise_streak",
                 "clear_streak", "probation_ticks", "flaps", "quarantines",
                 "grant_id", "parked", "quarantined_at", "resume_sent",
                 "first_suspect_at", "score")

    def __init__(self):
        self.gaps = collections.deque(maxlen=64)
        self.last_at: Optional[float] = None
        self.base = _Baseline()
        self.state = OK
        self.raise_streak = 0
        self.clear_streak = 0
        self.probation_ticks = 0
        self.flaps = 0
        self.quarantines = 0
        self.grant_id = 0
        self.parked: Optional[dict] = None
        self.quarantined_at = 0.0
        self.resume_sent = False
        self.first_suspect_at: Optional[float] = None
        self.score = 0.0


class GrayHealth:
    """The coordinator-side gray-failure plane; attach with
    ``GrayHealth(coord)`` (mirrors ``FleetScheduler``'s ``coord.sched``
    hook — the coordinator feeds :meth:`on_renew` from its LeaseRenew
    dispatch, drives :meth:`tick` from its serve-thread tick, and routes
    gray-granted PreemptDone frames to :meth:`on_preempt_done`).

    The three knobs the distmodel mutations disable map 1:1:

    - ``hysteresis=False`` -> confirm/clear collapse to one tick at one
      shared threshold (the ``no_hysteresis`` flap machine),
    - ``asymmetric=False`` -> per-link evidence is ignored (the
      ``symmetric_probe_only`` one-way-partition blind spot),
    - ``evict_on_first_suspicion=True`` -> the first confirmed raise
      revokes instead of entering probation.
    """

    def __init__(
        self,
        coord,
        *,
        raise_threshold: float = 3.0,
        clear_threshold: float = 1.0,
        confirm_ticks: int = 2,
        clear_ticks: int = 4,
        quarantine_after: int = 6,
        quarantine_cooldown: float = 3.0,
        evict_after_quarantines: int = 2,
        evict_cooldown: float = 10.0,
        actuator_rank: Optional[int] = None,
        link_weight: float = 2.0,
        hysteresis: bool = True,
        asymmetric: bool = True,
        evict_on_first_suspicion: bool = False,
        on_probation: Optional[Callable[[int], None]] = None,
        on_clear: Optional[Callable[[int], None]] = None,
        on_quarantine: Optional[Callable[[int], None]] = None,
    ):
        self.coord = coord
        self.raise_threshold = float(raise_threshold)
        self.clear_threshold = float(clear_threshold)
        self.confirm_ticks = max(1, int(confirm_ticks))
        self.clear_ticks = max(1, int(clear_ticks))
        self.quarantine_after = max(1, int(quarantine_after))
        self.quarantine_cooldown = float(quarantine_cooldown)
        self.evict_after_quarantines = int(evict_after_quarantines)
        self.evict_cooldown = float(evict_cooldown)
        self.actuator_rank = actuator_rank
        self.link_weight = float(link_weight)
        self.hysteresis = bool(hysteresis)
        self.asymmetric = bool(asymmetric)
        self.evict_on_first_suspicion = bool(evict_on_first_suspicion)
        self.on_probation = on_probation
        self.on_clear = on_clear
        self.on_quarantine = on_quarantine
        self._tracks: Dict[int, _Track] = {}
        #: (suspect_rank, reporter_rank) -> evidence baseline for the
        #: DIRECTED link suspect->reporter as the reporter experiences it
        self._links: Dict[Tuple[int, int], _Baseline] = {}
        self._next_grant = GRAY_GRANT_BASE
        self._pending_preempt: Optional[dict] = None
        # measured outcomes (rings: the plane outlives every episode)
        self.detection_latencies = collections.deque(maxlen=256)
        self.containment_mttrs = collections.deque(maxlen=256)
        self.probations = 0
        self.quarantines = 0
        self.evictions = 0
        self.recoveries = 0
        coord.gray = self

    # ------------------------------------------------------------- evidence
    def on_renew(self, member, now: float, links=()) -> None:
        """One lease renewal arrived (coordinator serve thread): record the
        inter-arrival gap, the member's own evidence, and any per-link
        evidence triples it reported about its peers."""
        t = self._tracks.setdefault(member.rank, _Track())
        if t.last_at is not None:
            t.gaps.append(now - t.last_at)
        t.last_at = now
        x = member_evidence(member.retrans_rate, member.nack_rate,
                            member.blocked_s, member.fsync_p95_ms,
                            member.busy_ratio)
        t.base.latest = x
        if (t.base.seen < t.base.warmup
                or (t.state == OK and t.raise_streak == 0)):
            # adaptive baseline, frozen the moment suspicion starts — the
            # anomaly must not train itself into "normal" (warm-up always
            # trains; see _Baseline)
            t.base.update(x)
        for peer, l_retrans, l_blocked in links:
            if peer == member.rank:
                continue
            # wider floor than the member baseline: link evidence is
            # quantized by small request windows, so one transiently late
            # reply must not z-spike into an indictment
            lb = self._links.setdefault((int(peer), member.rank),
                                        _Baseline(floor=1.0))
            lx = link_evidence(l_retrans, l_blocked)
            lb.latest = lx
            pt = self._tracks.get(int(peer))
            peer_ok = pt is None or (pt.state == OK and pt.raise_streak == 0)
            anomalous = lb.z() >= self.raise_threshold
            if lb.seen < lb.warmup or (peer_ok and not anomalous):
                # same freeze rule as the member baseline, judged on the
                # link's OWN z: an anomalous report must not train itself
                # into "normal" during the ticks before the member-level
                # streak starts (warm-up always trains)
                lb.update(lx)
        if t.state == QUARANTINED and t.resume_sent:
            # the resumed life is renewing again: unpark, and re-enter the
            # ladder at PROBATION — a recovered member earns trust back
            # through the same rungs it fell down
            self.coord.note_unparked(member.rank)
            t.parked = None
            t.resume_sent = False
            t.state = PROBATION
            t.probation_ticks = 0
            t.clear_streak = 0
            self.recoveries += 1
            self.coord._log(
                f"gray: rank {member.rank} resumed from quarantine — "
                "re-entering at probation (earns its way back)")

    # -------------------------------------------------------------- scoring
    def _phi(self, t: _Track, now: float) -> float:
        """Phi-accrual-style surprise of the CURRENT renewal gap against
        the member's own inter-arrival history (z-score form): adaptive,
        so a member that always renews every 2s is suspected at 4s while
        one that renews every 50ms is suspected at 150ms."""
        if t.last_at is None or len(t.gaps) < 4:
            return 0.0
        m = sum(t.gaps) / len(t.gaps)
        var = sum((g - m) ** 2 for g in t.gaps) / len(t.gaps)
        sd = max(math.sqrt(var), 0.25 * m, 1e-3)
        return max(0.0, ((now - t.last_at) - m) / sd)

    def _link_component(self, rank: int) -> float:
        """Third-party indictments: how many DISTINCT reporters currently
        see a suspect link from ``rank`` toward them."""
        if not self.asymmetric:
            return 0.0
        reporters = 0
        for (suspect, _reporter), lb in self._links.items():
            if suspect != rank or lb.seen == 0:
                continue
            if lb.z() >= self.raise_threshold and lb.latest > 0.05:
                reporters += 1
        return self.link_weight * min(reporters, 3)

    def score(self, rank: int, now: float) -> float:
        t = self._tracks.get(rank)
        if t is None:
            return 0.0
        own = max(t.base.z(), self._phi(t, now))
        return own + self._link_component(rank)

    # ---------------------------------------------------------------- tick
    def tick(self, now: float) -> None:
        """Drive the suspicion ladder (coordinator serve thread only, like
        ``FleetScheduler.tick``)."""
        raise_thr = self.raise_threshold
        clear_thr = (self.raise_threshold if not self.hysteresis
                     else self.clear_threshold)
        confirm = 1 if not self.hysteresis else self.confirm_ticks
        clear_n = 1 if not self.hysteresis else self.clear_ticks
        p = self._pending_preempt
        if p is not None and p.get("sent") and now - p["started"] > 30.0:
            self.coord._log(
                f"gray: park of rank {p['rank']} ABANDONED after 30s "
                f"(grant {p['grant_id']} never reported done)")
            self._pending_preempt = None
        for rank in list(self._tracks):
            t = self._tracks[rank]
            member = self.coord.members.get(rank)
            if member is None and t.state not in (QUARANTINED, EVICTED):
                continue  # lease-expired or left; nothing to contain
            if t.state == QUARANTINED:
                self._drive_quarantine(rank, t, now)
                continue
            if t.state == EVICTED:
                continue
            s = self.score(rank, now)
            t.score = s
            if t.state == OK:
                if s >= raise_thr:
                    t.raise_streak += 1
                    if t.first_suspect_at is None:
                        t.first_suspect_at = now
                    if t.raise_streak >= confirm:
                        if self.evict_on_first_suspicion:
                            self._evict(rank, t, now,
                                        "first confirmed suspicion "
                                        "(ladder disabled)")
                        else:
                            self._enter_probation(rank, t, now, s)
                else:
                    t.raise_streak = 0
                    if t.flaps == 0:
                        t.first_suspect_at = None
            elif t.state == PROBATION:
                if s <= clear_thr:
                    t.clear_streak += 1
                    if t.clear_streak >= clear_n:
                        self._clear(rank, t)
                else:
                    t.clear_streak = 0
                    t.probation_ticks += 1
                    if (t.probation_ticks >= self.quarantine_after
                            and s >= raise_thr):
                        if (self.evict_after_quarantines > 0
                                and t.quarantines
                                >= self.evict_after_quarantines):
                            self._evict(
                                rank, t, now,
                                f"confirmed gray: still suspect after "
                                f"{t.quarantines} quarantine cycle(s)")
                        else:
                            self._start_quarantine(rank, t, now)

    # -------------------------------------------------------------- ladder
    def _enter_probation(self, rank: int, t: _Track, now: float,
                         s: float) -> None:
        t.state = PROBATION
        t.raise_streak = 0
        t.clear_streak = 0
        t.probation_ticks = 0
        t.flaps += 1
        self.probations += 1
        if t.first_suspect_at is not None:
            self.detection_latencies.append(now - t.first_suspect_at)
        self.coord._log(
            f"gray: rank {rank} on PROBATION (suspicion {s:.1f} >= "
            f"{self.raise_threshold:.1f}) — routing around it, nobody "
            "dies")
        member = self.coord.members.get(rank)
        if member is not None and self.coord.speculation:
            from distributed_ml_pytorch_tpu.coord.coordinator import (
                KIND_WORKER,
            )

            if member.kind == KIND_WORKER:
                # MPMD/worker route-around: standby speculation on the
                # suspect, reusing the Sandblaster backup-task actuator
                self.coord.speculate_victim(rank)
        if self.on_probation is not None:
            self.on_probation(rank)

    def _clear(self, rank: int, t: _Track) -> None:
        t.state = OK
        t.raise_streak = 0
        t.clear_streak = 0
        t.probation_ticks = 0
        self.coord._log(f"gray: rank {rank} cleared probation — suspicion "
                        "below the clear threshold, trust restored")
        if self.on_clear is not None:
            self.on_clear(rank)

    def _start_quarantine(self, rank: int, t: _Track, now: float) -> None:
        p = self._pending_preempt
        if p is not None and p["rank"] != rank:
            return  # one park in flight at a time (mirrors the scheduler)
        if p is None:
            # checkpoint-park discipline (the scheduler's require_manifest
            # gate, reused): drive a snapshot barrier FIRST so the resume
            # restores checkpoint + exact WAL replay, never a cold start
            self._pending_preempt = {
                "rank": rank, "grant_id": 0, "started": now, "sent": False,
                "manifest_baseline":
                    int(getattr(self.coord, "manifests_written", 0))}
            trigger = getattr(self.coord, "trigger_snapshot", None)
            if trigger is not None:
                trigger()
            return
        if p["sent"]:
            return
        barrier_done = (int(getattr(self.coord, "manifests_written", 0))
                        > p["manifest_baseline"])
        if not barrier_done and now - p["started"] < 5.0:
            return  # barrier still in flight; next tick re-checks
        gid = self._next_grant
        self._next_grant += 1
        t.grant_id = gid
        p["grant_id"] = gid
        p["sent"] = True
        lm = self.coord.last_manifest
        snap_id = int(lm.snapshot_id) if lm is not None else 0
        from distributed_ml_pytorch_tpu.coord.coordinator import (
            encode_preempt_request,
        )
        from distributed_ml_pytorch_tpu.utils.messaging import MessageCode

        self.coord._log(
            f"gray: rank {rank} QUARANTINE — checkpoint-park under gray "
            f"grant {gid} (snapshot {snap_id}); its lease is exempt, its "
            "range restores on resume")
        self.coord._send(rank, MessageCode.PreemptRequest,
                         encode_preempt_request(gid, snap_id))
        if self.on_quarantine is not None:
            self.on_quarantine(rank)

    def on_preempt_done(self, sender: int, *, grant_id: int, snap_id: int,
                        lo: int, hi: int, apply_seq: int,
                        now: float) -> None:
        """Wired from ``Coordinator.handle`` for grant ids this plane owns
        (:meth:`owns_grant`)."""
        p = self._pending_preempt
        if p is None or p["grant_id"] != grant_id or p["rank"] != sender:
            self.coord._log(f"gray: stale PreemptDone from rank {sender} "
                            f"(grant {grant_id})")
            return
        t = self._tracks.setdefault(sender, _Track())
        member = self.coord.members.get(sender)
        parked = {
            "rank": sender,
            "incarnation": member.incarnation if member is not None else 0,
            "snapshot_id": snap_id,
            "lo": lo,
            "hi": hi,
            "apply_seq": apply_seq,
            "grant_id": grant_id,
            # tags the ticket as the gray plane's, so a restored
            # coordinator never resynthesizes a scheduler slot for it
            "gray": True,
        }
        self.coord.note_parked(sender, parked)
        t.parked = parked
        t.state = QUARANTINED
        t.quarantined_at = now
        t.quarantines += 1
        t.resume_sent = False
        self.quarantines += 1
        if t.first_suspect_at is not None:
            self.containment_mttrs.append(now - t.first_suspect_at)
        self.coord._log(
            f"gray: rank {sender} parked [{lo},{hi}) at apply seq "
            f"{apply_seq} under snapshot {snap_id} (grant {grant_id}) — "
            f"contained, cooldown {self.quarantine_cooldown:.1f}s")
        self._pending_preempt = None

    def _drive_quarantine(self, rank: int, t: _Track, now: float) -> None:
        if t.parked is None or t.resume_sent:
            return
        if now - t.quarantined_at < self.quarantine_cooldown:
            return
        from distributed_ml_pytorch_tpu.coord.coordinator import (
            encode_resume_request,
        )
        from distributed_ml_pytorch_tpu.utils.messaging import MessageCode

        t.resume_sent = True
        self.coord._log(
            f"gray: quarantine cooldown over — resuming rank {rank} from "
            f"snapshot {t.parked['snapshot_id']} (grant "
            f"{t.parked['grant_id']})")
        if self.actuator_rank is not None:
            self.coord._send(
                self.actuator_rank, MessageCode.ResumeRequest,
                encode_resume_request(t.parked["grant_id"], rank,
                                      t.parked["snapshot_id"]))

    def _evict(self, rank: int, t: _Track, now: float, why: str) -> None:
        t.state = EVICTED
        self.evictions += 1
        self.coord.revoke_member(rank, f"gray: {why}",
                                 cooldown=self.evict_cooldown)

    # ----------------------------------------------------------------- api
    def owns_grant(self, grant_id: int) -> bool:
        return grant_id >= GRAY_GRANT_BASE

    def state_of(self, rank: int) -> str:
        t = self._tracks.get(rank)
        return t.state if t is not None else OK

    def suspects(self) -> Dict[int, str]:
        return {r: t.state for r, t in self._tracks.items()
                if t.state in (PROBATION, QUARANTINED)}

    def suspect_count(self) -> int:
        return len(self.suspects())

    def flaps_of(self, rank: int) -> int:
        t = self._tracks.get(rank)
        return t.flaps if t is not None else 0

    def stats(self) -> dict:
        return {
            "probations": self.probations,
            "quarantines": self.quarantines,
            "evictions": self.evictions,
            "recoveries": self.recoveries,
            "suspects": dict(self.suspects()),
            "detection_latencies": list(self.detection_latencies),
            "containment_mttrs": list(self.containment_mttrs),
        }


class WireEvidence:
    """Turn a :class:`ReliableTransport`-style ``stats`` dict into the
    per-window deltas the renew tail wants. Workers (and the drills) hold
    one per transport: ``sample()`` returns ``(retrans_rate, blocked_s)``
    SINCE the previous sample, so a long-healed history never dilutes
    current weather. Tolerant of any object without a stats dict — it
    just reports zeros."""

    __slots__ = ("_transport", "_base")

    def __init__(self, transport) -> None:
        self._transport = transport
        self._base = (0, 0, 0.0)
        self.sample()  # swallow pre-construction history

    def sample(self) -> tuple:
        st = getattr(self._transport, "stats", None)
        if not isinstance(st, dict):
            return (0.0, 0.0)
        sent = int(st.get("sent", 0))
        retries = int(st.get("retries", 0))
        blk = float(st.get("window_blocked_s", 0.0))
        b_sent, b_retries, b_blk = self._base
        self._base = (sent, retries, blk)
        return (
            (retries - b_retries) / max(1, sent - b_sent),
            max(0.0, blk - b_blk),
        )
