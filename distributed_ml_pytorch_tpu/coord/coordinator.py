"""The coordinator — lease-based membership, elastic shard rebalancing,
straggler speculation, and the fleet-state export (ISSUE 3 tentpole).

DistBelief's Sandblaster batch framework puts a coordinator above the
parameter-server fleet: it owns no parameters, just the *assignment* of work
and data to machines, load-balancing and scheduling "backup replicas" of
straggling tasks (PAPER.md). This module is that role for this framework's
PS and serving planes, over the same tagged-float32 transports everything
else uses (``MessageCode`` 13-18):

- **Membership**: members join with a kind (worker / shard server / serving
  engine) and their :class:`~.messaging.ReliableTransport`-style incarnation
  stamp; liveness is a *lease* renewed by ``LeaseRenew`` frames (any frame
  from a member refreshes it). A member silent past its lease is removed —
  the same timeout discipline as ``utils/failure.FailureDetector``, plus
  explicit ``CoordJoin``/``CoordLeave`` so fleets grow and shrink mid-run.
  Incarnations order lives of a rank: a stale life's ``CoordLeave`` or
  ``LeaseRenew`` (e.g. a WorkerDone flush racing that rank's replacement)
  cannot evict or refresh the newer life.
- **Shard rebalancing**: when a shard server joins or dies, the coordinator
  computes the next :class:`~.shardmap.ShardMap` version and pushes it to
  every member; ``ShardedAsynchronous`` clients drain in-flight pushes and
  cut over at a step boundary, installing values for moved ranges
  (``coord/shardmap.py`` documents the handover).
- **Straggler speculation**: workers report progress (push count, step,
  step-latency EWMA) inside their lease renewals. A worker whose EWMA
  exceeds ``straggler_factor`` x the fleet median gets its remaining work
  replicated: the fastest live worker receives a ``SpeculateTask`` and
  races the straggler; results dedup first-wins at the PS via
  ``SpeculativeUpdate`` task ids (``coord/elastic.py``), so the epoch stops
  being gated by its slowest machine — Sandblaster's backup-task trick.
- **Fleet state**: a compact ``FleetState`` broadcast (worker/shard/engine
  counts + done flag) that ``serving/frontend.py`` consumes to reject-or-
  queue on engine loss and re-admit on recovery (:class:`~.member.FleetView`).

Determinism note: the coordinator's DECISIONS are pure functions of the
message/clock history (``handle``/``tick`` with an injectable clock; no
hidden threads), so tests drive it synchronously; the production ``run``
loop just feeds it a transport and wall time.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from distributed_ml_pytorch_tpu.coord.shardmap import (
    ShardEntry,
    ShardMap,
    rebalance,
)
from distributed_ml_pytorch_tpu.utils import obs
from distributed_ml_pytorch_tpu.utils.durability import atomic_write
from distributed_ml_pytorch_tpu.utils.messaging import (
    MessageCode,
    Transport,
    _join16,
    _split16,
    stamp_epoch,
)
from distributed_ml_pytorch_tpu.utils.wal import WriteAheadLog

_LOGGER = logging.getLogger(__name__)

#: member kinds on the wire (CoordJoin payload[0])
KIND_WORKER = 0
KIND_SHARD = 1
KIND_ENGINE = 2
KIND_STAGE = 3  # MPMD pipeline stage member (ISSUE 10, coord/stages.py)
KIND_AGENT = 4  # node agent: the scheduler's actuator (ISSUE 16)
_KIND_NAMES = {KIND_WORKER: "worker", KIND_SHARD: "shard",
               KIND_ENGINE: "engine", KIND_STAGE: "stage",
               KIND_AGENT: "agent"}

#: on-disk names inside a coordinator's ``durable_dir`` (ISSUE 17)
COORD_EPOCH_NAME = "coord_epoch"
COORD_CKPT_NAME = "coord_ckpt.json"
COORD_WAL_NAME = "coord.wal"


def _op_to_f32(op: dict) -> np.ndarray:
    """One coordinator WAL record: the JSON transition, space-padded to a
    whole number of float32 words so it rides ``utils/wal.py``'s existing
    float32-payload record format byte-exactly (the WAL never converts an
    already-float32 array, and replay hands the same bytes back)."""
    raw = json.dumps(op, sort_keys=True).encode("utf-8")
    raw += b" " * (-len(raw) % 4)
    return np.frombuffer(raw, np.float32)


def _f32_to_op(payload: np.ndarray) -> dict:
    return json.loads(payload.tobytes().decode("utf-8"))


def encode_join(kind: int, incarnation: int) -> np.ndarray:
    return np.asarray([float(kind), *_split16(incarnation)], np.float32)


def encode_leave(incarnation: int) -> np.ndarray:
    return np.asarray([*_split16(incarnation)], np.float32)


def encode_renew(incarnation: int, push_count: int = 0, step: int = 0,
                 ewma_ms: float = 0.0, wire_open: int = 0, nacks: int = 0,
                 bad_loss: int = 0, loss_ewma: float = 0.0,
                 gnorm_ewma: float = 0.0, retrans_rate: float = 0.0,
                 nack_rate: float = 0.0, blocked_s: float = 0.0,
                 fsync_p95_ms: float = 0.0, busy_ratio: float = 0.0,
                 links=()) -> np.ndarray:
    """``wire_open`` (ISSUE 7) counts the member's open circuit breakers —
    peers whose sends are timing out — so the lease view carries wire
    health, not just liveness. The tail (ISSUE 8) is the numerical-health
    telemetry: cumulative admission ``nacks`` received, ``bad_loss``
    nonfinite-loss observations, and the loss / grad-norm EWMAs — the
    reputation and rollback-watchdog inputs. The GRAY-health tail
    (ISSUE 20) carries the member's own data-plane weather: retransmit
    rate, nack rate, blocked-send seconds, fsync p95 and busy-vs-wall
    ratio — the adaptive-suspicion inputs a renewing-but-rotting member
    cannot hide. ``links`` appends per-DIRECTED-LINK evidence triples
    ``(peer_rank, link_retrans_rate, link_blocked_s)`` so the coordinator
    can suspect a one-way partition on ONE link while both endpoints stay
    healthy members. All values must be finite (receivers drop nonfinite
    renewals); the senders clamp. Pre-ISSUE-20 receivers simply ignore the
    extra floats; pre-ISSUE-20 senders omit them and the receiver keeps
    neutral (0.0) gray evidence — "didn't say" is not "gray"."""
    from distributed_ml_pytorch_tpu.utils.health import clamp_finite32

    tail = []
    for peer, l_retrans, l_blocked in links:
        tail += [float(peer), clamp_finite32(l_retrans),
                 clamp_finite32(l_blocked)]
    return np.asarray(
        [*_split16(incarnation), float(push_count), float(step),
         float(ewma_ms), float(wire_open), float(nacks), float(bad_loss),
         clamp_finite32(loss_ewma), clamp_finite32(gnorm_ewma),
         clamp_finite32(retrans_rate), clamp_finite32(nack_rate),
         clamp_finite32(blocked_s), clamp_finite32(fsync_p95_ms),
         clamp_finite32(busy_ratio), *tail],
        np.float32)


def encode_snapshot_request(snapshot_id: int, map_version: int) -> np.ndarray:
    return np.asarray(
        [*_split16(snapshot_id), *_split16(map_version)], np.float32)


def encode_snapshot_done(snapshot_id: int, map_version: int, lo: int,
                         hi: int, apply_seq: int,
                         push_count: int) -> np.ndarray:
    return np.asarray(
        [*_split16(snapshot_id), *_split16(map_version), *_split16(lo),
         *_split16(hi), *_split16(apply_seq), *_split16(push_count)],
        np.float32)


def encode_rollback_request(rollback_id: int, snapshot_id: int,
                            map_version: int, phase: int) -> np.ndarray:
    """Phase 0 = barrier start (shards restore, workers drop accumulators
    and pull, frontends hold submits); phase 1 = complete/abandoned."""
    return np.asarray(
        [*_split16(rollback_id), *_split16(snapshot_id),
         *_split16(map_version), float(phase)], np.float32)


def encode_rollback_done(rollback_id: int, map_version: int, lo: int,
                         hi: int, apply_seq: int) -> np.ndarray:
    return np.asarray(
        [*_split16(rollback_id), *_split16(map_version), *_split16(lo),
         *_split16(hi), *_split16(apply_seq)], np.float32)


def encode_preempt_request(grant_id: int, snapshot_id: int) -> np.ndarray:
    """Scheduler -> victim member: park yourself under ``grant_id``;
    ``snapshot_id`` names the FleetManifest the park restores from."""
    return np.asarray(
        [*_split16(grant_id), *_split16(snapshot_id)], np.float32)


def encode_preempt_done(grant_id: int, snapshot_id: int, lo: int, hi: int,
                        apply_seq: int) -> np.ndarray:
    return np.asarray(
        [*_split16(grant_id), *_split16(snapshot_id), *_split16(lo),
         *_split16(hi), *_split16(apply_seq)], np.float32)


def encode_slot_grant(grant_id: int, tenant_id: int, action: int,
                      slot_id: int) -> np.ndarray:
    """Scheduler -> node agent: action 1 grants ``slot_id`` to
    ``tenant_id`` (spawn that tenant's member kind), action 0 revokes."""
    return np.asarray(
        [*_split16(grant_id), float(tenant_id), float(action),
         float(slot_id)], np.float32)


def encode_resume_request(grant_id: int, rank: int,
                          snapshot_id: int) -> np.ndarray:
    """Scheduler -> node agent: resume the member parked as ``rank``,
    restoring ``snapshot_id`` bit-for-bit (manifest + WAL replay)."""
    return np.asarray(
        [*_split16(grant_id), float(rank), *_split16(snapshot_id)],
        np.float32)


#: the FleetState tail's section sentinel (ISSUE 12/13): engine ranks are
#: non-negative, so one negative float unambiguously splits the evolved
#: ``(engine_ranks, fleet_metrics)`` tail — and a pre-evolution frame
#: without it still decodes with an empty metrics section. The value is
#: DECLARED in WIRE_SCHEMAS[FleetState].rest_separator; distcheck DC405
#: checks that the decoder really splits on it.
FLEET_TAIL_SEPARATOR = -1.0

#: order of the ``fleet_metrics`` floats behind the separator in a
#: FleetState tail (ISSUE 12): the coordinator-side registry summary every
#: member sees for free on the broadcast it already consumes
FLEET_METRICS_FIELDS = (
    "events_total",    # decisions ever logged (the ring's total counter)
    "mean_ewma_ms",    # fleet-mean member step/busy latency EWMA
    "wire_open",       # summed open circuit breakers across members
    "nacks",           # summed admission nacks across members
    # appended fields decode gracefully on old receivers: decode_fleet
    # zips names to whatever floats arrived, so a short (pre-ISSUE-20)
    # tail simply omits the newer keys
    "gray_suspects",   # members at probation or worse (ISSUE 20)
)


def encode_fleet(version: int, n_workers: int, n_shards: int, n_engines: int,
                 workers_done: bool, engine_ranks=(),
                 fleet_metrics=()) -> np.ndarray:
    """The compact fleet broadcast; the tail lists the LIVE engine members'
    coordinator ranks, so a serving router can tell WHICH engine's lease
    expired, not just that a count dropped (per-engine health, ISSUE 6).
    ``fleet_metrics`` (ISSUE 12) rides BEHIND a ``-1`` separator — engine
    ranks are non-negative, so the split is unambiguous, and a frame
    without the separator (the pre-ISSUE-12 form) still decodes with an
    empty metrics tail."""
    tail = [float(r) for r in engine_ranks]
    metrics = [float(m) for m in fleet_metrics]
    if metrics:
        tail += [FLEET_TAIL_SEPARATOR] + metrics
    return np.asarray(
        [*_split16(version), float(n_workers), float(n_shards),
         float(n_engines), 1.0 if workers_done else 0.0, *tail], np.float32)


def decode_fleet(payload: np.ndarray) -> dict:
    if payload.size < 6 or not np.isfinite(payload[:6]).all():
        raise ValueError(f"malformed FleetState frame (size {payload.size})")
    tail = payload[6:]
    tail = tail[np.isfinite(tail)]
    neg = np.nonzero(tail < 0)[0]
    if neg.size:
        ranks, metrics = tail[:neg[0]], tail[neg[0] + 1:]
    else:
        ranks, metrics = tail, tail[:0]
    return {
        "version": _join16(payload[0], payload[1]),
        "n_workers": int(payload[2]),
        "n_shards": int(payload[3]),
        "n_engines": int(payload[4]),
        "workers_done": bool(payload[5]),
        "engine_ranks": [int(r) for r in ranks],
        "fleet_metrics": dict(zip(FLEET_METRICS_FIELDS,
                                  (float(m) for m in metrics))),
    }


@dataclasses.dataclass
class MemberInfo:
    """One live member: identity, lease, and its latest progress report."""

    rank: int
    kind: int
    incarnation: int
    last_seen: float
    push_count: int = 0
    step: int = 0
    ewma_ms: float = 0.0
    #: how many circuit breakers this member reports open on its own wire
    #: (ISSUE 7): a member that is ALIVE but cannot reach its peers is a
    #: different failure mode than a silent one, and the health view must
    #: distinguish them (a degraded link wants routing around, not eviction)
    wire_open: int = 0
    #: at least one LeaseRenew carried this member's metrics — a fully
    #: idle engine (0% occupancy, 0 TTFT) still counts as reporting, so
    #: scale-down advice can fire on a genuinely idle fleet
    reported: bool = False
    # --- numerical health telemetry (ISSUE 8) ---------------------------
    #: cumulative admission nacks this member has received; ``nack_base``
    #: anchors the offense counter at THIS life's first report, so a
    #: readmitted worker is judged on fresh behavior, not its history
    nacks: int = 0
    nack_base: int = -1
    #: nonfinite losses this member has observed (the hard rollback signal)
    bad_loss: int = 0
    loss_ewma: float = 0.0
    gnorm_ewma: float = 0.0
    # --- gray-health telemetry (ISSUE 20): the member's own data-plane
    # weather, neutral (0.0) until a post-ISSUE-20 renew reports it — a
    # short pre-ISSUE-20 frame leaves these at their defaults
    retrans_rate: float = 0.0
    nack_rate: float = 0.0
    blocked_s: float = 0.0
    fsync_p95_ms: float = 0.0
    busy_ratio: float = 0.0

    @property
    def kind_name(self) -> str:
        return _KIND_NAMES.get(self.kind, str(self.kind))


class Coordinator:
    """Rank-0 hub of the coordination star (see module docstring)."""

    def __init__(
        self,
        transport: Optional[Transport],
        n_params: int,
        *,
        lease: float = 2.0,
        straggler_factor: float = 3.0,
        straggler_after_steps: int = 4,
        speculation: bool = True,
        clock: Callable[[], float] = time.monotonic,
        manifest_dir: Optional[str] = None,
        snapshot_interval: float = 0.0,
        snapshot_timeout: float = 30.0,
        restore_manifest=None,
        engine_occ_high: float = 0.0,
        engine_occ_low: float = 0.0,
        engine_slo_ttft_ms: float = 0.0,
        scale_cooldown: float = 5.0,
        on_scale: Optional[Callable[[str, dict], None]] = None,
        auto_rollback: bool = False,
        rollback_loss_factor: float = 2.0,
        rollback_cooldown: float = 10.0,
        rollback_timeout: float = 30.0,
        reputation_nacks: int = 0,
        reputation_cooldown: float = 10.0,
        durable_dir: Optional[str] = None,
        grace: Optional[float] = None,
        restore_parked: bool = True,
        ckpt_every: int = 16,
    ):
        self.transport = transport
        self.lease = float(lease)
        self.straggler_factor = float(straggler_factor)
        self.straggler_after_steps = int(straggler_after_steps)
        self.speculation = bool(speculation)
        self._clock = clock
        self.members: Dict[int, MemberInfo] = {}
        self.shard_map = ShardMap(0, int(n_params), ())
        self.done_workers: set = set()
        self.speculated: Dict[int, int] = {}  # victim rank -> task id
        self._next_task = 1
        self._stop = threading.Event()
        #: human-readable decision log (tests/CLI). A capped RING since
        #: ISSUE 12 — the old unbounded List[str] leaked memory linearly
        #: on day-long soaks. List-like iteration/slicing is preserved
        #: (``events[-20:]`` renders unchanged); ``events.total`` counts
        #: everything ever logged, ``events.dropped`` what the ring forgot.
        self.events = obs.BoundedEvents(maxlen=1024)
        #: optional flight recorder (``utils/obs.SpanRecorder``), attached
        #: post-construction: every decision-log line doubles as a
        #: structured event on the fleet timeline, and rollback barriers
        #: dump the ring to ``obs_dir`` (set it alongside) so each MTTR
        #: ships with the window that explains it (ISSUE 12)
        self.recorder = None
        self.obs_dir: Optional[str] = None
        #: optional multi-tenant scheduler (ISSUE 16, ``coord/sched.py``):
        #: ``FleetScheduler(coord)`` attaches itself here; tick() drives
        #: its placement pass and handle() routes PreemptDone to it. A
        #: parked member's silence is then a PARK, not a death.
        self.sched = None
        #: optional gray-failure plane (ISSUE 20, ``coord/grayhealth.py``):
        #: ``GrayHealth(coord)`` attaches itself here; handle() feeds it
        #: renew arrivals + health tails, tick() drives the suspicion
        #: ladder, and PreemptDone frames whose grant ids live in the gray
        #: plane's reserved space route to it instead of the scheduler.
        self.gray = None
        # --- snapshot barrier (ISSUE 5): coordinator-aligned fleet ckpts ---
        self.manifest_dir = manifest_dir
        self.snapshot_interval = float(snapshot_interval)
        self.snapshot_timeout = float(snapshot_timeout)
        self._snap_seq = 0
        self._snap: Optional[dict] = None  # the in-flight barrier, if any
        #: set by trigger_snapshot() from any thread (GIL-atomic bool flag);
        #: consumed by tick() on the serve thread, where all decisions run
        self._snap_requested = False
        self._next_snap_at = (
            self._clock() + self.snapshot_interval
            if self.snapshot_interval > 0 else None)
        self.manifests_written = 0
        self.last_manifest = None
        # --- engine scaling advisory (ISSUE 6): replicas follow the
        # engines' OWN reported metrics. Engine members renew leases with
        # (occupancy%, queue depth, TTFT ms) — per-engine granularity of
        # the old all-or-nothing fleet hook. Past ``engine_occ_high`` mean
        # occupancy (or the TTFT SLO), the coordinator advises scale-UP;
        # below ``engine_occ_low`` with >1 replicas it advises scale-DOWN.
        # Advisory = a decision-log event + the ``on_scale`` callback (the
        # harness owns actually launching/retiring a replica; readmission
        # of an expired engine is the member's own join-retry, logged) —
        # thresholds at 0 disable the corresponding direction.
        self.engine_occ_high = float(engine_occ_high)
        self.engine_occ_low = float(engine_occ_low)
        self.engine_slo_ttft_ms = float(engine_slo_ttft_ms)
        self.scale_cooldown = float(scale_cooldown)
        self.on_scale = on_scale
        self._next_scale_at = 0.0
        self.scale_advice = collections.deque(maxlen=256)  # advisory ring
        # --- numerical health plane (ISSUE 8) ---------------------------
        # Worker REPUTATION: with ``reputation_nacks > 0``, a worker whose
        # lease renewals report that many admission nacks since (re)joining
        # gets its lease REVOKED — it rejoins with fresh params only after
        # ``reputation_cooldown`` (the incarnation machinery handles the
        # relife; meanwhile every poisoned push it keeps sending is nacked
        # at the gate, so the data plane stays safe regardless).
        # AUTO-ROLLBACK: with ``auto_rollback``, tick() watches the fleet's
        # loss telemetry — any reported nonfinite loss, or the fleet-mean
        # loss EWMA diverging past ``rollback_loss_factor`` x its best —
        # and drives a RollbackRequest barrier restoring the last good
        # FleetManifest (shards roll back in place, workers drop
        # accumulators and pull, frontends hold submits). MTTR is the
        # trigger -> all-shards-reported time (``rollback_mttrs``).
        self.auto_rollback = bool(auto_rollback)
        self.rollback_loss_factor = float(rollback_loss_factor)
        self.rollback_cooldown = float(rollback_cooldown)
        self.rollback_timeout = float(rollback_timeout)
        self.reputation_nacks = int(reputation_nacks)
        self.reputation_cooldown = float(reputation_cooldown)
        self._roll: Optional[dict] = None  # the in-flight barrier, if any
        self._roll_seq = 0
        #: set by trigger_rollback() from any thread; consumed by tick()
        self._rollback_requested = False
        self._next_rollback_at = 0.0
        self.rollbacks_done = 0
        self.rollbacks_abandoned = 0
        # ring, not list: the coordinator outlives every rollback and a
        # per-event list is exactly the DC503 leak class
        self.rollback_mttrs = collections.deque(maxlen=256)
        self._fleet_best_loss: Optional[float] = None
        self._bad_loss_seen: Dict[int, int] = {}
        self._reputation_block: Dict[int, float] = {}  # rank -> until
        self._block_logged: set = set()
        self.revoked_workers = 0
        # --- control-plane durability + fencing (ISSUE 17) ----------------
        # With ``durable_dir`` the coordinator is crash-restartable: every
        # state transition is WAL'd (log-then-mutate) before any broadcast,
        # a small JSON checkpoint compacts the log, and a restart replays
        # ckpt+WAL to reconstruct the member table, version clocks and the
        # durable parked-rank table. A persisted monotonic EPOCH stamps
        # every outbound frame (``stamp_epoch``) so a zombie pre-crash life
        # cannot command the fleet after its successor takes over, and the
        # restart opens a GRACE window (default = one lease) during which
        # lease expiry and speculation stay suspended while join-retry
        # traffic re-populates liveness — a control-plane blip must not
        # cascade into mass eviction.
        self.durable_dir = durable_dir
        self.grace = grace
        self.restore_parked = bool(restore_parked)
        self.epoch = 1
        self._wal = None
        self._wal_seq = 0
        self._ckpt_seq = 0
        self._ckpt_every = max(1, int(ckpt_every))
        self._ckpt_due = False
        self._ckpt_path: Optional[str] = None
        #: rank -> restore ticket of every member the SCHEDULER parked,
        #: maintained through WAL'd park/unpark transitions — the durable
        #: twin of ``FleetScheduler.parked_ranks()`` that survives a
        #: coordinator restart (the strand-forever regression, ISSUE 17)
        self._parked_durable: Dict[int, dict] = {}
        self._grace_until = 0.0
        self._grace_pending: set = set()
        self._sched_restore: Optional[dict] = None
        self.restored_members = 0
        self.stale_frames_fenced = 0  # kept for symmetry with CoordClient
        if restore_manifest is not None:
            # disaster recovery: adopt the manifest's shard map + snapshot
            # clock so rebalances and snapshot ids continue, not restart
            restore_manifest.validate()
            self.shard_map = restore_manifest.shard_map
            self._snap_seq = int(restore_manifest.snapshot_id)
            self.last_manifest = restore_manifest
            self._log(
                f"restored from manifest: snapshot {self._snap_seq}, "
                f"shard map v{self.shard_map.version}")
        if durable_dir is not None:
            self._init_durable()

    # ------------------------------------------------------------ bookkeeping
    def _log(self, msg: str) -> None:
        self.events.append(msg)
        if self.recorder is not None:
            # the string log PROMOTED: same content, structured, on the
            # same recorder every other plane writes to (ISSUE 12)
            self.recorder.event("coord", corr=0, msg=msg)
        _LOGGER.info("coordinator: %s", msg)

    # ------------------------------------------- durability (ISSUE 17)
    # distcheck: ignore[DC205] constructor-time restore: _init_durable runs
    # from __init__ before the serve thread exists; afterwards every write
    # to these attributes happens on the serve thread only (handle/tick),
    # the single-threaded-by-design contract in the module docstring
    def _init_durable(self) -> None:
        """Open the persisted epoch / checkpoint / WAL and reconstruct any
        previous life's state (constructor-time; serve thread not yet up)."""
        os.makedirs(self.durable_dir, exist_ok=True)
        epoch_path = os.path.join(self.durable_dir, COORD_EPOCH_NAME)
        prev_epoch = 0
        try:
            with open(epoch_path, "r", encoding="utf-8") as f:
                prev_epoch = int(f.read().strip() or 0)
        except (OSError, ValueError):
            prev_epoch = 0
        # the fence: strictly monotonic across lives, durable BEFORE this
        # life sends its first frame — two coordinators over one durable_dir
        # are totally ordered and the member side rejects the older epoch
        self.epoch = prev_epoch + 1
        atomic_write(epoch_path, str(self.epoch).encode("utf-8"))
        self._ckpt_path = os.path.join(self.durable_dir, COORD_CKPT_NAME)
        state = None
        try:
            with open(self._ckpt_path, "rb") as f:
                state = json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError):
            state = None
        self._wal = WriteAheadLog(
            os.path.join(self.durable_dir, COORD_WAL_NAME),
            incarnation=self.epoch)
        records, wal_stats = self._wal.replay()
        self._restore_durable(state, records)
        if prev_epoch and wal_stats.get("torn_tail"):
            self._log("durable restart: dropped one torn WAL tail record "
                      "(the crash artifact)")

    def _restore_durable(self, state: Optional[dict], records) -> None:
        now = self._clock()
        base_seq = 0
        if state is not None:
            base_seq = int(state.get("wal_seq", 0))
            for rank, kind, inc in state.get("members", ()):
                self.members[int(rank)] = MemberInfo(
                    int(rank), int(kind), int(inc), now)
            m = state.get("map")
            if m is not None:
                self.shard_map = ShardMap(
                    int(m["version"]), int(m["n_params"]),
                    [ShardEntry(*(int(v) for v in e))
                     for e in m.get("entries", ())])
            self._snap_seq = int(state.get("snap_seq", self._snap_seq))
            self._roll_seq = int(state.get("roll_seq", self._roll_seq))
            self._next_task = int(state.get("next_task", self._next_task))
            for rank, parked in (state.get("parked") or {}).items():
                self._parked_durable[int(rank)] = dict(parked)
            self._sched_restore = state.get("sched") or None
        for rec in records:
            if rec.seq <= base_seq:
                continue  # the checkpoint already covers it (idempotence)
            self._wal_seq = max(self._wal_seq, int(rec.seq))
            try:
                op = _f32_to_op(rec.payload)
            except (ValueError, UnicodeDecodeError):
                continue  # unreadable record: the ckpt/earlier ops stand
            self._apply_wal_op(op, now)
        self._wal_seq = max(self._wal_seq, base_seq)
        self._ckpt_seq = self._wal_seq
        if not self.restore_parked:
            # the ``forget_parked`` mutation knob (analysis/distmodel.py):
            # a restart that drops the durable park table re-arms lease
            # expiry on every parked member — the strand-forever bug
            self._parked_durable.clear()
            if self._sched_restore:
                for slot in self._sched_restore.get("slots", ()):
                    slot[5] = None
        if not (self.members or self._parked_durable
                or self.shard_map.version):
            return  # first life over an empty dir: nothing to restore
        self.restored_members = len(self.members)
        grace = self.lease if self.grace is None else float(self.grace)
        self._grace_pending = set(self.members) - set(self._parked_durable)
        if grace > 0 and self._grace_pending:
            self._grace_until = now + grace
        if self.last_manifest is None and self.manifest_dir:
            # adopt the previous life's published manifest, if one survives
            try:
                from distributed_ml_pytorch_tpu.coord.manifest import (
                    FleetManifest,
                )

                manifest = FleetManifest.load(self.manifest_path())
                manifest.validate()
                self.last_manifest = manifest
            except Exception:
                pass
        self._log(
            f"restarted as epoch {self.epoch}: restored "
            f"{len(self.members)} member(s), map v{self.shard_map.version}, "
            f"snapshot clock {self._snap_seq}, "
            f"{len(self._parked_durable)} parked rank(s)"
            + (f"; grace window {grace:.1f}s awaiting "
               f"{sorted(self._grace_pending)}"
               if self._grace_until else ""))

    # distcheck: ignore[DC205] WAL replay is constructor-time and
    # single-threaded (called from _restore_durable under __init__); the
    # live paths apply these same mutations on the serve thread only,
    # AFTER logging them (the DC406 log-then-mutate rule)
    def _apply_wal_op(self, op: dict, now: float) -> None:
        """Replay one journaled transition (restore path; same mutations
        the live path applies after logging)."""
        kind = op.get("op")
        if kind == "join":
            self.members[int(op["rank"])] = MemberInfo(
                int(op["rank"]), int(op["kind"]), int(op["inc"]), now)
        elif kind in ("leave", "expire", "revoke"):
            self.members.pop(int(op["rank"]), None)
        elif kind == "map":
            self.shard_map = ShardMap(
                int(op["version"]), int(op["n_params"]),
                [ShardEntry(*(int(v) for v in e))
                 for e in op.get("entries", ())])
        elif kind == "snap":
            self._snap_seq = max(self._snap_seq, int(op["id"]))
        elif kind == "roll":
            self._roll_seq = max(self._roll_seq, int(op["id"]))
        elif kind == "park":
            self._parked_durable[int(op["rank"])] = dict(op["parked"])
        elif kind == "unpark":
            self._parked_durable.pop(int(op["rank"]), None)
        # "manifest" carries no state beyond the snap clock (the manifest
        # FILE is the durable artifact; _restore_durable re-reads it)

    def _wal_record(self, **op) -> None:
        """Journal one control-plane transition BEFORE applying it — the
        log-then-mutate discipline distcheck DC406 pins on this module. A
        memory-only coordinator (no ``durable_dir``) skips the write but
        every call site still orders log-before-mutate."""
        if self._wal is None:
            return
        self._wal_seq += 1
        self._wal.append(self._wal_seq, _op_to_f32(op))
        self._wal.sync()

    def checkpoint(self) -> None:
        """Write the compact JSON checkpoint and truncate the WAL it now
        covers. Serve-thread only (like every other decision)."""
        if self._wal is None or self._ckpt_path is None:
            return
        sched_state = None
        if self.sched is not None:
            sched_state = {
                "next_grant": int(self.sched._next_grant),
                "next_slot": int(self.sched.ledger._next_slot),
                "slots": [
                    [int(s.slot_id),
                     None if s.rank is None else int(s.rank),
                     [int(o) for o in s.owners], s.state, int(s.grant_id),
                     s.parked]
                    for s in self.sched.ledger.slots.values()],
            }
        state = {
            "epoch": int(self.epoch),
            "wal_seq": int(self._wal_seq),
            "members": [[m.rank, m.kind, m.incarnation]
                        for m in self._live()],
            "map": {
                "version": int(self.shard_map.version),
                "n_params": int(self.shard_map.n_params),
                "entries": [[e.server_id, e.lo, e.hi, e.fresh_lo, e.fresh_hi]
                            for e in self.shard_map.entries],
            },
            "snap_seq": int(self._snap_seq),
            "roll_seq": int(self._roll_seq),
            "next_task": int(self._next_task),
            "parked": {str(r): p for r, p in self._parked_durable.items()},
            "sched": sched_state,
        }
        atomic_write(self._ckpt_path,
                     json.dumps(state, sort_keys=True).encode("utf-8"))
        self._ckpt_seq = self._wal_seq
        self._wal.truncate(self._wal_seq)

    def parked_ranks(self) -> set:
        """Ranks whose silence is a PARK, not a death — derived from the
        DURABLE park table union the scheduler's in-memory view, so a
        coordinator restart cannot silently re-arm lease expiry on a
        parked member (the ISSUE 17 satellite regression)."""
        parked = set(self._parked_durable)
        if self.sched is not None:
            parked |= self.sched.parked_ranks()
        return parked

    def note_parked(self, rank: int, parked: dict) -> None:
        """Scheduler hook: journal a park transition (log-then-mutate) and
        remember it durably; called by ``FleetScheduler.on_preempt_done``
        BEFORE the ledger mutates."""
        self._wal_record(op="park", rank=int(rank), parked=dict(parked))
        self._parked_durable[int(rank)] = dict(parked)
        self._ckpt_due = True

    def note_unparked(self, rank: int) -> None:
        """Scheduler hook: the parked rank's new life rejoined — journal
        the unpark and drop it from the durable table."""
        self._wal_record(op="unpark", rank=int(rank))
        self._parked_durable.pop(int(rank), None)
        self._ckpt_due = True

    def _restore_sched_state(self, sched) -> None:
        """Re-seed a freshly attached ``FleetScheduler`` from the previous
        life's checkpointed ledger (called from its constructor), then
        reconcile slots against the durable park table — a crash between
        a WAL'd park and the next checkpoint must still restore the slot
        as PARKED, never double-grant it."""
        restore = self._sched_restore
        if restore is not None:
            from distributed_ml_pytorch_tpu.coord.sched import Slot

            sched._next_grant = int(restore.get("next_grant", 1))
            sched.ledger._next_slot = int(restore.get("next_slot", 0))
            for sid, rank, owners, state, grant_id, parked in \
                    restore.get("slots", ()):
                slot = Slot(
                    slot_id=int(sid),
                    rank=None if rank is None else int(rank),
                    owners=[int(o) for o in owners], state=str(state),
                    grant_id=int(grant_id),
                    parked=None if parked is None else dict(parked))
                sched.ledger.slots[slot.slot_id] = slot
            self._sched_restore = None
        for slot in sched.ledger.slots.values():
            durable = self._parked_durable.get(slot.rank)
            if durable is not None and slot.parked is None:
                from distributed_ml_pytorch_tpu.coord.sched import PARKED

                slot.parked = dict(durable)
                slot.state = PARKED
                self._log(
                    f"restore: slot {slot.slot_id} reconciled to PARKED "
                    f"from the durable park table (rank {slot.rank})")
        # a crash between a WAL'd park and the next checkpoint leaves the
        # parked rank with NO slot at all (the ledger snapshot predates
        # the preemption, or never happened) — resynthesize it from the
        # ticket: owned by the borrower under its original grant, so the
        # tenant that took the capacity keeps it (no double-grant) and
        # releasing it drives the resume (no stranded member)
        known = {s.rank for s in sched.ledger.slots.values()}
        for rank, durable in sorted(self._parked_durable.items()):
            if rank in known:
                continue
            if durable.get("gray"):
                # a gray-plane quarantine ticket (ISSUE 20) has no slot:
                # the gray plane parked the member for containment, not
                # for capacity — resynthesizing a scheduler slot for it
                # would hand its "capacity" to a tenant it never borrowed
                continue
            from distributed_ml_pytorch_tpu.coord.sched import PARKED, Slot

            sid = int(durable.get("slot_id", sched.ledger._next_slot))
            gid = int(durable.get("grant_id", 0))
            borrower = durable.get("borrower")
            slot = Slot(
                slot_id=sid, rank=int(rank),
                owners=[] if borrower is None else [int(borrower)],
                state=PARKED, grant_id=gid, parked=dict(durable))
            sched.ledger.slots[sid] = slot
            sched.ledger._next_slot = max(sched.ledger._next_slot, sid + 1)
            sched._next_grant = max(sched._next_grant, gid + 1)
            self._log(
                f"restore: slot {sid} RESYNTHESIZED from the WAL'd park "
                f"ticket (rank {rank}, borrower {borrower}, grant {gid}) "
                f"— no checkpoint covered this preemption")

    def _live(self, kind: Optional[int] = None) -> List[MemberInfo]:
        out = [m for m in self.members.values()
               if kind is None or m.kind == kind]
        return sorted(out, key=lambda m: m.rank)

    def fleet_state(self) -> dict:
        workers = self._live(KIND_WORKER)
        engines = self._live(KIND_ENGINE)
        live = self._live()
        reported = [m for m in live if m.reported]
        fleet_metrics = [
            float(self.events.total),
            (sum(m.ewma_ms for m in reported) / len(reported)
             if reported else 0.0),
            float(sum(m.wire_open for m in live)),
            float(sum(m.nacks for m in live)),
            float(self.gray.suspect_count()) if self.gray is not None
            else 0.0,
        ]
        return {
            # registry-style fleet telemetry tail (ISSUE 12), wire order =
            # FLEET_METRICS_FIELDS; rides every FleetState broadcast
            "fleet_metrics": fleet_metrics,
            "version": self.shard_map.version,
            "n_workers": len(workers),
            "n_shards": len(self._live(KIND_SHARD)),
            "n_engines": len(engines),
            "engine_ranks": [m.rank for m in engines],
            # done requires at least one CLEAN leave, not just an empty
            # set: every worker lease-expiring at once (a transient stall)
            # must read as an outage, or the shard servers would all exit
            # under a fleet that is still training
            "workers_done": bool(self.done_workers) and not workers,
            "members": {
                m.rank: {"kind": m.kind_name, "incarnation": m.incarnation,
                         "step": m.step, "push_count": m.push_count,
                         "ewma_ms": m.ewma_ms, "wire_open": m.wire_open,
                         "nacks": m.nacks, "bad_loss": m.bad_loss,
                         "loss_ewma": m.loss_ewma}
                for m in self._live()
            },
        }

    def wire_health(self) -> Dict[int, int]:
        """Per-member open-breaker counts from the lease view (rank ->
        wire_open) — the coordinator-side read of ISSUE 7's circuit state."""
        return {m.rank: m.wire_open for m in self._live()}

    # membership decisions are single-threaded by design (handle/tick run
    # on the serve thread only — module docstring); engine_up is an
    # advisory GIL-atomic dict snapshot for the serving fleet hook, and a
    # one-poll-stale answer is within its contract. The DC205 anchor for
    # these attributes now sits on _init_durable/_apply_wal_op above.
    def engine_up(self) -> bool:
        return bool(self._live(KIND_ENGINE))

    def live_engine_ranks(self):
        """The live engine members' ranks — the per-engine face of
        :meth:`engine_up` a colocated serving router probes directly."""
        return {m.rank for m in self._live(KIND_ENGINE)}

    # --------------------------------------------------------------- sends
    def _send(self, rank: int, code: MessageCode, payload: np.ndarray) -> None:
        """One guarded send: a dead member must never take the hub down.
        Every outbound frame carries this life's epoch fence trailer
        (ISSUE 17) — the ONE stamping point, mirrored by the one stripping
        point in ``CoordClient._handle`` — so a zombie pre-crash life's
        delayed commands are rejected fleet-wide once a successor speaks."""
        if self.transport is None:
            return
        try:
            self.transport.send(code, stamp_epoch(payload, self.epoch),
                                dst=rank)
        except (OSError, ConnectionError, KeyError):
            pass  # its lease will expire; the tick path owns the cleanup

    def _broadcast(self, code: MessageCode, payload: np.ndarray) -> None:
        for m in self._live():
            self._send(m.rank, code, payload)

    def _broadcast_rollback(self, payload: np.ndarray) -> None:
        """Rollback frames reach the live fleet AND reputation-revoked
        ranks still cooling down: a revoked worker keeps running (its
        pushes are nacked at the gate, so the data plane is safe) and
        still holds an in-flight accumulator computed on the pre-rollback
        state — it must drop it and pull like everyone else, or its
        eventual readmitted pushes ride a stale base."""
        self._broadcast(MessageCode.RollbackRequest, payload)
        live = {m.rank for m in self._live()}
        for rank in self._reputation_block:
            if rank not in live:
                self._send(rank, MessageCode.RollbackRequest, payload)

    def _announce(self) -> None:
        """Push the current map + fleet state to everyone."""
        self._broadcast(MessageCode.ShardMapUpdate, self.shard_map.encode())
        fs = self.fleet_state()
        self._broadcast(MessageCode.FleetState, encode_fleet(
            fs["version"], fs["n_workers"], fs["n_shards"], fs["n_engines"],
            fs["workers_done"], fs["engine_ranks"], fs["fleet_metrics"]))

    # -------------------------------------------------------------- handle
    def handle(self, sender: int, code: MessageCode,
               payload: np.ndarray) -> None:
        """Process one member frame (the run loop's dispatch; synchronous
        and side-effect-complete, so tests call it directly)."""
        now = self._clock()
        # any frame from a restored member counts as re-attachment: the
        # grace window (ISSUE 17) closes early once everyone is back
        self._grace_pending.discard(sender)
        member = self.members.get(sender)
        if code == MessageCode.CoordJoin and payload.size >= 3:
            if not np.isfinite(payload[:3]).all():
                return
            kind = int(payload[0])
            inc = _join16(payload[1], payload[2])
            if member is not None and inc < member.incarnation:
                # a delayed join from a PREVIOUS life of this rank must not
                # demote the membership the newer life established
                self._log(f"ignored stale join of rank {sender} "
                          f"(inc {inc} < {member.incarnation})")
                return
            blocked_until = self._reputation_block.get(sender)
            if blocked_until is not None:
                if now < blocked_until:
                    # reputation cooldown (ISSUE 8): the revoked worker's
                    # join retries are refused until it expires; logged
                    # once, not per 2s retry
                    if sender not in self._block_logged:
                        self._block_logged.add(sender)
                        self._log(
                            f"join of worker {sender} refused: reputation "
                            f"cooldown ({blocked_until - now:.1f}s left)")
                    return
                del self._reputation_block[sender]
                self._block_logged.discard(sender)
                self._log(f"worker {sender} reputation cooldown over — "
                          "rejoin admitted (fresh params via its pull)")
            is_new = member is None or member.incarnation != inc
            rebirth = member is not None and inc > member.incarnation
            if is_new:
                self._wal_record(op="join", rank=sender, kind=kind, inc=inc)
                self.members[sender] = MemberInfo(sender, kind, inc, now)
                # a new life's bad_loss counter restarts at 0, so the
                # watchdog's consumed-evidence high-water mark must
                # re-anchor with it — a stale mark would silently absorb
                # the new life's first nonfinite-loss reports (the same
                # cross-life reset nack_base gets via MemberInfo)
                self._bad_loss_seen.pop(sender, None)
            else:
                # idempotent SAME-life re-join (members re-join every few
                # renews as lease-expiry insurance): refresh the lease but
                # KEEP the accumulated telemetry — recreating the record
                # here silently zeroed nacks/wire/loss state every few
                # seconds, which made reputation offenses (ISSUE 8)
                # unaccumulable by construction
                member.last_seen = now
            if kind == KIND_WORKER:
                self.done_workers.discard(sender)
            if is_new:
                self._log(f"{_KIND_NAMES.get(kind, kind)} {sender} "
                          f"{'rejoined' if rebirth else 'joined'} (inc {inc})")
                if kind == KIND_SHARD:
                    self._rebalance("join of shard server %d" % sender)
                else:
                    self._announce()
            else:
                # idempotent re-join (the client retries until answered):
                # answer the joiner alone, no fleet-wide rebroadcast
                self._send(sender, MessageCode.ShardMapUpdate,
                           self.shard_map.encode())
                fs = self.fleet_state()
                self._send(sender, MessageCode.FleetState, encode_fleet(
                    fs["version"], fs["n_workers"], fs["n_shards"],
                    fs["n_engines"], fs["workers_done"],
                    fs["engine_ranks"], fs["fleet_metrics"]))
            return
        if member is None:
            return  # pre-join (or post-expiry) chatter: the join retry fixes it
        if code == MessageCode.CoordLeave and payload.size >= 2:
            inc = _join16(payload[0], payload[1])
            if inc != member.incarnation:
                # THE WorkerDone-vs-concurrent-join race: the old life's
                # parting leave must not evict the rank's new life
                self._log(f"ignored stale leave of rank {sender} "
                          f"(inc {inc} != {member.incarnation})")
                return
            self._wal_record(op="leave", rank=sender)
            del self.members[sender]
            if member.kind == KIND_WORKER:
                self.done_workers.add(sender)
            self.speculated.pop(sender, None)
            self._log(f"{member.kind_name} {sender} left")
            if member.kind == KIND_SHARD:
                self._rebalance("leave of shard server %d" % sender)
            else:
                self._announce()
            return
        if code == MessageCode.SnapshotDone and payload.size >= 12:
            if not np.isfinite(payload[:12]).all():
                return
            member.last_seen = now
            self._on_snapshot_done(
                sender,
                snapshot_id=_join16(payload[0], payload[1]),
                map_version=_join16(payload[2], payload[3]),
                lo=_join16(payload[4], payload[5]),
                hi=_join16(payload[6], payload[7]),
                apply_seq=_join16(payload[8], payload[9]),
                push_count=_join16(payload[10], payload[11]))
            return
        if code == MessageCode.RollbackDone and payload.size >= 10:
            if not np.isfinite(payload[:10]).all():
                return
            member.last_seen = now
            self._on_rollback_done(
                sender,
                rollback_id=_join16(payload[0], payload[1]),
                map_version=_join16(payload[2], payload[3]),
                lo=_join16(payload[4], payload[5]),
                hi=_join16(payload[6], payload[7]),
                apply_seq=_join16(payload[8], payload[9]))
            return
        if code == MessageCode.PreemptDone and payload.size >= 10:
            if not np.isfinite(payload[:10]).all():
                return
            member.last_seen = now
            grant_id = _join16(payload[0], payload[1])
            # gray-plane quarantine parks (ISSUE 20) use a reserved grant-id
            # space so their PreemptDone acks never collide with — or get
            # swallowed by — the scheduler's grant bookkeeping
            if self.gray is not None and self.gray.owns_grant(grant_id):
                self.gray.on_preempt_done(
                    sender, grant_id=grant_id,
                    snap_id=_join16(payload[2], payload[3]),
                    lo=_join16(payload[4], payload[5]),
                    hi=_join16(payload[6], payload[7]),
                    apply_seq=_join16(payload[8], payload[9]),
                    now=now)
                return
            if self.sched is not None:
                self.sched.on_preempt_done(
                    sender,
                    grant_id=grant_id,
                    snap_id=_join16(payload[2], payload[3]),
                    lo=_join16(payload[4], payload[5]),
                    hi=_join16(payload[6], payload[7]),
                    apply_seq=_join16(payload[8], payload[9]),
                    now=now)
            return
        # distcheck: ignore[DC104] deliberate wire tolerance (WIRE_SCHEMAS
        # doc): the 5-field pre-ISSUE-7, 6-field pre-ISSUE-8 and 10-field
        # pre-ISSUE-20 renews stay FULL renews — the wire-health,
        # numerical-health and gray-health tails are optional, and an
        # absent field leaves the last report standing ("didn't say" is
        # not "healthy")
        if code == MessageCode.LeaseRenew and payload.size >= 5:
            n = min(int(payload.size), 15)
            if not np.isfinite(payload[:n]).all():
                return
            inc = _join16(payload[0], payload[1])
            if inc < member.incarnation:
                return  # stale life's heartbeat
            member.incarnation = max(member.incarnation, inc)
            member.last_seen = now
            member.push_count = int(payload[2])
            member.step = int(payload[3])
            member.ewma_ms = float(payload[4])
            member.reported = True
            if n >= 6:
                # wire-health field (ISSUE 7): log degraded<->healthy
                # transitions so link trouble is a first-class decision-log
                # event, like up/down membership
                wire_open = int(payload[5])
                if wire_open != member.wire_open:
                    if wire_open > 0:
                        self._log(
                            f"{member.kind_name} {sender} reports "
                            f"{wire_open} open circuit(s) on its wire "
                            "(degraded links)")
                    elif member.wire_open > 0:
                        self._log(
                            f"{member.kind_name} {sender} wire healthy "
                            "again (all circuits closed)")
                member.wire_open = wire_open
            if n >= 10:
                # numerical-health tail (ISSUE 8): nacks drive reputation,
                # bad_loss / loss_ewma drive the rollback watchdog
                member.nacks = int(payload[6])
                if member.nack_base < 0:
                    member.nack_base = member.nacks
                member.bad_loss = int(payload[7])
                member.loss_ewma = float(payload[8])
                member.gnorm_ewma = float(payload[9])
                self._check_reputation(member, now)
            links = ()
            if n >= 15:
                # gray-health tail (ISSUE 20): the adaptive-suspicion
                # evidence; per-link triples (peer, retrans, blocked_s)
                # ride behind the fixed fields
                member.retrans_rate = float(payload[10])
                member.nack_rate = float(payload[11])
                member.blocked_s = float(payload[12])
                member.fsync_p95_ms = float(payload[13])
                member.busy_ratio = float(payload[14])
                rest = payload[15:]
                rest = rest[np.isfinite(rest)]
                links = tuple(
                    (int(rest[k]), float(rest[k + 1]), float(rest[k + 2]))
                    for k in range(0, (rest.size // 3) * 3, 3))
            if self.gray is not None:
                self.gray.on_renew(member, now, links)
            return
        # any other frame from a known member is evidence of life
        member.last_seen = now

    # ---------------------------------------------------------------- tick
    def tick(self) -> bool:
        """Expire leases, rebalance, and (maybe) speculate; returns True if
        membership changed. Call at ~lease/4 cadence (the run loop does)."""
        now = self._clock()
        # --- restart grace window (ISSUE 17): while it holds, restored
        # members are presumed alive — expiring them on restart-time
        # silence would cascade a control-plane blip into mass eviction
        in_grace = bool(self._grace_until)
        if in_grace:
            if not self._grace_pending:
                self._log("grace window closed early: every restored "
                          "member re-attached")
                self._grace_until = 0.0
                in_grace = False
            elif now >= self._grace_until:
                self._log(
                    f"grace window over; still silent: "
                    f"{sorted(self._grace_pending)} — lease expiry re-armed")
                self._grace_until = 0.0
                self._grace_pending.clear()
                in_grace = False
        # a PARKED member (ISSUE 16) stops renewing by design: its silence
        # is the scheduler's doing, and expiring it would rebalance its
        # range away and make the resume impossible. Derived from the
        # DURABLE park table union the scheduler view (ISSUE 17).
        parked = self.parked_ranks()
        expired = [] if in_grace else [
            m for m in self.members.values()
            if now - m.last_seen > self.lease and m.rank not in parked]
        shard_died = False
        for m in expired:
            self._wal_record(op="expire", rank=m.rank)
            del self.members[m.rank]
            self.speculated.pop(m.rank, None)
            self._log(f"{m.kind_name} {m.rank} lease expired "
                      f"({now - m.last_seen:.1f}s silent)")
            shard_died |= m.kind == KIND_SHARD
        if shard_died:
            self._rebalance("lease expiry")
        elif expired:
            self._announce()
        if self.speculation and not in_grace:
            self.check_stragglers()
        self.check_engine_scaling(now)
        # --- multi-tenant scheduler pass (ISSUE 16; serve-thread only) ---
        if self.sched is not None:
            self.sched.tick(now)
        # --- gray-failure suspicion ladder (ISSUE 20; serve-thread only) ---
        if self.gray is not None:
            self.gray.tick(now)
        # --- snapshot barrier driving (serve-thread only, like the rest) ---
        due = (self._next_snap_at is not None and now >= self._next_snap_at)
        if self._snap_requested or due:
            self._snap_requested = False
            if self._next_snap_at is not None:
                self._next_snap_at = now + self.snapshot_interval
            self._start_snapshot(now)
        if (self._snap is not None
                and now - self._snap["started"] > self.snapshot_timeout):
            self._log(
                f"snapshot {self._snap['id']} abandoned: shards "
                f"{sorted(self._snap['expected'] - set(self._snap['got']))} "
                f"never reported within {self.snapshot_timeout:.0f}s")
            self._snap = None
        # --- auto-rollback watchdog + barrier driving (ISSUE 8) -----------
        self._check_numerical_health(now)
        if self._rollback_requested:
            self._rollback_requested = False
            self._start_rollback(now, "explicit trigger")
        if (self._roll is not None
                and now - self._roll["started"] > self.rollback_timeout):
            missing = sorted(self._roll["expected"]
                             - set(self._roll["got"]))
            self._log(
                f"rollback {self._roll['id']} ABANDONED: shards {missing} "
                f"never reported within {self.rollback_timeout:.0f}s")
            # the completion broadcast still goes out: member-side holds
            # (frontends, workers) must release even on an abandoned
            # barrier — they also carry their own TTL as the fail-open
            self._broadcast_rollback(encode_rollback_request(
                self._roll["id"], self._roll["snapshot_id"],
                self._roll["map_version"], 1))
            self.rollbacks_abandoned += 1
            self._flight_dump(f"rollback{self._roll['id']}-abandoned")
            self._roll = None
        # --- durable checkpoint cadence (ISSUE 17; serve thread, so every
        # WAL'd op is already applied by the time it is covered) ----------
        if self._wal is not None and (
                self._ckpt_due
                or self._wal_seq - self._ckpt_seq >= self._ckpt_every):
            self._ckpt_due = False
            self.checkpoint()
        return bool(expired)

    def _rebalance(self, why: str) -> None:
        live = [m.rank for m in self._live(KIND_SHARD)]
        new_map = rebalance(self.shard_map, live)
        # log-then-mutate (DC406): the map-version bump is durable BEFORE
        # the in-memory install and the broadcast below — a restart can
        # never hand out an older version than a frame already on the wire
        self._wal_record(
            op="map", version=new_map.version, n_params=new_map.n_params,
            entries=[[e.server_id, e.lo, e.hi, e.fresh_lo, e.fresh_hi]
                     for e in new_map.entries])
        self.shard_map = new_map
        self._log(
            f"shard map v{self.shard_map.version} on {why}: "
            + (", ".join(f"s{e.server_id}=[{e.lo},{e.hi})"
                         for e in self.shard_map.entries) or "EMPTY"))
        if self._snap is not None:
            # a barrier frozen at an older map version can never complete
            # consistently — abort it; the next interval/trigger retries
            self._log(
                f"snapshot {self._snap['id']} aborted: shard map moved to "
                f"v{self.shard_map.version} mid-barrier")
            self._snap = None
        self._announce()

    # ------------------------------------------------------ snapshot barrier
    def trigger_snapshot(self) -> None:
        """Request a fleet snapshot; the serve thread's next tick starts the
        barrier. Safe from any thread (bool-flag handshake)."""
        self._snap_requested = True

    def manifest_path(self) -> Optional[str]:
        if not self.manifest_dir:
            return None
        from distributed_ml_pytorch_tpu.coord.manifest import MANIFEST_NAME

        return os.path.join(self.manifest_dir, MANIFEST_NAME)

    def _start_snapshot(self, now: float) -> None:
        if self._snap is not None:
            self._log(
                f"snapshot request ignored: snapshot {self._snap['id']} "
                "still in flight")
            return
        shards = self._live(KIND_SHARD)
        if not shards:
            self._log("snapshot request ignored: no live shard servers")
            return
        parked = self.parked_ranks()
        if any(m.rank in parked for m in shards):
            # a parked shard can never answer the barrier, and a manifest
            # missing its range would not be a fleet snapshot — defer
            # until the scheduler resumes it
            self._log("snapshot request deferred: shard(s) "
                      f"{sorted(r for r in parked)} parked by the scheduler")
            return
        self._wal_record(op="snap", id=self._snap_seq + 1)
        self._snap_seq += 1
        self._snap = {
            "id": self._snap_seq,
            "map_version": self.shard_map.version,
            "expected": {m.rank for m in shards},
            "got": {},
            "started": now,
        }
        self._log(
            f"snapshot {self._snap_seq} started: map "
            f"v{self.shard_map.version}, awaiting "
            f"{sorted(self._snap['expected'])}")
        frame = encode_snapshot_request(self._snap_seq, self.shard_map.version)
        for m in shards:
            self._send(m.rank, MessageCode.SnapshotRequest, frame)

    def _on_snapshot_done(self, sender: int, *, snapshot_id: int,
                          map_version: int, lo: int, hi: int, apply_seq: int,
                          push_count: int) -> None:
        snap = self._snap
        if snap is None or snapshot_id != snap["id"]:
            self._log(
                f"stale SnapshotDone from shard {sender} "
                f"(snapshot {snapshot_id})")
            return
        if map_version != snap["map_version"]:
            # a shard checkpointed under another map: the barrier is mixed
            # and must not produce a manifest — abort loudly, retry later
            self._log(
                f"snapshot {snap['id']} aborted: shard {sender} reported "
                f"map v{map_version}, barrier is at v{snap['map_version']}")
            self._snap = None
            return
        entry = self.shard_map.entry_for(sender)
        if entry is None or (entry.lo, entry.hi) != (lo, hi):
            self._log(
                f"snapshot {snap['id']} aborted: shard {sender} reported "
                f"range [{lo},{hi}) but the map assigns "
                f"{None if entry is None else (entry.lo, entry.hi)}")
            self._snap = None
            return
        from distributed_ml_pytorch_tpu.coord.manifest import ShardRecord

        snap["got"][sender] = ShardRecord(
            server_id=sender, lo=lo, hi=hi, map_version=map_version,
            apply_seq=apply_seq, push_count=push_count)
        if set(snap["got"]) >= snap["expected"]:
            self._finalize_snapshot(snap)
            self._snap = None

    def _finalize_snapshot(self, snap: dict) -> None:
        from distributed_ml_pytorch_tpu.coord.manifest import FleetManifest

        manifest = FleetManifest(
            snapshot_id=snap["id"],
            map_version=snap["map_version"],
            n_params=self.shard_map.n_params,
            shards=tuple(snap["got"][r] for r in sorted(snap["got"])),
            complete=True,
        )
        path = self.manifest_path()
        if path is not None:
            os.makedirs(self.manifest_dir, exist_ok=True)
            manifest.write(path)
        self._wal_record(op="manifest", snap_id=int(manifest.snapshot_id),
                         map_version=int(manifest.map_version))
        self.last_manifest = manifest
        self.manifests_written += 1
        self._log(
            f"snapshot {snap['id']} complete: map v{snap['map_version']}, "
            + ", ".join(
                f"s{r.server_id}=[{r.lo},{r.hi})@{r.apply_seq}"
                for r in manifest.shards)
            + (f" -> {path}" if path else " (in-memory only)"))

    # -------------------------------------------- numerical health (ISSUE 8)
    def revoke_member(self, rank: int, why: str,
                      cooldown: Optional[float] = None) -> None:
        """The eviction actuator (serve thread): revoke a member's lease
        with a reputation cooldown — shared by the nack-count reputation
        check (ISSUE 8) and the gray plane's confirmed-gray escalation
        (ISSUE 20). A revoked shard's range rebalances away; join retries
        are refused until the cooldown expires, then the member rejoins
        with fresh params through the normal incarnation machinery."""
        member = self.members.get(rank)
        if member is None:
            return
        cd = self.reputation_cooldown if cooldown is None else float(cooldown)
        self._wal_record(op="revoke", rank=rank)
        del self.members[rank]
        self.speculated.pop(rank, None)
        self._reputation_block[rank] = self._clock() + cd
        self.revoked_workers += 1
        self._log(
            f"{member.kind_name} {rank} lease REVOKED: {why} — cooldown "
            f"{cd:.1f}s, then it rejoins and pulls fresh params")
        if member.kind == KIND_SHARD:
            self._rebalance(f"revocation of shard server {rank}")
        else:
            self._announce()

    def _check_reputation(self, member: MemberInfo, now: float) -> None:
        """Revoke a worker whose admission-nack count since (re)join
        crossed the limit. Called from the renew handler, serve thread."""
        if (self.reputation_nacks <= 0 or member.kind != KIND_WORKER
                or member.nack_base < 0):
            return
        offenses = member.nacks - member.nack_base
        if offenses < self.reputation_nacks:
            return
        self.revoke_member(
            member.rank,
            f"reputation: {offenses} quarantined update(s) this life")

    def trigger_rollback(self) -> None:
        """Request a fleet rollback to the last good manifest; the serve
        thread's next tick starts the barrier. Safe from any thread."""
        self._rollback_requested = True

    def _check_numerical_health(self, now: float) -> None:
        """The rollback watchdog: fire the barrier when any worker reports
        nonfinite losses, or the fleet-mean loss EWMA diverges past
        ``rollback_loss_factor`` x the best fleet-mean seen. The gate
        (utils/health.py) stops what it can SEE; this watchdog exists for
        the poison it cannot — norm-preserving SDC, slow divergence.

        Runs every tick regardless of ``auto_rollback`` so the best-loss
        baseline tracks the whole run's telemetry; the flag gates only the
        FIRING. A deployment that arms the watchdog mid-run (or a scenario
        that scripts the arming point) therefore judges divergence against
        the true healthy baseline, not against whatever already-diverged
        mean the first armed tick happened to see."""
        if now < self._next_rollback_at or self._roll is not None:
            return
        workers = [m for m in self._live(KIND_WORKER) if m.reported]
        if not workers:
            return
        why = None
        bad = [m.rank for m in workers
               if m.bad_loss > self._bad_loss_seen.get(m.rank, 0)]
        if bad:
            why = f"worker(s) {bad} report nonfinite losses"
        else:
            cur = [m.loss_ewma for m in workers if m.loss_ewma > 0]
            if cur:
                mean_loss = sum(cur) / len(cur)
                if (self._fleet_best_loss is None
                        or mean_loss < self._fleet_best_loss):
                    self._fleet_best_loss = mean_loss
                elif (self.rollback_loss_factor > 0
                      and mean_loss > self.rollback_loss_factor
                      * self._fleet_best_loss):
                    why = (f"fleet loss EWMA {mean_loss:.4g} diverged past "
                           f"{self.rollback_loss_factor:.2f}x best "
                           f"{self._fleet_best_loss:.4g}")
        if why is not None and self.auto_rollback:
            self._start_rollback(now, why)

    def _start_rollback(self, now: float, why: str) -> None:
        if self._roll is not None:
            self._log(
                f"rollback request ignored: rollback {self._roll['id']} "
                "still in flight")
            return
        manifest = self.last_manifest
        if manifest is None:
            self._log(f"rollback wanted ({why}) but no FleetManifest "
                      "exists yet — nothing good to restore")
            self._next_rollback_at = now + self.rollback_cooldown
            return
        if manifest.map_version != self.shard_map.version:
            self._log(
                f"rollback wanted ({why}) but the manifest is for map "
                f"v{manifest.map_version}, fleet is at "
                f"v{self.shard_map.version} — take a fresh snapshot first")
            self._next_rollback_at = now + self.rollback_cooldown
            return
        shards = self._live(KIND_SHARD)
        if not shards:
            self._log(f"rollback wanted ({why}) but no live shard servers")
            return
        if self._snap is not None:
            # a snapshot mid-rollback would capture the very state being
            # discarded — the barrier in flight loses
            self._log(
                f"snapshot {self._snap['id']} aborted: rollback supersedes")
            self._snap = None
        self._wal_record(op="roll", id=self._roll_seq + 1)
        self._roll_seq += 1
        self._roll = {
            "id": self._roll_seq,
            "snapshot_id": int(manifest.snapshot_id),
            "map_version": int(manifest.map_version),
            "expected": {m.rank for m in shards},
            "got": set(),
            "started": now,
        }
        self._next_rollback_at = now + self.rollback_cooldown
        # consume the evidence that fired this barrier: divergence must be
        # re-established on POST-restore telemetry, not refire on echoes
        for m in self.members.values():
            self._bad_loss_seen[m.rank] = m.bad_loss
            m.loss_ewma = 0.0
        self._fleet_best_loss = None
        self._log(
            f"ROLLBACK {self._roll_seq} started ({why}): restoring "
            f"snapshot {manifest.snapshot_id} / map "
            f"v{manifest.map_version}, awaiting shards "
            f"{sorted(self._roll['expected'])}")
        self._broadcast_rollback(encode_rollback_request(
            self._roll_seq, manifest.snapshot_id, manifest.map_version, 0))

    def _on_rollback_done(self, sender: int, *, rollback_id: int,
                          map_version: int, lo: int, hi: int,
                          apply_seq: int) -> None:
        roll = self._roll
        if roll is None or rollback_id != roll["id"]:
            self._log(f"stale RollbackDone from shard {sender} "
                      f"(rollback {rollback_id})")
            return
        if map_version != roll["map_version"]:
            self._log(
                f"rollback {roll['id']}: shard {sender} reported map "
                f"v{map_version}, barrier is at v{roll['map_version']} — "
                "ignoring (the timeout abandons a barrier that cannot "
                "complete)")
            return
        entry = self.shard_map.entry_for(sender)
        if entry is None or (entry.lo, entry.hi) != (lo, hi):
            self._log(
                f"rollback {roll['id']}: shard {sender} reported range "
                f"[{lo},{hi}) but the map assigns "
                f"{None if entry is None else (entry.lo, entry.hi)} — "
                "ignoring")
            return
        roll["got"].add(sender)
        self._log(
            f"rollback {roll['id']}: shard {sender} restored "
            f"[{lo},{hi}) at apply seq {apply_seq}")
        if roll["expected"] <= roll["got"]:
            now = self._clock()
            mttr = now - roll["started"]
            self.rollbacks_done += 1
            self.rollback_mttrs.append(mttr)
            self._log(
                f"ROLLBACK {roll['id']} complete in {mttr * 1e3:.0f} ms: "
                f"fleet restored to snapshot {roll['snapshot_id']} — "
                "workers resync by pull, frontends re-admit")
            self._broadcast_rollback(encode_rollback_request(
                roll["id"], roll["snapshot_id"], roll["map_version"], 1))
            self._roll = None
            self._flight_dump(f"rollback{roll['id']}")

    def _flight_dump(self, reason: str) -> None:
        """Automatic black-box write (ISSUE 12): when a recorder and an
        ``obs_dir`` are attached, persist the decision timeline covering
        the fault window — every rollback/restore MTTR number ships with
        the trace that explains it."""
        if self.recorder is not None and self.obs_dir:
            obs.flight_dump(self.recorder, self.obs_dir, reason)

    # ------------------------------------------------------- engine scaling
    def check_engine_scaling(self, now: Optional[float] = None) -> Optional[str]:
        """Advise replica scaling from the engines' own reported metrics
        (see the constructor note). Returns ``"up"``/``"down"`` when advice
        fired this call, else None — rate-limited by ``scale_cooldown``."""
        if self.engine_occ_high <= 0 and self.engine_occ_low <= 0 \
                and self.engine_slo_ttft_ms <= 0:
            return None
        now = self._clock() if now is None else now
        if now < self._next_scale_at:
            return None
        engines = self._live(KIND_ENGINE)
        # engine renewals carry (occupancy%, queue depth, TTFT ms) in the
        # (push_count, step, ewma_ms) renewal slots; skip members that have
        # never renewed so a just-joined replica cannot skew the mean — an
        # IDLE renewal (all zeros) still counts, or an idle fleet could
        # never earn scale-down advice
        reported = [m for m in engines if m.reported]
        if not reported:
            return None
        mean_occ = sum(m.push_count for m in reported) / (100.0 * len(reported))
        mean_ttft = sum(m.ewma_ms for m in reported) / len(reported)
        detail = {
            "n_engines": len(engines), "mean_occupancy": round(mean_occ, 3),
            "mean_ttft_ms": round(mean_ttft, 2),
            "per_engine": {m.rank: {"occupancy": m.push_count / 100.0,
                                    "queued": m.step, "ttft_ms": m.ewma_ms}
                           for m in reported},
        }
        direction = None
        if (self.engine_occ_high > 0 and mean_occ >= self.engine_occ_high) \
                or (self.engine_slo_ttft_ms > 0
                    and mean_ttft > self.engine_slo_ttft_ms):
            direction = "up"
        elif (self.engine_occ_low > 0 and mean_occ <= self.engine_occ_low
              and len(engines) > 1):
            direction = "down"
        if direction is None:
            return None
        self._next_scale_at = now + self.scale_cooldown
        self.scale_advice.append((direction, detail))
        self._log(
            f"engine scale-{direction} advised: mean occupancy "
            f"{mean_occ:.0%}, mean TTFT {mean_ttft:.1f} ms over "
            f"{len(reported)} reporting engine(s)")
        if self.on_scale is not None:
            self.on_scale(direction, detail)
        return direction

    # ---------------------------------------------------------- speculation
    def check_stragglers(self) -> Optional[int]:
        """Sandblaster backup tasks: when the slowest reporting worker's
        step-latency EWMA exceeds ``straggler_factor`` x the fleet median,
        replicate its remaining work to the fastest worker. Returns the
        task id when a speculation fired."""
        workers = [m for m in self._live(KIND_WORKER)
                   if m.ewma_ms > 0 and m.step >= self.straggler_after_steps
                   and m.rank not in self.speculated]
        if len(workers) < 2:
            return None
        by_speed = sorted(workers, key=lambda m: m.ewma_ms)
        victim = by_speed[-1]
        # lower median: at 2 workers this compares the slow one to the
        # OTHER worker (len//2 would pick the victim itself and the
        # detector could never fire on the smallest fleet)
        median = by_speed[(len(by_speed) - 1) // 2].ewma_ms
        if median <= 0 or victim.ewma_ms < self.straggler_factor * median:
            return None
        backup = by_speed[0]
        task_id = self._next_task
        self._next_task += 1
        self.speculated[victim.rank] = task_id
        self._log(
            f"straggler: worker {victim.rank} at {victim.ewma_ms:.1f} ms/step "
            f"(median {median:.1f}) — speculating its tail on worker "
            f"{backup.rank} as task {task_id}")
        frame = np.asarray(
            [float(task_id), float(victim.rank), float(victim.step)],
            np.float32)
        # BOTH parties get the task: the backup so it races the tail, the
        # victim so it tags its own late result with the same id — the PS
        # dedup (first task result wins) is what makes the duplication safe
        self._send(backup.rank, MessageCode.SpeculateTask, frame)
        self._send(victim.rank, MessageCode.SpeculateTask, frame)
        return task_id

    # distcheck: ignore[DC205] serve-thread only: the sole caller is
    # GrayHealth._enter_probation, reached from gray.tick() inside this
    # coordinator's own run loop — same thread as check_stragglers
    def speculate_victim(self, victim_rank: int) -> Optional[int]:
        """Route-around actuator for the gray plane (ISSUE 20): replicate
        a PROBATION worker's remaining work onto the fastest healthy
        worker, reusing the Sandblaster backup-task machinery verbatim —
        probation bends traffic away from the suspect instead of waiting
        for the straggler detector's latency threshold to notice it."""
        victim = self.members.get(victim_rank)
        if (victim is None or victim.kind != KIND_WORKER
                or victim_rank in self.speculated):
            return None
        candidates = [m for m in self._live(KIND_WORKER)
                      if m.rank != victim_rank]
        if not candidates:
            return None
        backup = min(candidates, key=lambda m: (m.ewma_ms, m.rank))
        task_id = self._next_task
        self._next_task += 1
        self.speculated[victim_rank] = task_id
        self._log(
            f"gray probation: speculating worker {victim_rank}'s tail on "
            f"worker {backup.rank} as task {task_id}")
        frame = np.asarray(
            [float(task_id), float(victim_rank), float(victim.step)],
            np.float32)
        self._send(backup.rank, MessageCode.SpeculateTask, frame)
        self._send(victim_rank, MessageCode.SpeculateTask, frame)
        return task_id

    # ----------------------------------------------------------------- run
    def stop(self) -> None:
        self._stop.set()

    def run(self, timeout: Optional[float] = None) -> None:
        """Serve until ``stop()`` (or ``timeout``): pump frames + tick."""
        if self.transport is None:
            raise ValueError("Coordinator.run needs a transport")
        deadline = None if timeout is None else self._clock() + timeout
        next_tick = self._clock()
        while not self._stop.is_set():
            now = self._clock()
            if deadline is not None and now >= deadline:
                break
            if now >= next_tick or self._snap_requested:
                # a requested snapshot barrier starts at the next loop pass,
                # not the next lease tick — drills measure MTTR in real time
                self.tick()
                next_tick = now + max(0.05, self.lease / 4)
            msg = self.transport.recv(timeout=0.1)
            if msg is None:
                continue
            try:
                self.handle(*msg)
            except (ValueError, IndexError, OverflowError):
                continue  # malformed member frame: drop, never die
