"""L0 runtime: device mesh bootstrap (replaces reference ``example/main.py:163-165``).

The reference bootstraps distribution with env-var TCP rendezvous into a gloo
process group::

    os.environ['MASTER_ADDR'] = args.master
    os.environ['MASTER_PORT'] = args.port
    dist.init_process_group('gloo', rank=args.rank, world_size=args.world_size)

The TPU-native analog is multi-controller JAX: ``jax.distributed.initialize``
replaces the rendezvous (coordinator address in place of MASTER_ADDR:PORT),
and the transport underneath is XLA's compiled collectives over ICI within a
slice / DCN across slices — not a Python socket layer. All parallelism in this
framework is expressed over a named ``jax.sharding.Mesh`` built here.

For single-host testing, ``simulate_cpu_devices(n)`` documents the env recipe
that stands in for a cluster, mirroring how the reference smoke-tests its
3-rank topology on localhost (``Makefile:13-20``, SURVEY.md §4).
"""

from __future__ import annotations

import os
from typing import Mapping, Sequence

import numpy as np

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the multi-host runtime.

    Maps the reference CLI surface onto JAX's coordinator: ``--master``/
    ``--port`` → ``coordinator_address``, ``--world-size`` → ``num_processes``,
    ``--rank`` → ``process_id`` (reference ``example/main.py:151-155,163-165``).
    On Cloud TPU pods all three arguments are discovered automatically and may
    be ``None``. Safe to call once per process, before any jax computation.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def simulate_cpu_devices(n: int = 8) -> None:
    """Arrange for ``n`` virtual CPU devices (single-host cluster simulation).

    Must run before jax initializes a backend. This is the framework's analog
    of the reference's localhost multi-process smoke topology (SURVEY.md §4):
    unit tests exercise real ``psum``/``ppermute`` collectives on an n-device
    CPU mesh without TPU hardware.
    """
    _set_host_device_count_flag(n)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _set_host_device_count_flag(n: int) -> None:
    """Put the host-platform device-count token into XLA_FLAGS, replacing
    any token with a different count (last-request-wins, e.g. an
    ``ensure_min_devices(2)`` demo bootstrap followed by the test
    conftest's ``force_cpu_devices(8)``). Only effective before the first
    CPU client is created — the runtime parses the flag once."""
    flags = os.environ.get("XLA_FLAGS", "")
    token = f"--xla_force_host_platform_device_count={n}"
    if token in flags.split():
        return
    kept = [f for f in flags.split()
            if not f.startswith("--xla_force_host_platform_device_count")]
    os.environ["XLA_FLAGS"] = " ".join(kept + [token])


def force_cpu_devices(n: int = 8) -> None:
    """Re-initialize jax on the CPU platform with ``n`` virtual devices, even if
    a backend is already live (this environment's sitecustomize initializes a
    TPU backend at interpreter boot). Used by tests and the localhost demos to
    simulate a multi-chip mesh on one host — the framework's analog of the
    reference's localhost multi-process smoke topology (SURVEY.md §4).
    """
    import jax as _jax

    # Set the device-count flag BEFORE touching jax.devices(): the CPU client
    # reads XLA_FLAGS once at its first creation, so on runtimes without the
    # jax_num_cpu_devices config option this is the only lever — and it only
    # works if no CPU backend exists yet.
    simulate_cpu_devices(n)
    devs = _jax.devices()
    if len(devs) >= n and devs[0].platform == "cpu":
        return
    from jax._src import xla_bridge

    xla_bridge._clear_backends()
    xla_bridge.get_backend.cache_clear()
    _jax.config.update("jax_platforms", "cpu")
    try:
        _jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # older jax: no such option; the re-created backend re-reads the
        # XLA_FLAGS token set above on runtimes that parse flags per-client
        pass
    assert len(_jax.devices()) == n, _jax.devices()


def ensure_min_devices(n: int) -> None:
    """Guarantee at least ``n`` devices, provisioning virtual CPU devices
    only when needed.

    Unlike calling ``jax.devices()`` and then :func:`force_cpu_devices`,
    this sets the host-platform device-count flag BEFORE the first backend
    creation when no backend exists yet — on runtimes without the
    ``jax_num_cpu_devices`` config option that order is the only one that
    works. Only the flag is set pre-boot (never ``JAX_PLATFORMS``), so a
    host with real accelerator chips still initializes them and is left
    untouched when they satisfy ``n``.
    """
    from jax._src import xla_bridge

    if not xla_bridge._backends:
        _set_host_device_count_flag(n)
    if len(jax.devices()) < n:
        force_cpu_devices(n)


def make_mesh(
    axis_sizes: Mapping[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named device mesh.

    ``axis_sizes`` maps axis names to sizes, e.g. ``{"data": 8}`` or
    ``{"data": 4, "model": 2}``. Defaults to a 1-D ``data`` mesh over every
    addressable device — the shape of the reference's world (rank list) with
    the parameter-server specialization removed: in sync SPMD every device is
    a worker.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if axis_sizes is None:
        axis_sizes = {"data": len(devs)}
    names = tuple(axis_sizes.keys())
    shape = tuple(axis_sizes.values())
    n = int(np.prod(shape))
    if n != len(devs):
        raise ValueError(
            f"mesh shape {dict(axis_sizes)} needs {n} devices, have {len(devs)}"
        )
    if devices is None:
        mesh_devs = mesh_utils.create_device_mesh(shape)
    else:
        mesh_devs = np.array(devs).reshape(shape)
    return Mesh(mesh_devs, names)


def sharded_init(init_fn, rng, shardings):
    """Jit ``init_fn(rng)`` so its output lands with ``shardings`` — with
    values INDEPENDENT of the mesh shape.

    On runtimes whose threefry is not partitionable (jax <= 0.4.x default),
    ``jit(init_fn, out_shardings=...)`` generates DIFFERENT random values for
    a leaf that is sharded over one mesh axis while replicated over another
    (measured: identical keys gave divergent block kernels on a
    ``{"data": 2, "stage": 2}`` mesh vs a ``{"stage": 2}`` mesh — the root
    cause of the dp×pp×tp composite-loss "divergence" in dryrun_multichip;
    1-D meshes agree with the unsharded init exactly). There the init runs
    unsharded and is resharded with ``device_put`` — every device briefly
    holds the full tree, the compat price of value-determinism. With a
    partitionable threefry the sharded lowering is already value-invariant,
    so the memory-frugal ``out_shardings`` path is kept.
    """
    if jax.config.jax_threefry_partitionable:
        return jax.jit(init_fn, out_shardings=shardings)(rng)
    return jax.device_put(jax.jit(init_fn)(rng), shardings)


def data_mesh(n_devices: int | None = None) -> Mesh:
    """1-D ``data`` mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return make_mesh({"data": len(devs)}, devices=devs)


def local_device_count() -> int:
    return jax.local_device_count()


def process_rank() -> int:
    """This controller's rank (reference ``dist.get_rank()``, ``example/main.py:105``)."""
    return jax.process_index()


def world_size() -> int:
    return jax.process_count()
