from distributed_ml_pytorch_tpu.runtime.mesh import (
    initialize_distributed,
    data_mesh,
    make_mesh,
    simulate_cpu_devices,
    local_device_count,
    process_rank,
    world_size,
)

__all__ = [
    "initialize_distributed",
    "data_mesh",
    "make_mesh",
    "simulate_cpu_devices",
    "local_device_count",
    "process_rank",
    "world_size",
]
