"""C12 parity: cloud job submission.

The reference submits ``example/main.py`` to an AzureML compute target and
prints the portal URL (``run-pytorch.py:7-19``). The TPU-native analog targets
Cloud TPU VMs. With no cloud SDK/credentials in the environment, this module
always *builds* the full submission spec; it submits when the ``gcloud`` CLI
is available and otherwise prints the exact commands to run (a dry-run, which
in an air-gapped build environment is the whole behavior — the reference's
observable contract is "submit and print how to watch the run").
"""

from __future__ import annotations

import argparse
import shlex
import shutil
import subprocess
import sys
from dataclasses import dataclass, field
from typing import List


def _label_value(name: str) -> str:
    """Sanitize a free-form experiment name into a valid GCP label value."""
    import re

    return re.sub(r"[^a-z0-9_-]", "-", name.lower())[:63] or "experiment"


@dataclass
class TPUJobSpec:
    """Submission spec (the ScriptRunConfig analog, ``run-pytorch.py:10-12``)."""

    name: str = "single-cpu"                # reference experiment name (:9)
    compute_target: str = "distbelief-single"  # reference target name (:12)
    accelerator_type: str = "v5litepod-1"
    zone: str = "us-central1-a"
    runtime_version: str = "tpu-ubuntu2204-base"
    script: str = "distributed_ml_pytorch_tpu.training.cli"
    script_args: List[str] = field(default_factory=list)

    def create_command(self) -> List[str]:
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "create", self.compute_target,
            f"--zone={self.zone}",
            f"--accelerator-type={self.accelerator_type}",
            f"--version={self.runtime_version}",
            # experiment name (run-pytorch.py:9); GCP label values must be
            # lowercase [a-z0-9_-], <=63 chars
            f"--labels=experiment={_label_value(self.name)}",
        ]

    def run_command(self) -> List[str]:
        inner = "python -m {} {}".format(
            self.script, " ".join(shlex.quote(a) for a in self.script_args)
        )
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "ssh", self.compute_target,
            f"--zone={self.zone}",
            "--worker=all",
            f"--command={inner}",
        ]

    def portal_url(self) -> str:
        return (
            "https://console.cloud.google.com/compute/tpus/details/"
            f"{self.zone}/{self.compute_target}"
        )


def submit(spec: TPUJobSpec, dry_run: bool = False) -> str:
    """Submit (or print) the job; returns the portal URL (parity with
    ``run.get_portal_url()``, ``run-pytorch.py:18-19``)."""
    cmds = [spec.create_command(), spec.run_command()]
    if dry_run or shutil.which("gcloud") is None:
        reason = "dry run" if dry_run else "no gcloud available — dry run"
        print(f"# {reason}; execute these to submit:")
        for cmd in cmds:
            print(" ".join(shlex.quote(c) for c in cmd))
    else:
        # create is idempotent: an already-existing compute target is fine
        # (resubmission to the same target, like the reference's reuse of its
        # AzureML compute target); any other create failure is fatal.
        create = subprocess.run(spec.create_command(), capture_output=True, text=True)
        if create.returncode != 0:
            err = (create.stderr or "") + (create.stdout or "")
            if "already exists" not in err.lower() and "ALREADY_EXISTS" not in err:
                sys.stderr.write(err)
                raise subprocess.CalledProcessError(
                    create.returncode, spec.create_command(), output=err
                )
        subprocess.run(spec.run_command(), check=True)
    url = spec.portal_url()
    print(url)
    return url


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="Submit a training job to a Cloud TPU VM")
    p.add_argument("--name", default="single-cpu")
    p.add_argument("--compute-target", default="distbelief-single")
    p.add_argument("--accelerator-type", default="v5litepod-1")
    p.add_argument("--zone", default="us-central1-a")
    p.add_argument("--dry-run", action="store_true")
    args, extra = p.parse_known_args(argv)
    if extra and extra[0] == "--":
        extra = extra[1:]
    spec = TPUJobSpec(
        name=args.name,
        compute_target=args.compute_target,
        accelerator_type=args.accelerator_type,
        zone=args.zone,
        script_args=extra,
    )
    submit(spec, dry_run=args.dry_run)
    return 0


if __name__ == "__main__":
    sys.exit(main())
