"""C4/C5: the trainer and evaluator (parity with reference ``example/main.py:31-133``).

The reference's hot loop (``example/main.py:57-91``) is zero_grad → forward →
cross_entropy → backward → step with periodic eval. Here the whole step —
forward, loss, backward, SGD update — is one jitted function: XLA fuses the
elementwise chain into the conv/matmul kernels on the MXU, and the only
host↔device traffic per step is the input batch in and a scalar loss out.

Parity decisions (SURVEY.md §7 "reproduce the intent, not the defect"):

- plain SGD, ``momentum=0.0`` (reference ``example/main.py:44``);
- eval every ``log_interval`` batches with ``i > 0`` (``:83-84``) and a
  verbose eval each epoch end (``:93``);
- ``test_loss`` is the *sum* of per-batch mean losses (``:125`` semantics —
  identical to a single number when ``test_batch_size`` covers the whole
  set, the reference default of 10000);
- accuracy over the **full** test set (the reference scores only its final
  batch with swapped args — a defect, not copied);
- no eval-mode leak: dropout is controlled per-call by ``train=``, unlike the
  reference whose ``net.eval()`` at ``:113`` permanently disables dropout
  after the first mid-epoch eval;
- the never-stepped LambdaLR scheduler (``:47-48``): the default
  (``--lr-schedule constant``) matches the reference's *effective* behavior,
  and ``make_lr_schedule`` offers its *configured* 1/(epoch+1) decay done
  right (``inverse-epoch``), plus cosine.
"""

from __future__ import annotations

import sys
import time
from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from distributed_ml_pytorch_tpu.data import CIFAR10_CLASSES, iterate_batches
from distributed_ml_pytorch_tpu.utils.metrics import (
    MetricsLogger,
    print_classification_report,
    print_eval_line,
)
from distributed_ml_pytorch_tpu.utils.tracing import (
    StepTimer,
    TraceWindow,
    annotate_step,
)

Pytree = Any


class TrainState(struct.PyTreeNode):
    """Minimal functional train state: params + optimizer state + step count."""

    params: Pytree
    opt_state: optax.OptState
    step: jnp.ndarray

    @classmethod
    def create(cls, params: Pytree, tx: optax.GradientTransformation) -> "TrainState":
        return cls(params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32))


def make_lr_schedule(
    kind: str, lr: float, steps_per_epoch: int = 1, total_epochs: int = 1
):
    """Working LR schedules — the reference *configures* a ``LambdaLR`` with
    ``1/(epoch+1)`` decay but never calls ``scheduler.step()``, so its lr
    stays constant (``example/main.py:47-48``; SURVEY.md §5.6 flags the dead
    scheduler). This implements the intent:

    - ``constant`` — the reference's *effective* behavior (default);
    - ``inverse-epoch`` — the reference's *configured* behavior, done right;
    - ``cosine`` — cosine decay to 0 over the whole run.

    Returns an optax schedule (step → lr) or a float for ``constant``.
    """
    if kind == "constant":
        return lr
    if kind == "inverse-epoch":
        spe = max(1, int(steps_per_epoch))
        return lambda step: lr / (step // spe + 1)
    if kind == "cosine":
        total = max(1, int(steps_per_epoch) * int(total_epochs))
        return optax.cosine_decay_schedule(lr, decay_steps=total)
    raise ValueError(f"unknown lr schedule {kind!r} (constant|inverse-epoch|cosine)")


def make_optimizer(
    name: str,
    lr,
    momentum: float = 0.0,
    weight_decay: float | None = None,
    grad_clip: float = 0.0,
) -> optax.GradientTransformation:
    """Optimizer registry for the ``--optimizer`` flag.

    ``sgd`` is the reference's recipe (``optim.SGD(lr, momentum=0.0)``,
    ``example/main.py:44``); ``adam`` and ``adamw`` are extensions. ``lr``
    may be a float or an optax schedule.

    ``grad_clip > 0`` prepends global-norm clipping. ``weight_decay`` is
    decoupled (AdamW-style) for ``adamw``; for ``sgd``/``adam`` it is
    classic L2 regularization (``optax.add_decayed_weights`` folded into the
    gradient before the update rule). ``None`` (the default) keeps each
    optimizer's own default — in particular adamw retains optax's 1e-4 —
    while an explicit ``0.0`` disables decay.
    """
    name = name.lower()
    if name == "sgd":
        base = optax.sgd(lr, momentum=momentum if momentum else None)
    elif name == "adam":
        base = optax.adam(lr)
    elif name == "adamw":
        base = optax.adamw(lr) if weight_decay is None else optax.adamw(
            lr, weight_decay=weight_decay
        )
    else:
        raise ValueError(f"unknown optimizer {name!r} (sgd|adam|adamw)")
    chain = []
    if grad_clip and grad_clip > 0:
        chain.append(optax.clip_by_global_norm(grad_clip))
    if weight_decay and name in ("sgd", "adam"):
        chain.append(optax.add_decayed_weights(weight_decay))
    if not chain:
        return base
    return optax.chain(*chain, base)


def build_tx(
    optimizer: str,
    lr,
    momentum: float = 0.0,
    weight_decay: float | None = None,
    grad_clip: float = 0.0,
    grad_accum: int = 1,
) -> optax.GradientTransformation:
    """``make_optimizer`` + the grad-accumulation wrap — the single assembly
    point shared by :func:`create_train_state` and :func:`tx_from_args` so
    a new chain element cannot diverge between the kwarg and CLI paths."""
    tx = make_optimizer(optimizer, lr, momentum, weight_decay, grad_clip)
    if int(grad_accum) > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=int(grad_accum))
    return tx


def create_train_state(
    model,
    rng: jax.Array,
    lr,
    momentum: float = 0.0,
    sample_shape=(1, 32, 32, 3),
    grad_accum: int = 1,
    optimizer: str = "sgd",
    weight_decay: float | None = None,
    grad_clip: float = 0.0,
) -> Tuple[TrainState, optax.GradientTransformation]:
    """Initialize params + optimizer (reference ``optim.SGD(lr, momentum=0.0)``,
    ``example/main.py:44``). ``lr`` may be a float or an optax schedule
    (see :func:`make_lr_schedule`).

    ``grad_accum > 1`` wraps the optimizer in ``optax.MultiSteps``: gradients
    average over that many consecutive micro-batches before one SGD update
    is applied — the effective batch grows without growing per-step HBM.
    """
    params = model.init(rng, jnp.zeros(sample_shape))["params"]
    tx = build_tx(optimizer, lr, momentum, weight_decay, grad_clip, grad_accum)
    return TrainState.create(params, tx), tx


def tx_from_args(args, steps_per_epoch: int) -> optax.GradientTransformation:
    """Build the optax transform from the CLI argument surface — the ONE
    place the optimizer/schedule/accumulation knobs are read, shared by the
    single-process, sync/fsdp, local-sgd, AND async-PS trainers so a new
    knob cannot be silently dropped by one mode.

    ``steps_per_epoch`` is in raw batches; with ``--grad-accum K`` the LR
    schedule advances once per K micro-batches (``optax.MultiSteps`` emits
    one optimizer update per K), so the schedule's epoch is measured in
    optimizer updates.
    """
    grad_accum = int(getattr(args, "grad_accum", 1) or 1)
    lr = make_lr_schedule(
        getattr(args, "lr_schedule", "constant"),
        args.lr,
        steps_per_epoch=max(1, int(steps_per_epoch) // grad_accum),
        total_epochs=args.epochs,
    )
    return build_tx(
        getattr(args, "optimizer", "sgd"),
        lr,
        getattr(args, "momentum", 0.0),
        getattr(args, "weight_decay", None),
        getattr(args, "grad_clip", 0.0),
        grad_accum,
    )


def state_from_args(args, model, steps_per_epoch: int, sample_shape=(1, 32, 32, 3)):
    """``(state, tx)`` from the CLI surface (see :func:`tx_from_args`)."""
    tx = tx_from_args(args, steps_per_epoch)
    params = model.init(
        jax.random.key(getattr(args, "seed", 0)), jnp.zeros(sample_shape)
    )["params"]
    return TrainState.create(params, tx), tx


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy (reference ``F.cross_entropy``, ``example/main.py:71``)."""
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def chunked_lm_loss(model, params, tokens, targets, chunk: int = 2048,
                    ce_dtype=None):
    """Masked-mean next-token CE WITHOUT materializing (batch, seq, vocab)
    logits — the long-context LM loss.

    At S=32k the GPT-2-small logits tensor alone is 6.6 GB (f32), which is
    what stops the full model training at that length, not the attention
    (the flash kernel handles S=32k fine — ops/attention.py). This runs
    the Transformer body once (``model.clone(head=False)`` → post-LayerNorm
    hiddens, O(S·d)), then a ``lax.scan`` over sequence chunks applies the
    lm_head matmul + CE per chunk under ``jax.checkpoint`` — the backward
    recomputes each chunk's logits instead of saving them, so peak logits
    memory is O(chunk·vocab) in both passes.

    Same loss definition as ``fsdp.lm_loss_builder`` (final sequence
    position masked); exact equality is tested. ``seq`` must divide by
    ``chunk``.

    ``ce_dtype`` (default ``None``): dtype the per-chunk logits are cast
    to before the softmax CE. ``None`` keeps the activation dtype — the
    dense-loss convention, +3.7% on the 32k leg vs an f32 upcast. Under
    bf16 activations the CE gradient (softmax − one-hot) is then computed
    from 8-bit-mantissa logits; a measured 60-step bf16 training
    comparison at vocab 16k tracks the per-chunk-f32 trajectory within
    noise (``tests/test_transformer.py::
    test_chunked_lm_loss_bf16_ce_tracks_f32_ce_training``), but callers
    training larger vocabularies who want f32 CE can pass
    ``ce_dtype=jnp.float32`` — the upcast buffer is per-chunk
    (``chunk × vocab``), not the full sequence.
    """
    b, s = tokens.shape
    if s % chunk:
        raise ValueError(f"seq {s} must divide by chunk {chunk}")
    h = model.clone(head=False).apply({"params": params}, tokens)
    w = params["lm_head"]["kernel"]
    n = s // chunk
    hc = h.reshape(b, n, chunk, h.shape[-1]).transpose(1, 0, 2, 3)
    tc = targets.reshape(b, n, chunk).transpose(1, 0, 2)
    mask = jnp.ones((b, s), jnp.float32).at[:, -1].set(0.0)
    mc = mask.reshape(b, n, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_ce(w_, h_c, t_c, m_c):
        # 2-D logits in the activation dtype — the same convention as the
        # dense loss (fsdp.lm_loss_builder): the old per-chunk f32 upcast
        # materialized a 412 MB f32 logits buffer per 2048-token chunk at
        # GPT-2-small shapes (2x the bf16 bytes through HBM, twice per
        # step under the checkpoint's recompute)
        b_, c_, d_ = h_c.shape
        logits = h_c.reshape(b_ * c_, d_) @ w_.astype(h_c.dtype)
        if ce_dtype is not None:
            logits = logits.astype(ce_dtype)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, t_c.reshape(-1))
        return jnp.sum(ce * m_c.reshape(-1))

    def body(carry, xs):
        h_c, t_c, m_c = xs
        return carry + chunk_ce(w, h_c, t_c, m_c), None

    loss_sum, _ = jax.lax.scan(body, jnp.zeros(()), (hc, tc, mc))
    return loss_sum / jnp.sum(mask)


def _sgd_step_body(model, tx, state: TrainState, images, labels, dropout_rng):
    """Unjitted single-step update shared by the per-step and scanned trainers.

    The dropout rng folds in ``state.step``, so the same body produces the
    same stream whether steps are dispatched one at a time or scanned.
    """
    rng = jax.random.fold_in(dropout_rng, state.step)

    def loss_fn(params):
        logits = model.apply(
            {"params": params}, images, train=True, rngs={"dropout": rng}
        )
        return cross_entropy_loss(logits, labels)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return state.replace(params=params, opt_state=opt_state, step=state.step + 1), loss


def make_train_step(model, tx: optax.GradientTransformation) -> Callable:
    """One fully-jitted SGD step: forward + loss + backward + update."""

    # Donating the state lets XLA update params/opt-state in place instead of
    # allocating a second copy in HBM each step (ignored, with no harm, on
    # backends that can't donate).
    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, images, labels, dropout_rng) -> Tuple[TrainState, jnp.ndarray]:
        return _sgd_step_body(model, tx, state, images, labels, dropout_rng)

    return train_step


def make_scan_train_step(model, tx: optax.GradientTransformation) -> Callable:
    """K SGD steps in ONE compiled program via ``lax.scan`` — the TPU-idiomatic
    trainer for small models, where per-step host dispatch dominates.

    ``(state, images [K,B,...], labels [K,B], dropout_rng) → (state, losses [K])``
    processes K *distinct* microbatches with exactly the same per-step update
    (and dropout stream) as :func:`make_train_step` dispatched K times — the
    equivalence is tested — but pays the host→device round-trip once per K
    steps instead of per step. On a tunneled/latency-bound device this is an
    order of magnitude in throughput; there is no reference counterpart
    (its hot loop is Python per step, ``example/main.py:59-91``).
    """

    @partial(jax.jit, donate_argnums=(0,))
    def scan_train_step(state: TrainState, images, labels, dropout_rng):
        def body(st, batch):
            bx, by = batch
            return _sgd_step_body(model, tx, st, bx, by, dropout_rng)

        return jax.lax.scan(body, state, (images, labels))

    return scan_train_step


def _accum_update_body(model, tx, microbatch: int, state: TrainState,
                       images, labels, dropout_rng,
                       effective_update_batch: Optional[int],
                       remat: bool):
    """Unjitted large-batch update via a microbatch accumulation scan.

    ``images`` is one large batch ``(B, ...)`` with ``B = k·microbatch``;
    the scan runs the forward+backward on each microbatch and accumulates
    the SUM of per-microbatch mean gradients into a zeros-initialized
    accumulator (a scan carry — XLA updates it in place, so peak HBM is
    one microbatch's activations + one gradient-sized buffer, never the
    full batch's activations).

    Update semantics (the large-batch recipe knob):

    - ``effective_update_batch=None`` — the accumulated grad is divided
      by ``k``: exactly the mean over the full ``B`` (one large-batch
      step; equal to the unaccumulated step up to float summation order).
    - ``effective_update_batch=e`` (e.g. 64) — the accumulated grad is
      scaled by ``microbatch/e``, making it ``Σ`` of the ``B/e``
      batch-``e`` mean gradients at the current params. For SGD the
      applied update is then the SUM of the ``B/e`` reference-recipe
      batch-``e`` updates evaluated at frozen params — first-order
      equivalent to ``B/e`` sequential recipe steps (linear-scaling, per
      the weight-update engineering of arXiv:2004.13336) — so the
      throughput leg preserves the batch-64 *effective update* while the
      compute runs at large-batch geometry.

    ``remat`` wraps the microbatch loss in ``jax.checkpoint`` (recompute
    activations in the backward) — measured OFF as the default: AlexNet
    microbatch activations are far below HBM, so remat only adds FLOPs.
    """
    b = images.shape[0]
    if b % microbatch:
        raise ValueError(f"batch {b} must divide by microbatch {microbatch}")
    k = b // microbatch
    if effective_update_batch is not None:
        if effective_update_batch <= 0:
            raise ValueError(
                f"effective_update_batch must be positive, got "
                f"{effective_update_batch} (use None for the large-batch "
                f"mean update)")
        scale = microbatch / float(effective_update_batch)
    else:
        scale = 1.0 / k
    mi = images.reshape(k, microbatch, *images.shape[1:])
    ml = labels.reshape(k, microbatch)

    def micro_loss(params, bx, by, rng):
        logits = model.apply(
            {"params": params}, bx, train=True, rngs={"dropout": rng})
        return cross_entropy_loss(logits, by)

    if remat:
        micro_loss = jax.checkpoint(micro_loss)
    step_key = jax.random.fold_in(dropout_rng, state.step)

    def body(carry, batch):
        acc, loss_sum, j = carry
        bx, by = batch
        rng = jax.random.fold_in(step_key, j)  # unique per (update, micro)
        loss, grads = jax.value_and_grad(micro_loss)(state.params, bx, by, rng)
        acc = jax.tree.map(jnp.add, acc, grads)
        return (acc, loss_sum + loss, j + 1), None

    zeros = jax.tree.map(jnp.zeros_like, state.params)
    carry0 = (zeros, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32))
    (acc, loss_sum, _), _ = jax.lax.scan(body, carry0, (mi, ml))
    grads = jax.tree.map(lambda gsum: gsum * scale, acc)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    new_state = state.replace(
        params=params, opt_state=opt_state, step=state.step + 1)
    return new_state, loss_sum / k


def make_accum_train_step(model, tx: optax.GradientTransformation,
                          microbatch: int,
                          effective_update_batch: Optional[int] = None,
                          remat: bool = False) -> Callable:
    """ONE optimizer update from a large batch via a microbatch scan.

    ``(state, images [B, ...], labels [B], dropout_rng) → (state, loss)``
    with ``B`` a multiple of ``microbatch``. See :func:`_accum_update_body`
    for the accumulator and update-scaling semantics; the state is donated
    so params/opt-state update in place.
    """

    @partial(jax.jit, donate_argnums=(0,))
    def accum_step(state: TrainState, images, labels, dropout_rng):
        return _accum_update_body(
            model, tx, microbatch, state, images, labels, dropout_rng,
            effective_update_batch, remat)

    return accum_step


def make_scan_accum_train_step(model, tx: optax.GradientTransformation,
                               microbatch: int,
                               effective_update_batch: Optional[int] = None,
                               remat: bool = False) -> Callable:
    """U accumulated large-batch updates in ONE compiled program.

    ``(state, images [U, B, ...], labels [U, B], dropout_rng) →
    (state, losses [U])`` — the :func:`make_scan_train_step` analog for
    the gradient-accumulation recipe, so the large-batch bench legs pay
    host dispatch once per U updates like the parity leg does.
    """

    @partial(jax.jit, donate_argnums=(0,))
    def scan_accum_step(state: TrainState, images, labels, dropout_rng):
        def outer(st, batch):
            bx, by = batch
            return _accum_update_body(
                model, tx, microbatch, st, bx, by, dropout_rng,
                effective_update_batch, remat)

        return jax.lax.scan(outer, state, (images, labels))

    return scan_accum_step


def make_eval_fn(model) -> Callable:
    """Jitted per-batch eval: (summed-mean loss contribution, predictions)."""

    @jax.jit
    def eval_step(params, images, labels):
        logits = model.apply({"params": params}, images, train=False)
        loss = cross_entropy_loss(logits, labels)
        preds = jnp.argmax(logits, axis=-1)
        return loss, preds

    return eval_step


def evaluate(
    eval_step: Callable,
    params: Pytree,
    x_test: np.ndarray,
    y_test: np.ndarray,
    test_batch_size: int,
    verbose: bool = False,
) -> Tuple[float, float]:
    """Full test-set pass (reference ``evaluate``, ``example/main.py:110-133``).

    Returns ``(test_loss, test_accuracy)`` where ``test_loss`` accumulates
    per-batch mean losses (reference ``:125`` summed semantics) and accuracy
    covers the whole test set.
    """
    total_loss = 0.0
    preds_all = []
    labels_all = []
    for bx, by in iterate_batches(
        x_test, y_test, min(test_batch_size, len(x_test)), shuffle=False, drop_last=False
    ):
        loss, preds = eval_step(params, bx, by)
        total_loss += float(loss)
        preds_all.append(np.asarray(preds))
        labels_all.append(by)
    y_pred = np.concatenate(preds_all)
    y_true = np.concatenate(labels_all)
    accuracy = float((y_pred == y_true).mean())
    if verbose:
        print_classification_report(y_true, y_pred, CIFAR10_CLASSES, total_loss, accuracy)
    return total_loss, accuracy


def run_training_loop(
    *,
    model,
    state: TrainState,
    train_step: Callable,
    eval_step: Callable,
    data,
    args,
    logger: MetricsLogger,
    on_step: Optional[Callable] = None,
    ckpt=None,
    start_epoch: int = 0,
    start_iter: int = 0,
    scan_step: Optional[Callable] = None,
) -> TrainState:
    """Shared epoch/batch loop (reference ``example/main.py:57-93`` shape).

    ``on_step(state, epoch, i) -> state`` lets parallel strategies hook the
    between-steps boundary (e.g. the async-PS param swap) without forking the
    trainer — the backend-agnosticism SURVEY.md §7 calls for.

    ``ckpt`` (a ``utils.checkpoint.Checkpointer``) is offered every step after
    the update; its ``save_interval_steps`` decides which are accepted, and the
    saves are async so the next step launches while bytes drain to disk.
    ``start_epoch``/``start_iter`` fast-forward a resumed run to the exact
    batch (the shuffle order is a pure function of ``(seed, epoch)``).

    ``scan_step`` (``make_scan_train_step``-shaped) enables chunked dispatch:
    with ``--steps-per-dispatch K > 1``, up to K consecutive batches are
    stacked and trained in one compiled program. Chunks never cross a
    ``log_interval`` or ``--ckpt-every`` boundary (evals see exactly the
    params they would per-step; checkpoint steps land on exact multiples, as
    orbax requires), and per-step losses still land in the CSV row-for-row
    (the scan returns all K). Batches are uniform (``iterate_batches`` drops
    the last partial batch), so stacking is always well-shaped.
    """
    x_train, y_train, x_test, y_test = data
    dropout_rng = jax.random.key(getattr(args, "seed", 0) + 1)
    tracer = TraceWindow(
        getattr(args, "profile_dir", None),
        start=getattr(args, "profile_start", 10),
        n_steps=getattr(args, "profile_steps", 10),
    )
    # persistent step counter: resumed runs continue where the checkpoint
    # left off, so --profile-start addresses the same step numbering as
    # --ckpt-every and the CSV logs
    global_step = int(state.step)
    # one timer for the whole run: warmup-skip covers XLA compile, which
    # only happens on the first steps; per-epoch stats via reset_stats()
    timer = StepTimer(items_per_step=args.batch_size)
    chunk_k = int(getattr(args, "steps_per_dispatch", 1) or 1)
    use_scan = scan_step is not None and chunk_k > 1 and on_step is None

    def run_one(state, i, bx, by):
        """One per-step dispatch (the reference-shaped path)."""
        nonlocal global_step
        tracer.on_step(global_step)
        if on_step is not None:
            state = on_step(state, epoch, i)
        timer.start()
        with annotate_step("train", global_step):
            state, loss = train_step(state, bx, by, dropout_rng)
            loss_val = float(loss)  # blocks on the step's output
        timer.tick()
        if ckpt is not None:
            ckpt.save(int(state.step), state)
        global_step += 1
        tracer.after_step(global_step)
        return state, [(i, loss_val)]

    def run_chunk(state, chunk):
        """One scanned dispatch over len(chunk) stacked batches."""
        nonlocal global_step
        if len(chunk) == 1:
            return run_one(state, *chunk[0])
        tracer.on_step(global_step, n_steps=len(chunk))
        bxs = np.stack([c[1] for c in chunk])
        bys = np.stack([c[2] for c in chunk])
        timer.start()
        with annotate_step("train", global_step):
            state, losses = scan_step(state, bxs, bys, dropout_rng)
            losses = np.asarray(losses)  # blocks on the chunk's output
        timer.tick_n(len(chunk))
        if ckpt is not None:
            ckpt.save(int(state.step), state)
        global_step += len(chunk)
        tracer.after_step(global_step)
        return state, [(c[0], float(l)) for c, l in zip(chunk, losses)]

    def emit(records):
        """Per-step CSV rows + boundary evals (reference :83-89 telemetry)."""
        for i, loss_val in records:
            rec_extra = {}
            if i % args.log_interval == 0 and i > 0:  # reference :83-84
                test_loss, test_acc = evaluate(
                    eval_step, state.params, x_test, y_test, args.test_batch_size
                )
                rec_extra = {"test_loss": test_loss, "test_accuracy": test_acc}
            rec = logger.log_step(i, loss_val, **rec_extra)
            if rec_extra:
                print_eval_line(rec)

    try:
        for epoch in range(start_epoch, args.epochs):
            print("Training for epoch {}".format(epoch))
            skip = start_iter if epoch == start_epoch else 0
            pending = []  # buffered (i, bx, by) awaiting a chunk flush
            batch_iter = iterate_batches(
                x_train, y_train, args.batch_size,
                seed=getattr(args, "seed", 0), epoch=epoch, start_iter=skip,
            )
            prefetch_n = int(getattr(args, "prefetch", 2) or 0)
            if not use_scan and prefetch_n > 0:
                # per-step path: keep batches in flight so the H2D copy
                # overlaps the previous step's compute (the chunked path
                # stacks on host, so it stays on numpy batches)
                from distributed_ml_pytorch_tpu.data import prefetch_to_device

                batch_iter = prefetch_to_device(batch_iter, prefetch_n)
            for i, (bx, by) in enumerate(batch_iter, start=skip):
                if not use_scan:
                    state, records = run_one(state, i, bx, by)
                    emit(records)
                    continue
                pending.append((i, bx, by))
                # flush on a full chunk, at an eval boundary (so the eval sees
                # exactly the params after step i, never later ones), or at a
                # checkpoint boundary (orbax accepts saves only at exact
                # multiples of --ckpt-every, so a boundary must be a chunk end)
                at_eval = i % args.log_interval == 0 and i > 0
                at_ckpt = (
                    ckpt is not None
                    and (global_step + len(pending)) % ckpt.save_interval_steps == 0
                )
                if len(pending) >= chunk_k or at_eval or at_ckpt:
                    state, records = run_chunk(state, pending)
                    pending = []
                    emit(records)
            if pending:
                state, records = run_chunk(state, pending)
                pending = []
                emit(records)
            # a window straddling the epoch boundary is truncated here rather
            # than polluting the capture with the full-test-set eval below
            tracer.close()
            evaluate(eval_step, state.params, x_test, y_test, args.test_batch_size, verbose=True)
            line = timer.report("epoch {} train-step time".format(epoch))
            if line:
                print(line)
            timer.reset_stats()
    finally:
        tracer.close()
        tracer.warn_if_never_opened()
        # commit the last completed step even when interrupted mid-epoch —
        # the exact scenario checkpointing exists for. If the interruption
        # landed inside a donating train_step, `state` may reference deleted
        # buffers; never let that mask the original exception.
        if ckpt is not None:
            try:
                ckpt.save(int(state.step), state, force=True)
                ckpt.wait()
            except Exception as e:  # pragma: no cover - interrupt-timing dependent
                print(f"warning: final checkpoint save failed: {e}", file=sys.stderr)
    return state


def setup_checkpoint(args, state: TrainState, steps_per_epoch: int):
    """Build the Checkpointer from CLI flags and fast-forward a resumed run.

    Returns ``(ckpt, state, start_epoch, start_iter)``; ``ckpt`` is ``None``
    when ``--ckpt-dir`` is unset. Shared by the single-process and sync-DP
    trainers (orbax handles replicated/sharded arrays the same way).
    """
    if not getattr(args, "ckpt_dir", None):
        return None, state, 0, 0
    from distributed_ml_pytorch_tpu.utils.checkpoint import (
        Checkpointer,
        maybe_restore,
        resume_position,
    )

    ckpt = Checkpointer(
        args.ckpt_dir,
        max_to_keep=getattr(args, "ckpt_keep", 3),
        save_interval_steps=getattr(args, "ckpt_every", 500),
    )
    start_epoch = start_iter = 0
    if getattr(args, "resume", False):
        state, resume_step = maybe_restore(ckpt, state)
        if resume_step:
            start_epoch, start_iter = resume_position(resume_step, steps_per_epoch)
            print(
                "resumed from step {} → epoch {} iter {}".format(
                    resume_step, start_epoch, start_iter
                )
            )
    return ckpt, state, start_epoch, start_iter


def train_single(args) -> Tuple[TrainState, MetricsLogger]:
    """Single-process baseline training (reference ``make single``/``make gpu``,
    SURVEY.md §3.5). Runs on whatever backend jax selected — the TPU chip by
    default here, CPU under ``--backend=cpu``."""
    from distributed_ml_pytorch_tpu.data import get_dataset
    from distributed_ml_pytorch_tpu.models import get_model

    x_train, y_train, x_test, y_test = get_dataset(args)
    model = get_model(
        getattr(args, "model", "alexnet"),
        dtype=jnp.bfloat16 if getattr(args, "dtype", "float32") == "bfloat16" else jnp.float32,
    )
    steps_per_epoch = max(1, len(x_train) // args.batch_size)
    state, tx = state_from_args(args, model, steps_per_epoch)
    train_step = make_train_step(model, tx)
    scan_step = (
        make_scan_train_step(model, tx)
        if int(getattr(args, "steps_per_dispatch", 1) or 1) > 1
        else None
    )
    eval_step = make_eval_fn(model)
    logger = MetricsLogger(getattr(args, "log_dir", "log"))

    ckpt, state, start_epoch, start_iter = setup_checkpoint(args, state, steps_per_epoch)

    t0 = time.time()
    try:
        state = run_training_loop(
            model=model,
            state=state,
            train_step=train_step,
            eval_step=eval_step,
            data=(x_train, y_train, x_test, y_test),
            args=args,
            logger=logger,
            ckpt=ckpt,
            start_epoch=start_epoch,
            start_iter=start_iter,
            scan_step=scan_step,
        )
    finally:
        if ckpt is not None:
            ckpt.close()
    print("Finished Training ({:.1f}s)".format(time.time() - t0))
    return state, logger
