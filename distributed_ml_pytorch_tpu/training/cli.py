"""C8: CLI + process bootstrap (parity with reference ``example/main.py:140-168``).

Reproduces the reference's 15-flag surface (``example/main.py:142-155``) and
adds the TPU-era flags (``--backend``, ``--model``, ``--mode``, data options).
Flag-mapping notes:

- ``--cuda`` (reference: move model to GPU) → alias for ``--backend=tpu``:
  "put compute on the accelerator". On this hardware that is the TPU chip,
  and it is also the default, so the flag is accepted for script parity.
- ``--rank``/``--world-size``/``--master``/``--port`` configure either the
  async-PS control plane (TCP star, ``utils/messaging.py``) or multi-host
  JAX (``runtime/mesh.py``), replacing MASTER_ADDR/MASTER_PORT + gloo
  (``example/main.py:163-165``).
- ``--server`` turns this process into the parameter server
  (``example/main.py:166-167`` → ``init_server`` parity). Unlike the
  reference — where ``main(args)`` still runs after ``server.run()`` returns,
  a structural quirk (SURVEY.md §3.2) — the server process exits cleanly.
- ``--mode`` selects the parallelism strategy for distributed runs:
  ``ps`` (async parameter server, the reference's core), ``sync``
  (per-step psum allreduce over the device mesh — BASELINE.json's
  ``--backend=tpu`` north-star path), ``local-sgd`` (compiled periodic
  averaging, the idiomatic reformulation of push/pull cadence).
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Distbelief training example (TPU-native)")
    # --- reference 15-flag surface (example/main.py:142-155) ---
    p.add_argument("--batch-size", type=int, default=64, metavar="N",
                   help="input batch size for training (default: 64)")
    p.add_argument("--test-batch-size", type=int, default=10000, metavar="N",
                   help="input batch size for testing (default: 10000)")
    p.add_argument("--epochs", type=int, default=20, metavar="N",
                   help="number of epochs to train (default: 20)")
    p.add_argument("--lr", type=float, default=0.008, metavar="LR",
                   help="learning rate (default: 0.008)")
    p.add_argument("--num-pull", type=int, default=10, metavar="N",
                   help="how often to pull params (default: 10)")
    p.add_argument("--num-push", type=int, default=10, metavar="N",
                   help="how often to push grads (default: 10)")
    p.add_argument("--cuda", action="store_true", default=False,
                   help="use the accelerator (alias for --backend=tpu on this hardware)")
    p.add_argument("--log-interval", type=int, default=100, metavar="N",
                   help="how often to evaluate and print out")
    p.add_argument("--no-distributed", action="store_true", default=False,
                   help="run the single-process baseline instead of distributed training")
    p.add_argument("--rank", type=int, metavar="N",
                   help="rank of current process (0 is server, 1+ is training node)")
    p.add_argument("--world-size", type=int, default=3, metavar="N",
                   help="size of the world")
    p.add_argument("--server", action="store_true", default=False,
                   help="server node?")
    p.add_argument("--n-servers", type=int, default=1, metavar="K",
                   help="(--mode ps) shard the parameter server across K "
                        "ranks (0..K-1), each owning a contiguous range of "
                        "the central vector on its own port (port+shard) — "
                        "the DistBelief layout (parallel/sharded_ps.py)")
    p.add_argument("--master", type=str, default="localhost",
                   help="ip address of the master (server) node")
    p.add_argument("--port", type=str, default="29500",
                   help="port on master node to communicate with")
    # --- TPU-era extensions ---
    p.add_argument("--backend", type=str, default="auto", choices=["auto", "tpu", "cpu"],
                   help="compute backend (auto = jax default platform)")
    p.add_argument("--mode", type=str, default="ps",
                   choices=["ps", "sync", "local-sgd", "fsdp"],
                   help="distributed strategy: async parameter server (reference core), "
                        "sync psum allreduce, compiled local-SGD averaging, or "
                        "fully-sharded data parallel (ZeRO-3: 1/N params per device)")
    p.add_argument("--model", type=str, default="alexnet",
                   choices=["alexnet", "lenet", "resnet18", "resnet50"],
                   help="model architecture (reference hardcodes AlexNet, example/main.py:41)")
    p.add_argument("--dtype", type=str, default="float32", choices=["float32", "bfloat16"],
                   help="compute dtype (bfloat16 feeds the MXU natively)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--data-root", type=str, default="./data",
                   help="CIFAR-10 location (reference downloads here, example/main.py:24)")
    p.add_argument("--synthetic-data", action="store_true", default=False,
                   help="force the deterministic synthetic dataset")
    p.add_argument("--download", action="store_true", default=False,
                   help="fetch real CIFAR-10 (checksum-verified) into "
                        "--data-root when missing; failures fall back to the "
                        "synthetic stand-in (the reference always downloads, "
                        "example/main.py:24 — default-off here so offline "
                        "runs never stall on a dead network)")
    p.add_argument("--synthetic-train-size", type=int, default=50000)
    p.add_argument("--synthetic-test-size", type=int, default=10000)
    p.add_argument("--log-dir", type=str, default="runs",
                   help="worker CSV telemetry directory (default an "
                        "UNTRACKED run directory — the old tracked "
                        "log/node*.csv churn is gone; both log/ and runs/ "
                        "are .gitignored)")
    p.add_argument("--transport", type=str, default="auto",
                   choices=["auto", "native", "python"],
                   help="PS control-plane transport: C++ library "
                        "(native/transport.cpp), pure Python, or auto-detect")
    p.add_argument("--reliable", action="store_true", default=False,
                   help="wrap the PS control plane in the reliability layer "
                        "(per-peer sequence numbers, frame CRC, ack+retry "
                        "with capped backoff, receiver dedup — gradient "
                        "pushes apply exactly once under duplicates/loss); "
                        "set it on EVERY rank of the world")
    p.add_argument("--sync-every", type=int, default=0, metavar="K",
                   help="local-sgd mode: average params every K steps "
                        "(default 0 = use --num-push)")
    p.add_argument("--ckpt-dir", type=str, default="",
                   help="checkpoint directory (empty = checkpointing off; "
                        "reference has no checkpointing at all, SURVEY.md §5.4)")
    p.add_argument("--ckpt-every", type=int, default=500, metavar="N",
                   help="save a checkpoint every N global steps (--mode ps: "
                        "every N gradient pushes, summed across workers)")
    p.add_argument("--ckpt-keep", type=int, default=3, metavar="N",
                   help="retain the newest N checkpoints (ignored by --mode "
                        "ps, which keeps one atomically-replaced file)")
    p.add_argument("--resume", action="store_true", default=False,
                   help="resume from the latest checkpoint in --ckpt-dir")
    p.add_argument("--wal", action="store_true", default=False,
                   help="PS server: write-ahead-log every applied update "
                        "BEFORE its delivery ack (requires --ckpt-dir; "
                        "pair with --reliable — the deferred ack rides the "
                        "reliability envelope); recovery = restore "
                        "checkpoint + replay the log, so no acked "
                        "GradientUpdate can be lost to a crash")
    p.add_argument("--admission", action="store_true", default=False,
                   help="PS server: numerical admission gate (ISSUE 8) — "
                        "every GradientUpdate/ShardPush passes finiteness "
                        "+ per-worker EWMA norm-outlier checks BEFORE "
                        "accounting/WAL; rejects are quarantined and "
                        "explicitly nacked (UpdateNack), the worker "
                        "resyncs by pulling fresh params")
    p.add_argument("--admission-z", type=float, default=6.0, metavar="Z",
                   help="admission gate: reject a push whose log-norm "
                        "z-score vs the sender's own history exceeds Z")
    p.add_argument("--admission-warmup", type=int, default=8, metavar="N",
                   help="admission gate: per-sender pushes admitted before "
                        "the z-score check activates (finiteness is "
                        "checked from the first push)")
    p.add_argument("--manifest-path", type=str, default="",
                   help="elastic shard servers (--coord): path of the "
                        "coordinator's FleetManifest — required to honor "
                        "auto-rollback barriers (RollbackRequest restores "
                        "the last good snapshot in place)")
    p.add_argument("--profile-dir", type=str, default="",
                   help="capture an xprof/TensorBoard trace of a training-step "
                        "window into this directory (reference has no tracing "
                        "at all, SURVEY.md §5.1)")
    p.add_argument("--profile-start", type=int, default=10, metavar="N",
                   help="global step at which the trace window opens")
    p.add_argument("--profile-steps", type=int, default=10, metavar="N",
                   help="number of steps the trace window covers")
    p.add_argument("--metrics-dump", type=str, default="", metavar="PATH",
                   help="write the metrics-registry snapshot JSON "
                        "(utils/metrics.get_registry, ISSUE 12) at exit — "
                        "reliable-transport counters, component stats; "
                        "'-' prints to stdout")
    p.add_argument("--rejoin", action="store_true", default=False,
                   help="PS-mode worker restart: reconnect to a running "
                        "server and ADOPT its central params instead of "
                        "installing this process's fresh init (elastic "
                        "recovery; the reference has none, SURVEY.md §5.3)")
    p.add_argument("--prefetch", type=int, default=2, metavar="N",
                   help="keep N batches' host→device copies in flight ahead "
                        "of compute (per-step path; 0 disables)")
    p.add_argument("--optimizer", type=str, default="sgd",
                   choices=("sgd", "adam", "adamw"),
                   help="optimizer; sgd is the reference recipe "
                        "(example/main.py:44). In --mode ps this is the "
                        "WORKER-local optimizer: pushes carry the local "
                        "param deltas and the server still just adds them "
                        "(the DownPour generalization)")
    p.add_argument("--momentum", type=float, default=0.0, metavar="M",
                   help="sgd momentum (the reference hardcodes 0.0)")
    p.add_argument("--weight-decay", type=float, default=None, metavar="WD",
                   help="weight decay: decoupled (AdamW-style) for adamw, "
                        "classic L2 for sgd/adam; unset keeps each "
                        "optimizer's default (adamw: optax's 1e-4), 0 disables")
    p.add_argument("--grad-clip", type=float, default=0.0, metavar="NORM",
                   help="clip gradients to this global norm before the "
                        "optimizer update; 0 disables")
    p.add_argument("--lr-schedule", type=str, default="constant",
                   choices=("constant", "inverse-epoch", "cosine"),
                   help="learning-rate schedule; the reference configures "
                        "1/(epoch+1) decay but never steps it (SURVEY.md "
                        "§5.6) — 'inverse-epoch' is that intent done right")
    p.add_argument("--grad-accum", type=int, default=1, metavar="K",
                   help="average gradients over K micro-batches before each "
                        "optimizer update (optax.MultiSteps) — effective "
                        "batch K×batch-size without K× activation HBM")
    p.add_argument("--steps-per-dispatch", type=int, default=1, metavar="K",
                   help="fuse up to K consecutive SGD steps into one "
                        "compiled program (lax.scan) — amortizes host "
                        "dispatch; per-step CSV logging and eval cadence "
                        "are preserved. In --mode ps, K caps the fused "
                        "between-comm runs (default auto = 64) and K > 1 "
                        "forces chunked dispatch on; in --mode local-sgd, "
                        "K steps round up to whole sync rounds per dispatch")
    p.add_argument("--chunked-dispatch", choices=("auto", "on", "off"),
                   default="auto",
                   help="(--mode ps workers) compile each between-comm run "
                        "of local SGD into one lax.scan dispatch with exact "
                        "push/pull cadence semantics; 'auto' enables it on "
                        "TPU, where per-batch dispatch — not the DownPour "
                        "protocol — bounds worker throughput")
    p.add_argument("--heartbeat-interval", type=float, default=1.0, metavar="SEC",
                   help="PS-mode worker liveness heartbeat cadence; 0 disables "
                        "(the reference has no failure detection, SURVEY.md §5.3)")
    p.add_argument("--worker-timeout", type=float, default=30.0, metavar="SEC",
                   help="PS-mode server declares a worker failed after this "
                        "long without a frame, instead of waiting forever; "
                        "0 disables")
    p.add_argument("--coord", type=str, default="", metavar="HOST:PORT",
                   help="attach this PS-mode rank to an elastic control "
                        "plane (coord/cli.py): membership + lease liveness, "
                        "coordinator-pushed shard maps (workers cut over at "
                        "step boundaries; shard servers resize), straggler "
                        "speculation. Empty = static fleet (the classic "
                        "launch-time topology)")
    p.add_argument("--staleness-damping", type=float, default=0.0, metavar="D",
                   help="PS-mode server scales each gradient push by "
                        "1/(1 + D*staleness), where staleness counts central "
                        "versions since that worker's last pull (straggler "
                        "mitigation, arxiv 2006.02924); 0 = reference "
                        "behavior (apply raw)")
    # --- scalable optimizer plane (ISSUE 14) ----------------------------
    p.add_argument("--compress", type=str, default="none",
                   choices=("none", "int8", "topk"),
                   help="PS-mode gradient wire compression "
                        "(utils/compress.py): pushes ride CompressedUpdate "
                        "frames with per-worker error-feedback residuals — "
                        "int8 = per-block symmetric quantization (~4x fewer "
                        "bytes), topk = sparsified (idx, value) pairs; the "
                        "server decodes BEFORE the admission gate and WAL")
    p.add_argument("--compress-block", type=int, default=1024, metavar="B",
                   help="int8 quantization block size (one absmax scale per "
                        "block; multiple of 4)")
    p.add_argument("--compress-topk", type=float, default=0.01, metavar="F",
                   help="top-k fraction of elements kept per push "
                        "(--compress topk)")
    p.add_argument("--combine", type=str, default="add",
                   choices=("add", "adasum"),
                   help="how the PS combines concurrent pushes: add = the "
                        "reference behavior; adasum = angle-aware merge "
                        "against the overlap applied since the pusher's "
                        "last pull (arXiv:2006.02924) — the alternative to "
                        "--staleness-damping (mutually exclusive)")
    p.add_argument("--server-opt", type=str, default="none",
                   choices=("none", "sgdm", "adam"),
                   help="ZeRO-style sharded server-side optimizer "
                        "(parallel/optplane.py): each server/shard owns "
                        "momentum (sgdm) or Adam moments for EXACTLY its "
                        "range — state cost scales 1/shards; state rides "
                        "checkpoints + WAL replay (arXiv:2004.13336)")
    p.add_argument("--server-lr", type=float, default=1.0, metavar="LR",
                   help="server-side optimizer step scale (1.0 with sgdm "
                        "momentum 0 reproduces the plain add)")
    p.add_argument("--server-momentum", type=float, default=0.9, metavar="M",
                   help="server-side sgdm momentum over incoming deltas")
    return p


def _apply_backend(args) -> None:
    if args.cuda and args.backend == "auto":
        args.backend = "tpu"
    if args.backend == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from distributed_ml_pytorch_tpu.runtime.mesh import force_cpu_devices

        force_cpu_devices(int(os.environ.get("DMT_CPU_DEVICES", "1")))


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return _main(args)
    finally:
        # observability plane (ISSUE 12): whatever the run registered or
        # attached (reliable-transport counters via make_transport, any
        # component providers) is dumped in one JSON snapshot
        if getattr(args, "metrics_dump", ""):
            from distributed_ml_pytorch_tpu.coord.cli import dump_metrics

            dump_metrics(args.metrics_dump)


def _main(args) -> int:
    print(args)
    _apply_backend(args)

    import jax

    if args.resume and not args.ckpt_dir:
        print("error: --resume requires --ckpt-dir", file=sys.stderr)
        return 2

    if args.no_distributed:
        # reference `make single` / `make gpu` path (SURVEY.md §3.5)
        from distributed_ml_pytorch_tpu.training.trainer import train_single

        _announce_dataset(args)
        _state, logger = train_single(args)
        name = "single.csv" if jax.devices()[0].platform == "cpu" else "tpu.csv"
        path = logger.to_csv(name)
        print("wrote", path)
        print("Finished Training")
        return 0

    # Every advertised knob works in every mode (VERDICT r3 #1):
    # - ps workers build their local optax transform from the full surface
    #   (optimizer/momentum/weight-decay/grad-clip/lr-schedule/grad-accum,
    #   parallel/async_ps.py train_worker; --steps-per-dispatch caps the
    #   fused chunk length), and --profile-dir traces a worker-step window;
    # - local-sgd wires the same transform plus checkpoint/resume at round
    #   boundaries, profiling, and --steps-per-dispatch round fusion.

    if args.mode == "ps" and args.worker_timeout > 0:
        hb = args.heartbeat_interval
        if hb <= 0 or hb * 3 > args.worker_timeout:
            # without fast heartbeats, "silent" and "dead" are
            # indistinguishable: sparse push/pull cadence or a long jit
            # compile would falsely fail a healthy worker
            print(
                "warning: --worker-timeout {:.0f}s needs heartbeats well "
                "under it (got --heartbeat-interval {}); healthy-but-quiet "
                "workers may be declared failed".format(args.worker_timeout, hb),
                file=sys.stderr,
            )

    if args.mode == "ps":
        # only the module imports sit in the try: a run-time ImportError
        # from inside training must surface, not masquerade as a build issue
        try:
            if getattr(args, "n_servers", 1) > 1 or getattr(args, "coord", ""):
                # the sharded entry also hosts the elastic (--coord) path:
                # k=1 is just a one-entry shard map there
                from distributed_ml_pytorch_tpu.parallel.sharded_ps import (
                    run_sharded_ps_process as ps_entry,
                )
            else:
                from distributed_ml_pytorch_tpu.parallel.async_ps import (
                    run_ps_process as ps_entry,
                )
        except ImportError as e:
            print(f"error: --mode ps is unavailable in this build: {e}", file=sys.stderr)
            return 2
        return ps_entry(args)
    else:
        # mesh-based modes share one epilogue; each trainer returns
        # (state, MetricsLogger)
        if args.mode == "sync":
            from distributed_ml_pytorch_tpu.parallel.sync import train_sync as train_fn
        elif args.mode == "fsdp":
            from distributed_ml_pytorch_tpu.parallel.fsdp import train_fsdp as train_fn
        else:
            from distributed_ml_pytorch_tpu.parallel.local_sgd import (
                train_local_sgd as train_fn,
            )

        _announce_dataset(args)
        _state, logger = train_fn(args)
        path = logger.to_csv("node{}.csv".format(jax.process_index()))
        print("wrote", path)
        print("Finished Training")
        return 0


def _announce_dataset(args) -> None:
    from distributed_ml_pytorch_tpu.data.cifar10 import _load_pickle_batches

    real = (not args.synthetic_data) and _load_pickle_batches(args.data_root) is not None
    print("dataset: {} CIFAR-10".format("real" if real else "synthetic"))


if __name__ == "__main__":
    sys.exit(main())
