from distributed_ml_pytorch_tpu.training.trainer import (
    TrainState,
    create_train_state,
    make_train_step,
    make_eval_fn,
    evaluate,
    train_single,
)

__all__ = [
    "TrainState",
    "create_train_state",
    "make_train_step",
    "make_eval_fn",
    "evaluate",
    "train_single",
]
