"""Runtime witnesses — the dynamic half of DC202 (lock order) and of
DC503's fallible exemptions (bounded state; ``BoundedStateWitness``).

The static lock graph (``analysis/concurrency.py``) is an over-
approximation built from lexical nesting; this witness observes the REAL
acquisition orders of a running scenario and cross-validates:

- every lock the witness sees created inside the package must map to a
  statically known ``threading.Lock()/RLock()`` creation site
  (``collect_lock_sites``) — if not, the static model has a hole;
- the observed acquisition-order graph must be acyclic — a runtime cycle
  is a latent deadlock even if no run has hung yet.

Install by patching the ``threading.Lock``/``RLock`` factories, so every
lock constructed AFTER install (transports, frontends, coord clients —
they all create their locks in ``__init__``) is wrapped. The wrapper keys
each lock by its creation site (file:line), so all instances born at one
source line are one node — exactly the granularity of the static graph.

Enabled in the determinism suites via the ``DISTCHECK_WITNESS`` env flag
(:func:`maybe_install`): the chaos/coord acceptance scenarios then double
as concurrency validators at zero cost to the default test run.

The witness itself synchronizes with raw ``_thread.allocate_lock()``
primitives so its own bookkeeping never enters the graph.
"""

from __future__ import annotations

import os
import sys
import threading
import _thread
from typing import Dict, List, Optional, Set, Tuple

Site = Tuple[str, int]  # (filename, lineno) of the lock's creation


class _WitnessLock:
    """Drop-in for a ``threading.Lock``/``RLock``, reporting to a witness."""

    __slots__ = ("_inner", "site", "_witness")

    def __init__(self, inner, site: Site, witness: "LockOrderWitness"):
        self._inner = inner
        self.site = site
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness._note_acquire(self)
        return ok

    def release(self) -> None:
        self._witness._note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def _at_fork_reinit(self) -> None:  # stdlib calls this after fork
        reinit = getattr(self._inner, "_at_fork_reinit", None)
        if reinit is not None:
            reinit()


class LockOrderWitness:
    """Observe lock creation sites and acquisition-order edges."""

    def __init__(self, package_root: Optional[str] = None):
        self._mu = _thread.allocate_lock()
        self._tls = threading.local()
        self._edges: Dict[Tuple[Site, Site], int] = {}  # edge -> count
        self._sites: Set[Site] = set()
        self._orig_lock = None
        self._orig_rlock = None
        self._enabled = False
        if package_root is None:
            package_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
        self.package_root = package_root

    # ------------------------------------------------------------- install
    def install(self) -> "LockOrderWitness":
        if self._enabled:
            return self
        self._orig_lock = threading.Lock
        self._orig_rlock = threading.RLock
        witness = self

        def make_lock():
            site = witness._creation_site()
            inner = witness._orig_lock()
            witness._register(site)
            return _WitnessLock(inner, site, witness)

        def make_rlock():
            site = witness._creation_site()
            inner = witness._orig_rlock()
            witness._register(site)
            return _WitnessLock(inner, site, witness)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        self._enabled = True
        return self

    def uninstall(self) -> None:
        if not self._enabled:
            return
        threading.Lock = self._orig_lock
        threading.RLock = self._orig_rlock
        self._enabled = False  # existing wrapped locks keep working silently

    def _creation_site(self) -> Site:
        frame = sys._getframe(2)  # caller of threading.Lock()
        return (frame.f_code.co_filename, frame.f_lineno)

    def _register(self, site: Site) -> None:
        with self._mu:
            self._sites.add(site)

    # ----------------------------------------------------------- recording
    def _stack(self) -> List["_WitnessLock"]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _note_acquire(self, lock: _WitnessLock) -> None:
        if not self._enabled:
            return
        stack = self._stack()
        reentrant = any(held is lock for held in stack)
        if not reentrant:
            new_edges = [
                (held.site, lock.site) for held in stack
                if held.site != lock.site]
            if new_edges:
                with self._mu:
                    for edge in new_edges:
                        self._edges[edge] = self._edges.get(edge, 0) + 1
        stack.append(lock)

    def _note_release(self, lock: _WitnessLock) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return

    # ------------------------------------------------------------ analysis
    def edges(self) -> Dict[Tuple[Site, Site], int]:
        with self._mu:
            return dict(self._edges)

    def sites(self) -> Set[Site]:
        with self._mu:
            return set(self._sites)

    def package_sites(self) -> Set[Site]:
        return {s for s in self.sites() if s[0].startswith(self.package_root)}

    def cycles(self) -> List[List[Site]]:
        """Every elementary cycle in the observed order graph (DFS; the
        graphs here are tiny)."""
        graph: Dict[Site, Set[Site]] = {}
        for (a, b) in self.edges():
            graph.setdefault(a, set()).add(b)
        cycles: List[List[Site]] = []
        seen_cycles: Set[Tuple[Site, ...]] = set()

        def dfs(start: Site, node: Site, path: List[Site]):
            for nxt in graph.get(node, ()):
                if nxt == start:
                    canon = tuple(sorted(path))
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        cycles.append(path + [start])
                elif nxt not in path:
                    dfs(start, nxt, path + [nxt])

        for start in graph:
            dfs(start, start, [start])
        return cycles

    def report(self) -> str:
        lines = ["lock-order witness:"]
        for (a, b), n in sorted(self.edges().items()):
            lines.append(
                f"  {a[0]}:{a[1]} -> {b[0]}:{b[1]}  ({n} acquisitions)")
        for cycle in self.cycles():
            lines.append("  CYCLE: " + " -> ".join(
                f"{s[0]}:{s[1]}" for s in cycle))
        return "\n".join(lines)


def maybe_install(package_root: Optional[str] = None) -> Optional[LockOrderWitness]:
    """Install a witness iff ``DISTCHECK_WITNESS`` is set (how the chaos /
    coord determinism suites opt in without taxing the default run)."""
    if not os.environ.get("DISTCHECK_WITNESS"):
        return None
    return LockOrderWitness(package_root).install()


# ----------------------------------------------------- bounded-state witness

class BoundedStateWitness:
    """Runtime half of DC503's *fallible* exemptions.

    The static pass clears a growing container when it sees prune/upsert/
    memo evidence — but "there is a ``pop`` in the class" does not prove
    the pop ever RUNS. This witness watches real containers and fails a
    scenario whose watched container grew monotonically past its budget:
    exactly the case where the static exemption was wrong.

    Sampling is read-only (``len``) and happens between scenario rounds /
    at teardown, never inside the traffic path — so the chaos suites'
    byte-identical log guarantees are untouched.
    """

    def __init__(self, budget: int = 4096):
        self.budget = int(budget)
        self._watched: List[Tuple[str, object, int]] = []
        self.series: Dict[str, List[int]] = {}

    def watch(self, name: str, container: object,
              budget: Optional[int] = None) -> None:
        self._watched.append(
            (name, container, self.budget if budget is None else int(budget)))
        self.series.setdefault(name, [])

    def sample(self) -> None:
        for name, container, _ in self._watched:
            try:
                self.series[name].append(len(container))  # type: ignore[arg-type]
            except TypeError:
                pass  # not sized (witness config error) — nothing to say

    def violations(self) -> List[str]:
        """Watched containers whose sampled sizes only ever went up AND
        ended past budget — growth with a plateau or a dip is a working
        prune; growth that never once receded is the leak."""
        budgets = {name: b for name, _, b in self._watched}
        out = []
        for name, sizes in sorted(self.series.items()):
            if len(sizes) < 2 or sizes[-1] <= budgets.get(name, self.budget):
                continue
            if sizes[-1] > sizes[0] and \
                    all(b >= a for a, b in zip(sizes, sizes[1:])):
                out.append(
                    f"{name}: grew {sizes[0]} -> {sizes[-1]} monotonically "
                    f"over {len(sizes)} samples (budget "
                    f"{budgets.get(name, self.budget)}) — the static DC503 "
                    "exemption did not hold at runtime")
        return out


_EXEMPT_INDEX: Optional[Dict[Tuple[str, str], Set[str]]] = None


def _exempt_index() -> Dict[Tuple[str, str], Set[str]]:
    """(module, class) -> exempt attrs, from the static pass — memoized:
    one package parse per process, only ever under DISTCHECK_WITNESS."""
    global _EXEMPT_INDEX
    if _EXEMPT_INDEX is None:
        from distributed_ml_pytorch_tpu.analysis import cli, distflow
        from distributed_ml_pytorch_tpu.analysis.core import load_package
        idx: Dict[Tuple[str, str], Set[str]] = {}
        for e in distflow.bounded_exemptions(load_package(cli.default_root())):
            mod = "distributed_ml_pytorch_tpu." + \
                e.path[:-len(".py")].replace("/", ".").split(
                    "distributed_ml_pytorch_tpu.", 1)[-1]
            idx.setdefault((mod, e.cls), set()).add(e.attr)
        _EXEMPT_INDEX = idx
    return _EXEMPT_INDEX


def scan_exempt_sizes() -> List[Tuple[str, str, int]]:
    """One gc pass: the current size of every DC503-exempt container on a
    live package instance — ``(class, attr, len)`` rows."""
    import gc

    idx = _exempt_index()
    out: List[Tuple[str, str, int]] = []
    for obj in gc.get_objects():
        t = type(obj)
        attrs = idx.get((getattr(t, "__module__", ""), t.__name__))
        if not attrs:
            continue
        for attr in attrs:
            container = getattr(obj, attr, None)
            try:
                out.append((t.__name__, attr, len(container)))  # type: ignore[arg-type]
            except TypeError:
                pass
    return out


def check_exempt_budget(budget: int = 4096) -> List[Tuple[str, str, int]]:
    """Teardown gate for the acceptance scenarios: any statically-exempt
    container still holding more than ``budget`` entries when the scenario
    is over means its prune/memo story didn't hold — fail loudly."""
    return [row for row in scan_exempt_sizes() if row[2] > budget]
