"""Runtime lock-order witness — the dynamic half of DC202.

The static lock graph (``analysis/concurrency.py``) is an over-
approximation built from lexical nesting; this witness observes the REAL
acquisition orders of a running scenario and cross-validates:

- every lock the witness sees created inside the package must map to a
  statically known ``threading.Lock()/RLock()`` creation site
  (``collect_lock_sites``) — if not, the static model has a hole;
- the observed acquisition-order graph must be acyclic — a runtime cycle
  is a latent deadlock even if no run has hung yet.

Install by patching the ``threading.Lock``/``RLock`` factories, so every
lock constructed AFTER install (transports, frontends, coord clients —
they all create their locks in ``__init__``) is wrapped. The wrapper keys
each lock by its creation site (file:line), so all instances born at one
source line are one node — exactly the granularity of the static graph.

Enabled in the determinism suites via the ``DISTCHECK_WITNESS`` env flag
(:func:`maybe_install`): the chaos/coord acceptance scenarios then double
as concurrency validators at zero cost to the default test run.

The witness itself synchronizes with raw ``_thread.allocate_lock()``
primitives so its own bookkeeping never enters the graph.
"""

from __future__ import annotations

import os
import sys
import threading
import _thread
from typing import Dict, List, Optional, Set, Tuple

Site = Tuple[str, int]  # (filename, lineno) of the lock's creation


class _WitnessLock:
    """Drop-in for a ``threading.Lock``/``RLock``, reporting to a witness."""

    __slots__ = ("_inner", "site", "_witness")

    def __init__(self, inner, site: Site, witness: "LockOrderWitness"):
        self._inner = inner
        self.site = site
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness._note_acquire(self)
        return ok

    def release(self) -> None:
        self._witness._note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def _at_fork_reinit(self) -> None:  # stdlib calls this after fork
        reinit = getattr(self._inner, "_at_fork_reinit", None)
        if reinit is not None:
            reinit()


class LockOrderWitness:
    """Observe lock creation sites and acquisition-order edges."""

    def __init__(self, package_root: Optional[str] = None):
        self._mu = _thread.allocate_lock()
        self._tls = threading.local()
        self._edges: Dict[Tuple[Site, Site], int] = {}  # edge -> count
        self._sites: Set[Site] = set()
        self._orig_lock = None
        self._orig_rlock = None
        self._enabled = False
        if package_root is None:
            package_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
        self.package_root = package_root

    # ------------------------------------------------------------- install
    def install(self) -> "LockOrderWitness":
        if self._enabled:
            return self
        self._orig_lock = threading.Lock
        self._orig_rlock = threading.RLock
        witness = self

        def make_lock():
            site = witness._creation_site()
            inner = witness._orig_lock()
            witness._register(site)
            return _WitnessLock(inner, site, witness)

        def make_rlock():
            site = witness._creation_site()
            inner = witness._orig_rlock()
            witness._register(site)
            return _WitnessLock(inner, site, witness)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        self._enabled = True
        return self

    def uninstall(self) -> None:
        if not self._enabled:
            return
        threading.Lock = self._orig_lock
        threading.RLock = self._orig_rlock
        self._enabled = False  # existing wrapped locks keep working silently

    def _creation_site(self) -> Site:
        frame = sys._getframe(2)  # caller of threading.Lock()
        return (frame.f_code.co_filename, frame.f_lineno)

    def _register(self, site: Site) -> None:
        with self._mu:
            self._sites.add(site)

    # ----------------------------------------------------------- recording
    def _stack(self) -> List["_WitnessLock"]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _note_acquire(self, lock: _WitnessLock) -> None:
        if not self._enabled:
            return
        stack = self._stack()
        reentrant = any(held is lock for held in stack)
        if not reentrant:
            new_edges = [
                (held.site, lock.site) for held in stack
                if held.site != lock.site]
            if new_edges:
                with self._mu:
                    for edge in new_edges:
                        self._edges[edge] = self._edges.get(edge, 0) + 1
        stack.append(lock)

    def _note_release(self, lock: _WitnessLock) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return

    # ------------------------------------------------------------ analysis
    def edges(self) -> Dict[Tuple[Site, Site], int]:
        with self._mu:
            return dict(self._edges)

    def sites(self) -> Set[Site]:
        with self._mu:
            return set(self._sites)

    def package_sites(self) -> Set[Site]:
        return {s for s in self.sites() if s[0].startswith(self.package_root)}

    def cycles(self) -> List[List[Site]]:
        """Every elementary cycle in the observed order graph (DFS; the
        graphs here are tiny)."""
        graph: Dict[Site, Set[Site]] = {}
        for (a, b) in self.edges():
            graph.setdefault(a, set()).add(b)
        cycles: List[List[Site]] = []
        seen_cycles: Set[Tuple[Site, ...]] = set()

        def dfs(start: Site, node: Site, path: List[Site]):
            for nxt in graph.get(node, ()):
                if nxt == start:
                    canon = tuple(sorted(path))
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        cycles.append(path + [start])
                elif nxt not in path:
                    dfs(start, nxt, path + [nxt])

        for start in graph:
            dfs(start, start, [start])
        return cycles

    def report(self) -> str:
        lines = ["lock-order witness:"]
        for (a, b), n in sorted(self.edges().items()):
            lines.append(
                f"  {a[0]}:{a[1]} -> {b[0]}:{b[1]}  ({n} acquisitions)")
        for cycle in self.cycles():
            lines.append("  CYCLE: " + " -> ".join(
                f"{s[0]}:{s[1]}" for s in cycle))
        return "\n".join(lines)


def maybe_install(package_root: Optional[str] = None) -> Optional[LockOrderWitness]:
    """Install a witness iff ``DISTCHECK_WITNESS`` is set (how the chaos /
    coord determinism suites opt in without taxing the default run)."""
    if not os.environ.get("DISTCHECK_WITNESS"):
        return None
    return LockOrderWitness(package_root).install()
