import sys

from distributed_ml_pytorch_tpu.analysis.cli import main

sys.exit(main())
