"""Timeline analyzer — merges per-member flight-recorder dumps and explains
where the wall clock went (ISSUE 12; the ``analysis`` package's first
RUNTIME-artifact analyzer, next to the static distcheck families).

Input: a directory of ``flight_*.jsonl`` dumps written by
``utils/obs.SpanRecorder.dump_jsonl`` / ``flight_dump`` — one ``kind:
meta`` header line (member, plane, drop accounting) then one span per
line. Producers: MPMD stage members and the driver (``parallel/mpmd.py``),
the coordinator (``coord/coordinator.py``), any ``ReliableTransport`` with
a recorder attached, the PS and serving engines when wired.

Outputs (one dict, ``render()`` for humans, ``--json`` for machines):

- **bubble attribution** — per stage-member fraction of its wall clock in
  each exclusive state (compute / wait-act / wait-grad / wire-blocked /
  ckpt / idle; they sum to ~1 by StateClock construction), plus the
  stage-seconds aggregate whose ``1 - compute`` IS the bench's bubble
  fraction — decomposed instead of a single opaque 0.88.
- **wire attribution** — from each member's final ``wire-stats`` event:
  retransmit share (retries / sent), ack frames per data frame (the ack
  tax's wire cost), credit-block seconds (send() blocked at the window).
- **correlation journeys** — spans stitched on the correlation id that
  rode the reliability envelope: how many units of work crossed members,
  and the longest end-to-end journeys (first-touch -> last-touch).

Robustness contract (regression-tested): torn/partial dump lines are
tolerated and COUNTED (a flight recorder written during a crash may lose
its tail); unknown plane tags are SURFACED, never dropped (a new plane's
dumps must show up as "unknown to this analyzer", not vanish); a missing
``attribution`` summary falls back to summing the member's state spans.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

#: exclusive serve-loop states the analyzer knows how to attribute, per
#: plane tag (``SpanRecorder.plane``). An unfamiliar plane still gets its
#: per-state numbers — it is just listed in ``unknown_planes`` so a new
#: subsystem's dumps are never silently half-read.
KNOWN_PLANES: Dict[str, tuple] = {
    "mpmd": ("compute", "wait-act", "wait-grad", "wire-blocked", "ckpt",
             "idle"),
    "ps": ("apply", "wal", "idle"),
    "serving": ("prefill", "decode", "idle"),
    "wire": ("wire-blocked",),
    "coord": (),
}

#: the states whose summed fraction is "the pipeline is waiting" — the
#: decomposition of the bubble (everything except compute)
MPMD_WAIT_STATES = ("wait-act", "wait-grad", "wire-blocked", "ckpt", "idle")


def load_dump(path: str) -> dict:
    """Parse one JSONL flight dump, tolerating torn lines.

    Returns ``{member, plane, reason, spans, events, torn_lines, meta}``.
    A line that fails to parse (truncated write mid-crash) increments
    ``torn_lines`` and is skipped — a dump is evidence, not a contract.
    A file with no parseable meta header still yields its spans under
    ``member=<filename>`` / ``plane="?"``.
    """
    member = os.path.basename(path)
    plane = "?"
    meta: dict = {}
    spans: List[dict] = []
    events: List[dict] = []
    torn = 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                torn += 1
                continue
            if not isinstance(row, dict):
                torn += 1
                continue
            if row.get("kind") == "meta":
                meta = row
                member = str(row.get("member", member))
                plane = str(row.get("plane", plane))
                continue
            if not {"name", "t0_ns", "t1_ns"} <= set(row):
                torn += 1
                continue
            (events if row.get("state") == "event" else spans).append(row)
    return {
        "path": path, "member": member, "plane": plane,
        "reason": str(meta.get("reason", "")), "meta": meta,
        "spans": spans, "events": events, "torn_lines": torn,
    }


def load_dir(dump_dir: str) -> List[dict]:
    """Every ``*.jsonl`` dump in a directory, sorted by file name."""
    if not os.path.isdir(dump_dir):
        raise FileNotFoundError(f"no such dump directory: {dump_dir}")
    out = []
    for name in sorted(os.listdir(dump_dir)):
        if name.endswith(".jsonl"):
            out.append(load_dump(os.path.join(dump_dir, name)))
    return out


def _member_attribution(dump: dict) -> Optional[dict]:
    """Per-state seconds + fractions for one member dump.

    Prefers the member's own ``attribution`` summary event (the
    StateClock flush: exact, survives ring drops of early spans); falls
    back to summing the retained state spans when none exists (a death
    dump taken before any flush)."""
    attr_events = [e for e in dump["events"] if e["name"] == "attribution"]
    seconds: Dict[str, float] = {}
    wall = 0.0
    if attr_events:
        ev = attr_events[-1]  # the final flush wins
        m = ev.get("meta") or {}
        wall = float(m.get("wall_s", 0.0))
        seconds = {k: float(v) for k, v in m.items()
                   if k != "wall_s" and isinstance(v, (int, float))}
    elif dump["spans"]:
        t0 = min(s["t0_ns"] for s in dump["spans"])
        t1 = max(s["t1_ns"] for s in dump["spans"])
        wall = max(0.0, (t1 - t0) / 1e9)
        for s in dump["spans"]:
            state = str(s.get("state", s["name"]))
            seconds[state] = seconds.get(state, 0.0) \
                + max(0, s["t1_ns"] - s["t0_ns"]) / 1e9
    if wall <= 0.0:
        return None
    known = KNOWN_PLANES.get(dump["plane"], ())
    fractions = {k: v / wall for k, v in seconds.items()}
    return {
        "member": dump["member"],
        "plane": dump["plane"],
        "reason": dump["reason"],
        "wall_s": round(wall, 6),
        "seconds": {k: round(v, 6) for k, v in sorted(seconds.items())},
        "fractions": {k: round(v, 6) for k, v in sorted(fractions.items())},
        #: how much of the wall the named states explain — the acceptance
        #: bar is >= 0.95 per stage on a bench run
        "accounted": round(sum(fractions.values()), 6),
        "unknown_states": sorted(k for k in seconds if known
                                 and k not in known),
    }


def _wire_attribution(dumps: List[dict]) -> dict:
    """Aggregate the members' final ``wire-stats`` events into the wire's
    share of the story: retransmit share, ack frames per data frame, and
    credit-block seconds."""
    totals: Dict[str, float] = {}
    members = 0
    for d in dumps:
        stats_events = [e for e in d["events"] if e["name"] == "wire-stats"]
        if not stats_events:
            continue
        members += 1
        m = stats_events[-1].get("meta") or {}  # teardown emission wins
        for k, v in m.items():
            if isinstance(v, (int, float)):
                totals[k] = totals.get(k, 0.0) + float(v)
    sent = totals.get("sent", 0.0)
    acked = totals.get("acked", 0.0)
    out = {
        "members_reporting": members,
        "sent": int(sent),
        "retries": int(totals.get("retries", 0)),
        "retransmit_share": round(totals.get("retries", 0.0) / sent, 6)
        if sent else 0.0,
        "ack_frames": int(totals.get("acks_tx", 0)
                          + totals.get("cum_acks_tx", 0)),
        "acks_per_data_frame": round(
            (totals.get("acks_tx", 0.0) + totals.get("cum_acks_tx", 0.0))
            / acked, 6) if acked else 0.0,
        "credit_block_s": round(totals.get("window_blocked_s", 0.0), 6),
        "window_blocked_events": int(totals.get("window_blocked", 0)),
        "breaker_opens": int(totals.get("breaker_opens", 0)),
        "crc_dropped": int(totals.get("crc_dropped", 0)),
        "dup_dropped": int(totals.get("dup_dropped", 0)),
    }
    return out


def _journeys(dumps: List[dict], top_n: int = 5) -> dict:
    """Stitch spans/events on correlation ids across members."""
    by_corr: Dict[int, List[tuple]] = {}
    for d in dumps:
        for s in d["spans"] + d["events"]:
            corr = int(s.get("corr", 0))
            if corr:
                by_corr.setdefault(corr, []).append(
                    (d["member"], s["t0_ns"], s["t1_ns"], s["name"]))
    cross = {c: rows for c, rows in by_corr.items()
             if len({m for m, *_ in rows}) > 1}
    longest = sorted(
        ((max(r[2] for r in rows) - min(r[1] for r in rows)) / 1e9, c)
        for c, rows in cross.items())[-top_n:]
    return {
        "correlated_units": len(by_corr),
        "cross_member_units": len(cross),
        "longest": [
            {"corr": c, "duration_s": round(dur, 6),
             "members": sorted({m for m, *_ in cross[c]}),
             "hops": len(cross[c])}
            for dur, c in reversed(longest)
        ],
    }


def analyze(dump_dir: str) -> dict:
    """The whole report over one dump directory (see module docstring)."""
    dumps = load_dir(dump_dir)
    members = []
    unknown_planes = sorted({d["plane"] for d in dumps
                             if d["plane"] not in KNOWN_PLANES})
    torn = sum(d["torn_lines"] for d in dumps)
    dropped = sum(int(d["meta"].get("dropped", 0)) for d in dumps)
    for d in dumps:
        attr = _member_attribution(d)
        if attr is not None:
            members.append(attr)

    # stage-seconds aggregate over the pipeline members: the bench's
    # bubble fraction, decomposed
    stages = [m for m in members if m["plane"] == "mpmd"
              and m["member"].startswith("stage")]
    bubble = None
    if stages:
        wall = sum(m["wall_s"] for m in stages)
        agg: Dict[str, float] = {}
        for m in stages:
            for k, v in m["seconds"].items():
                agg[k] = agg.get(k, 0.0) + v
        fractions = {k: round(v / wall, 6) for k, v in sorted(agg.items())}
        bubble = {
            "stages": len(stages),
            "stage_seconds": round(wall, 6),
            "fractions": fractions,
            "bubble_fraction": round(
                1.0 - fractions.get("compute", 0.0), 6),
            "wait_fraction": round(
                sum(fractions.get(k, 0.0) for k in MPMD_WAIT_STATES), 6),
        }

    return {
        "dump_dir": dump_dir,
        "n_dumps": len(dumps),
        "torn_lines": torn,
        "ring_dropped_spans": dropped,
        "unknown_planes": unknown_planes,
        "members": members,
        "bubble_attribution": bubble,
        "wire_attribution": _wire_attribution(dumps),
        "journeys": _journeys(dumps),
    }


def render(report: dict) -> str:
    """Human-readable rendering of :func:`analyze`'s report."""
    lines = [
        f"timeline: {report['n_dumps']} dump(s) in {report['dump_dir']}"
        + (f", {report['torn_lines']} torn line(s) tolerated"
           if report["torn_lines"] else "")
        + (f", {report['ring_dropped_spans']} span(s) aged out of rings"
           if report["ring_dropped_spans"] else ""),
    ]
    if report["unknown_planes"]:
        lines.append(
            "  WARNING: unknown plane tag(s) "
            f"{report['unknown_planes']} — attributed generically, "
            "teach analysis/timeline.KNOWN_PLANES about them")
    for m in report["members"]:
        fr = ", ".join(f"{k} {v:.1%}" for k, v in m["fractions"].items())
        lines.append(
            f"  {m['member']} [{m['plane']}] wall {m['wall_s']:.3f}s "
            f"(accounted {m['accounted']:.1%}): {fr}")
        if m["unknown_states"]:
            lines.append(
                f"    unknown state(s) for this plane: "
                f"{m['unknown_states']}")
    b = report["bubble_attribution"]
    if b:
        fr = ", ".join(f"{k} {v:.1%}" for k, v in b["fractions"].items())
        lines.append(
            f"  bubble: {b['bubble_fraction']:.1%} of "
            f"{b['stages']}-stage seconds not compute — {fr}")
    w = report["wire_attribution"]
    if w["members_reporting"]:
        lines.append(
            f"  wire: retransmit share {w['retransmit_share']:.2%}, "
            f"{w['acks_per_data_frame']:.2f} ack frames/data frame, "
            f"credit-block {w['credit_block_s']:.3f}s, "
            f"{w['breaker_opens']} breaker open(s)")
    j = report["journeys"]
    lines.append(
        f"  correlation: {j['correlated_units']} unit(s), "
        f"{j['cross_member_units']} crossed members")
    for leg in j["longest"]:
        lines.append(
            f"    corr {leg['corr']}: {leg['duration_s']:.3f}s over "
            f"{len(leg['members'])} member(s) {leg['members']} "
            f"({leg['hops']} span/event(s))")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="distcheck timeline",
        description="merge flight-recorder dumps; attribute the bubble "
                    "and the wire (ISSUE 12)")
    parser.add_argument("dump_dir", help="directory of flight_*.jsonl "
                                         "dumps (e.g. <run>/obs)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    args = parser.parse_args(argv)
    report = analyze(args.dump_dir)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render(report))
    return 0 if report["n_dumps"] else 1
