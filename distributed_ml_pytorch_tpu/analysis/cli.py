"""distcheck CLI — ``python -m distributed_ml_pytorch_tpu.analysis``.

Runs the four checker families over a package tree, applies inline
suppressions and the checked-in baseline, and exits non-zero when any
unsuppressed, non-baselined finding remains — the ``make lint`` contract.

    python -m distributed_ml_pytorch_tpu.analysis                 # the package
    python -m distributed_ml_pytorch_tpu.analysis --baseline tests/distcheck_baseline.txt
    python -m distributed_ml_pytorch_tpu.analysis --keys          # baseline keys (regen script)
    python -m distributed_ml_pytorch_tpu.analysis --json          # machine-readable findings
    python -m distributed_ml_pytorch_tpu.analysis path/to/pkg     # any tree (fixtures)

The ``timeline`` subcommand (ISSUE 12) is the package's first RUNTIME
analyzer: it merges flight-recorder dumps and attributes the bubble and
the wire (``analysis/timeline.py``; ``make timeline``):

    python -m distributed_ml_pytorch_tpu.analysis timeline <dump-dir> [--json]

The ``distmodel`` subcommand (ISSUE 13) model-checks the extracted
protocol: bounded exhaustive exploration of the exactly-once / lease /
watermark-replay invariants, with every counterexample emitted as a
replayable chaos schedule (``analysis/distmodel.py``; ``make distmodel``):

    python -m distributed_ml_pytorch_tpu.analysis distmodel [--json] [--mutate NAME] [--out DIR]
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Tuple

from distributed_ml_pytorch_tpu.analysis import (
    concurrency,
    distflow,
    protomodel,
    tracing_hygiene,
    wire,
)
from distributed_ml_pytorch_tpu.analysis.core import (
    Finding,
    Package,
    apply_suppressions,
    baseline_keys,
    load_package,
    read_baseline,
)

CHECKERS = (wire.check, protomodel.check, concurrency.check,
            tracing_hygiene.check, distflow.check)


def analyze(pkg: Package) -> Tuple[List[Finding], List[Finding]]:
    """(active, suppressed) findings for one loaded package."""
    findings: List[Finding] = []
    for checker in CHECKERS:
        findings.extend(checker(pkg))
    return apply_suppressions(pkg, findings)


def analyze_path(root: str, rel_base: Optional[str] = None):
    return analyze(load_package(root, rel_base=rel_base))


def default_root() -> str:
    import distributed_ml_pytorch_tpu

    return os.path.dirname(os.path.abspath(distributed_ml_pytorch_tpu.__file__))


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "timeline":
        # runtime analyzer (ISSUE 12): its own arg surface, no package scan
        from distributed_ml_pytorch_tpu.analysis import timeline

        return timeline.main(argv[1:])
    if argv and argv[0] == "distmodel":
        # bounded model checker (ISSUE 13): its own arg surface
        from distributed_ml_pytorch_tpu.analysis import distmodel

        return distmodel.main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="distcheck",
        description="protocol / concurrency / tracing-hygiene static "
                    "analysis for the distributed_ml_pytorch_tpu stack")
    parser.add_argument(
        "root", nargs="?", default=None,
        help="package directory to analyze (default: the installed "
             "distributed_ml_pytorch_tpu package)")
    parser.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="known-findings file; only NEW findings fail the run "
             "(tests/distcheck_baseline.txt in CI)")
    parser.add_argument(
        "--keys", action="store_true",
        help="print baseline keys instead of rendered findings "
             "(consumed by tests/regen_distcheck_baseline.py)")
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also list findings silenced by inline suppressions")
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable findings on stdout (CI / bench_all "
             "consume lint results without scraping text)")
    args = parser.parse_args(argv)

    root = args.root or default_root()
    active, suppressed = analyze_path(root)
    baseline = read_baseline(args.baseline) if args.baseline else frozenset()
    keys = baseline_keys(active)
    new = [f for f, k in zip(active, keys) if k not in baseline]
    known = [f for f, k in zip(active, keys) if k in baseline]

    if args.keys:
        for key in keys:
            print(key)
        return 0
    if args.json:
        import json as _json

        def row(f, key, baselined):
            return {"path": f.path, "line": f.line, "code": f.code,
                    "message": f.message, "baseline_key": key,
                    "baselined": baselined}

        payload = {
            "clean": not new,
            "counts": {"new": len(new), "baselined": len(known),
                       "suppressed": len(suppressed)},
            "findings": [row(f, k, k in baseline)
                         for f, k in zip(active, keys)],
        }
        if args.show_suppressed:
            payload["suppressed"] = [
                {"path": f.path, "line": f.line, "code": f.code,
                 "message": f.message} for f in suppressed]
        _json.dump(payload, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
        return 0 if not new else 1

    for f in new:
        print(f.render())
    if known:
        print(f"# {len(known)} known finding(s) carried by the baseline "
              f"({args.baseline})", file=sys.stderr)
    if args.show_suppressed and suppressed:
        print(f"# {len(suppressed)} suppressed finding(s):", file=sys.stderr)
        for f in suppressed:
            print("#   " + f.render(), file=sys.stderr)
    if new:
        print(f"distcheck: {len(new)} finding(s)", file=sys.stderr)
        return 1
    print(f"distcheck: clean ({len(suppressed)} suppressed"
          + (f", {len(known)} baselined" if known else "") + ")",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
