"""Bounded explicit-state model checking of the wire protocol (ISSUE 13).

``analysis/protomodel.py`` extracts WHAT the protocol promises (dedup
keys, log-before-ack durability, incarnation-ordered leases, watermark
replay); this module checks that those rules actually COMPOSE into the
invariants the seeded acceptance scenarios only sample:

- **ps** — the DownPour commit protocol: workers push ``GradientUpdate``
  frames over the reliability envelope toward a WAL'd server that applies
  under env-seq dedup, group-fsyncs, and releases delivery acks after the
  covering sync. Invariants: *exactly-once apply* (no update's delta lands
  twice in any reachable state), *acked => applied* across crash/restore
  (equivalently: no lost ack after the crash truncates the un-fsynced WAL
  tail).
- **lease** — the coordination plane: lives of one rank join / renew /
  leave with incarnation stamps, frames arbitrarily delayed, duplicated
  and reordered. Invariants: *lease monotonicity across lives* (the
  admitted incarnation never goes backward) and *no stale-life eviction*
  (an old life's wandering ``CoordLeave`` cannot evict a newer live
  member).
- **mpmd** — the pipeline hand-off: a stage ships ``(step, microbatch)``
  activations to a successor that dedups by ``(step, mb)``, checkpoints at
  step-boundary watermarks, dies, restarts, and is healed by the
  neighbor's watermark-bounded replay. Invariants: *no microbatch applied
  twice* and *watermark replay fills every hole* (a quiescent pipeline has
  no gap below its frontier).
- **copt** — the compressed optimizer-plane push path (ISSUE 14): lossy
  quantized pushes with per-worker error feedback, silent corruption of
  in-flight compressed frames, a server that admission-gates on the
  DECODED norm. Invariants: *quiescent error bound* (quantization error
  is deferred via the residual, never compounded) and *no poison
  applied* (a decoded outlier never reaches the applied sum).
- **dpull** — the delta-encoded pull-reply plane (ISSUE 18): a server
  tracking each worker's last-shipped view answers pulls with top-k
  deltas against that base or a full fallback, replies get lost or
  delayed across a crash-restore that re-fills the same version numbers
  with different bytes. Invariant: *stamp-authenticated view* (a worker
  whose held stamp matches the server's current ``(epoch, ver)`` holds
  exactly the central bytes).
- **coordfail** — the control plane's own failure protocol (ISSUE 17):
  coordinator crash/partition mid-epoch with one preemption in flight, a
  successor restoring from ckpt+WAL, delayed zombie control frames, a
  blipped member rejoining. Invariants: *map authority monotonic across
  coordinator lives* (a stale-epoch command never actuates), *no member
  evicted during the re-attach grace window*, *no parked member
  stranded and no slot double-granted across restart*.
- **gray** — the gray-failure suspicion ladder (ISSUE 20): one member
  renews its lease on time throughout while transient bursts, isolated
  marginal spikes, and a persistent one-way gray link schedule against
  the detector. Invariants: *no live renewing member evicted on
  transient weather* (confirmed suspicion enters probation, never the
  evict rung), *a persistent one-way gray link is contained within the
  deadline* (third-party link evidence indicts what the victim's own
  clean report launders), *no flap cycles* (isolated marginal spikes
  never meet the confirm/clear hysteresis).

Exploration is exhaustive breadth-first over SMALL configurations (2
workers x 2 updates; 2 lives; 3-stage pipeline slice with 2 steps x 2
microbatches) up to a configurable depth: every interleaving of send /
deliver / drop / dup / reorder (delivery order is free) / retransmit /
fsync / crash / restart within the fault budgets is visited, which is
exactly what a seeded scenario suite cannot do.

**Mutations** re-run a model with one protocol guard removed (the
soundness corpus: ``ack_before_fsync``, ``no_dedup``,
``no_seed_on_restore``, ``no_incarnation_gate``, ``watermark_off_by_one``,
``no_mb_dedup``, ``no_error_feedback``, ``decode_before_admission``,
``stale_delta_base``, ``no_full_fallback_on_restore``,
``park_without_manifest``, ``double_grant_slot``, ``no_epoch_fence``,
``expire_on_restart``, ``forget_parked``, ``no_hysteresis``,
``symmetric_probe_only``, ``evict_on_first_suspicion``); the
checker must find a counterexample for each. Every
counterexample is emitted as a JSON artifact carrying the event trace, a
concrete :class:`~.chaos.ChaosPlan` (deterministic windowed fault rules
derived from the trace's drop/dup events), a crash script, and a pytest
repro stub; :func:`replay_counterexample` drives the REAL
``ReliableTransport`` / ``ParameterServer`` / WAL stack through the same
schedule — failing under the mutated configuration, passing on the
correct one — closing the loop between the static model and the running
system (``tests/test_distmodel.py``).

CLI::

    python -m distributed_ml_pytorch_tpu.analysis distmodel            # all models, must hold
    python -m distributed_ml_pytorch_tpu.analysis distmodel --json
    python -m distributed_ml_pytorch_tpu.analysis distmodel \\
        --mutate ack_before_fsync --out /tmp/ce                        # expect a counterexample
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, Tuple

Label = Tuple  # one event, e.g. ("deliver", 1, 0); rendered with _fmt


def _fmt(label: Label) -> str:
    return " ".join(str(x) for x in label)


@dataclasses.dataclass
class Result:
    """One bounded-exploration verdict. ``complete`` distinguishes a
    verdict that covered every state within the depth bound from one the
    ``max_states`` cap truncated mid-frontier — an ok on a truncated
    search is still only a bounded claim, and the CLI says so."""

    model: str
    mutation: Optional[str]
    ok: bool
    states: int
    depth: int
    invariant: Optional[str] = None       # the violated invariant, if any
    trace: Optional[List[Label]] = None   # events from the initial state
    complete: bool = True                 # False when max_states truncated

    def to_json(self) -> dict:
        out = {"model": self.model, "mutation": self.mutation,
               "ok": self.ok, "states": self.states, "depth": self.depth,
               "complete": self.complete}
        if not self.ok:
            out["invariant"] = self.invariant
            out["trace"] = [_fmt(e) for e in self.trace or []]
        return out


class Model:
    """An explicit-state model: initial state, successor relation, and a
    state invariant. States are hashable tuples; successors enumerate
    EVERY enabled event so the exploration is exhaustive up to depth."""

    name = "model"

    def initial(self):
        raise NotImplementedError

    def successors(self, state) -> Iterable[Tuple[Label, tuple]]:
        raise NotImplementedError

    def invariant(self, state) -> Optional[str]:
        raise NotImplementedError


def explore(model: Model, max_depth: int = 14,
            max_states: int = 400_000) -> Result:
    """Breadth-first exhaustive exploration; the first violating state's
    shortest trace becomes the counterexample."""
    init = model.initial()
    parents: Dict[tuple, Optional[Tuple[tuple, Label]]] = {init: None}
    frontier = [init]
    depth = 0
    truncated = False
    violation = model.invariant(init)
    bad = init if violation else None
    while frontier and bad is None and depth < max_depth \
            and not truncated:
        depth += 1
        nxt = []
        for state in frontier:
            for label, succ in model.successors(state):
                if succ in parents:
                    continue
                parents[succ] = (state, label)
                v = model.invariant(succ)
                if v is not None:
                    violation, bad = v, succ
                    break
                nxt.append(succ)
                if len(parents) >= max_states:
                    truncated = True
                    break
            if bad is not None or truncated:
                break
        frontier = nxt
    if bad is None:
        return Result(model.name, getattr(model, "mutation", None),
                      True, len(parents), depth, complete=not truncated)
    trace: List[Label] = []
    cur = bad
    while parents[cur] is not None:
        prev, label = parents[cur]
        trace.append(label)
        cur = prev
    trace.reverse()
    return Result(model.name, getattr(model, "mutation", None),
                  False, len(parents), depth, violation, trace)


# =====================================================================
# ps — exactly-once / WAL-before-ack / crash-restore
# =====================================================================

class PSModel(Model):
    """The DownPour push path: ``n_workers`` workers each push
    ``n_updates`` GradientUpdates through the reliability envelope to one
    WAL'd shard server, under bounded drop/dup/crash budgets. Delivery
    picks ANY in-flight frame, so reordering is implicit.

    State ::

        (sent,        # per worker: next seq to send
         acked,       # per worker: frozenset of acked seqs
         net,         # in-flight data frames: sorted (w, seq), dup copies allowed
         net_acks,    # in-flight acks: sorted (w, seq)
         up,          # server alive?
         seen,        # server dedup state: frozenset (w, seq)
         wal_synced,  # fsync'd WAL records (sorted)
         wal_pend,    # appended, not yet fsync'd (sorted)
         applied,     # live applied multiset (sorted, dups possible)
         deferred,    # delivery acks withheld for the group fsync
         drops, dups, crashes)   # remaining fault budgets

    Mutations: ``ack_before_fsync`` (delivery acks released at apply),
    ``no_dedup`` (receiver never consults ``seen``),
    ``no_seed_on_restore`` (restart forgets the dedup seed the WAL
    carries).
    """

    name = "ps"

    def __init__(self, n_workers: int = 2, n_updates: int = 2,
                 drops: int = 1, dups: int = 1, crashes: int = 1,
                 mutation: Optional[str] = None):
        self.n_workers = n_workers
        self.n_updates = n_updates
        self.budgets = (drops, dups, crashes)
        self.mutation = mutation

    def initial(self):
        w = self.n_workers
        return ((0,) * w, (frozenset(),) * w, (), (), True,
                frozenset(), (), (), (), (), *self.budgets)

    def successors(self, st):
        (sent, acked, net, net_acks, up, seen, wal_synced, wal_pend,
         applied, deferred, drops, dups, crashes) = st
        mut = self.mutation
        out = []

        def pack(**kw):
            vals = dict(sent=sent, acked=acked, net=net, net_acks=net_acks,
                        up=up, seen=seen, wal_synced=wal_synced,
                        wal_pend=wal_pend, applied=applied,
                        deferred=deferred, drops=drops, dups=dups,
                        crashes=crashes)
            vals.update(kw)
            return (vals["sent"], vals["acked"], vals["net"],
                    vals["net_acks"], vals["up"], vals["seen"],
                    vals["wal_synced"], vals["wal_pend"], vals["applied"],
                    vals["deferred"], vals["drops"], vals["dups"],
                    vals["crashes"])

        # worker sends its next update
        for w in range(self.n_workers):
            if sent[w] < self.n_updates:
                frame = (w, sent[w])
                out.append((("send", w, sent[w]), pack(
                    sent=tuple(s + 1 if i == w else s
                               for i, s in enumerate(sent)),
                    net=tuple(sorted(net + (frame,))))))
        # retransmit: an unacked, not-currently-in-flight frame (the RTO
        # path; at-least-once delivery without an explicit timer)
        for w in range(self.n_workers):
            for seq in range(sent[w]):
                frame = (w, seq)
                if seq not in acked[w] and frame not in net:
                    out.append((("retransmit", w, seq), pack(
                        net=tuple(sorted(net + (frame,))))))
        # wire faults within budget
        for frame in sorted(set(net)):
            if drops > 0:
                lst = list(net)
                lst.remove(frame)
                out.append((("drop", *frame),
                            pack(net=tuple(lst), drops=drops - 1)))
            if dups > 0:
                out.append((("dup", *frame), pack(
                    net=tuple(sorted(net + (frame,))), dups=dups - 1)))
        for ackf in sorted(set(net_acks)):
            if drops > 0:
                lst = list(net_acks)
                lst.remove(ackf)
                out.append((("drop_ack", *ackf),
                            pack(net_acks=tuple(lst), drops=drops - 1)))
        # delivery (any in-flight frame — reordering is implicit)
        if up:
            for frame in sorted(set(net)):
                lst = list(net)
                lst.remove(frame)
                kw = dict(net=tuple(lst))
                if mut != "no_dedup" and frame in seen:
                    # duplicate: re-ack, never re-apply — UNLESS its ack
                    # is still withheld for the group fsync (re-acking a
                    # deferred frame early is exactly the bug the real
                    # transport's `withheld` check prevents; the model
                    # rediscovers it if this branch re-acks blindly)
                    if frame not in deferred:
                        kw["net_acks"] = tuple(
                            sorted(set(net_acks) | {frame}))
                else:
                    kw["seen"] = seen | {frame}
                    kw["wal_pend"] = tuple(sorted(wal_pend + (frame,)))
                    kw["applied"] = tuple(sorted(applied + (frame,)))
                    if mut == "ack_before_fsync":
                        kw["net_acks"] = tuple(
                            sorted(set(net_acks) | {frame}))
                    else:
                        kw["deferred"] = tuple(sorted(
                            set(deferred) | {frame}))
                out.append((("deliver", *frame), pack(**kw)))
            if wal_pend:
                out.append((("fsync",), pack(
                    wal_synced=tuple(sorted(wal_synced + wal_pend)),
                    wal_pend=(),
                    net_acks=tuple(sorted(set(net_acks) | set(deferred))),
                    deferred=())))
            if crashes > 0:
                # the crash loses everything but the fsync'd log
                out.append((("crash",), pack(
                    up=False, seen=frozenset(), wal_pend=(), applied=(),
                    deferred=(), crashes=crashes - 1)))
        else:
            restored_seen = (frozenset() if mut == "no_seed_on_restore"
                             else frozenset(wal_synced))
            out.append((("restart",), pack(
                up=True, seen=restored_seen, applied=wal_synced)))
        # ack delivery to the worker
        for ackf in sorted(set(net_acks)):
            w, seq = ackf
            lst = list(net_acks)
            lst.remove(ackf)
            out.append((("deliver_ack", w, seq), pack(
                net_acks=tuple(lst),
                acked=tuple(a | {seq} if i == w else a
                            for i, a in enumerate(acked)))))
        return out

    def invariant(self, st):
        (sent, acked, net, net_acks, up, seen, wal_synced, wal_pend,
         applied, deferred, drops, dups, crashes) = st
        if len(applied) != len(set(applied)):
            dup = next(f for f in applied if applied.count(f) > 1)
            return (f"exactly-once violated: update w{dup[0]}#{dup[1]} "
                    "applied twice")
        if up:
            live = set(applied)
            for w, a in enumerate(acked):
                for seq in a:
                    if (w, seq) not in live:
                        return (f"acked update w{w}#{seq} is not applied "
                                "after restore — the ack outlived the "
                                "truncated WAL tail (log-before-ack "
                                "violated)")
        return None


# =====================================================================
# lease — incarnation-ordered membership
# =====================================================================

class LeaseModel(Model):
    """Lives ``1..n_lives`` of one member rank, each sending at most one
    join / renew / leave, frames delayed / duplicated / delivered in any
    order toward one coordinator.

    State ::

        (sent_kinds,   # per life: frozenset of {join, renew, leave} sent
         net,          # in-flight (kind, inc), dup copies allowed
         member_inc,   # coordinator's admitted incarnation (0 = none)
         flag,         # sticky violation recorded at apply time (0 = ok)
         dups)

    The violations are properties of a TRANSITION (adopting an older
    incarnation over a newer one; a stale life's leave evicting the
    current one), so they are latched into ``flag`` when the offending
    frame is applied — a later legitimate epoch must not mask them. A
    clean re-join after a clean leave (history legitimately resets) is
    NOT a violation, matching the real coordinator.

    Mutation: ``no_incarnation_gate`` — the coordinator applies whatever
    arrives, in arrival order.
    """

    name = "lease"

    _OK, _BACKWARD, _STALE_EVICT = 0, 1, 2

    def __init__(self, n_lives: int = 2, dups: int = 1,
                 mutation: Optional[str] = None):
        self.n_lives = n_lives
        self.mutation = mutation
        self.dups = dups

    def initial(self):
        return ((frozenset(),) * self.n_lives, (), 0, self._OK, self.dups)

    def successors(self, st):
        sent_kinds, net, member_inc, flag, dups = st
        gate = self.mutation != "no_incarnation_gate"
        out = []
        for life in range(self.n_lives):
            inc = life + 1
            for kind in ("join", "renew", "leave"):
                if kind in sent_kinds[life]:
                    continue
                out.append(((kind, inc), (
                    tuple(k | {kind} if i == life else k
                          for i, k in enumerate(sent_kinds)),
                    tuple(sorted(net + ((kind, inc),))),
                    member_inc, flag, dups)))
        for frame in sorted(set(net)):
            if dups > 0:
                out.append((("dup", *frame), (
                    sent_kinds, tuple(sorted(net + (frame,))),
                    member_inc, flag, dups - 1)))
            kind, inc = frame
            lst = list(net)
            lst.remove(frame)
            mi, fl = member_inc, flag
            if kind in ("join", "renew"):
                if kind == "renew" and mi == 0:
                    pass  # renew for an unknown member: ignored
                elif gate and mi and inc < mi:
                    pass  # stale life's frame: gated away
                else:
                    if mi and inc < mi:
                        fl = self._BACKWARD  # adopted an OLDER life
                    mi = inc
            else:  # leave
                if mi == 0 or (gate and inc != mi):
                    pass
                else:
                    if inc < mi:
                        fl = self._STALE_EVICT
                    mi = 0
            out.append((("deliver", kind, inc),
                        (sent_kinds, tuple(lst), mi, fl, dups)))
        return out

    def invariant(self, st):
        _sent, _net, _member_inc, flag, _dups = st
        if flag == self._BACKWARD:
            return ("lease monotonicity violated: a stale life's "
                    "join/renew rolled the admitted incarnation backward")
        if flag == self._STALE_EVICT:
            return ("stale-life eviction: an old life's CoordLeave "
                    "evicted the newer live incarnation")
        return None


# =====================================================================
# mpmd — (step, mb) dedup + watermark replay
# =====================================================================

class MpmdModel(Model):
    """One stage hand-off of the MPMD pipeline: the upstream stage ships
    microbatches ``0..steps*M-1`` in order (retaining everything), the
    receiver applies under ``(step, mb)`` dedup, checkpoints its
    step-boundary watermark, crashes, and is healed by watermark-bounded
    replay — ``parallel/mpmd.py``'s restart contract, with the replay
    cutoff mirrored by :func:`~.mpmd.replay_covers`.

    State ::

        (produced,     # next index the sender will ship
         net,          # in-flight indices, dup copies allowed
         applied,      # receiver's applied set
         dup_applied,  # sticky: some index was applied twice
         ckpt_wm,      # last checkpointed watermark (step boundary)
         up, dups, crashes)

    Mutations: ``watermark_off_by_one`` (replay re-ships strictly ABOVE
    the announced watermark), ``no_mb_dedup`` (receiver re-applies
    redeliveries).
    """

    name = "mpmd"

    def __init__(self, steps: int = 2, microbatches: int = 2,
                 dups: int = 1, crashes: int = 1,
                 mutation: Optional[str] = None):
        self.total = steps * microbatches
        self.M = microbatches
        self.mutation = mutation
        self.budgets = (dups, crashes)

    def initial(self):
        return (0, (), frozenset(), False, 0, True, *self.budgets)

    def _watermark(self, applied: FrozenSet[int]) -> int:
        wm = 0
        while wm + self.M <= self.total and all(
                i in applied for i in range(wm, wm + self.M)):
            wm += self.M
        return wm

    def successors(self, st):
        produced, net, applied, dup_applied, ckpt_wm, up, dups, crashes = st
        mut = self.mutation
        out = []
        if produced < self.total:
            out.append((("ship", produced), (
                produced + 1, tuple(sorted(net + (produced,))), applied,
                dup_applied, ckpt_wm, up, dups, crashes)))
        for idx in sorted(set(net)):
            if dups > 0:
                out.append((("dup", idx), (
                    produced, tuple(sorted(net + (idx,))), applied,
                    dup_applied, ckpt_wm, up, dups - 1, crashes)))
            if up:
                lst = list(net)
                lst.remove(idx)
                if idx in applied:
                    out.append((("deliver", idx), (
                        produced, tuple(lst), applied,
                        dup_applied or mut == "no_mb_dedup",
                        ckpt_wm, up, dups, crashes)))
                else:
                    out.append((("deliver", idx), (
                        produced, tuple(lst), applied | {idx},
                        dup_applied, ckpt_wm, up, dups, crashes)))
        if up:
            wm = self._watermark(applied)
            if wm > ckpt_wm:
                out.append((("checkpoint", wm), (
                    produced, net, applied, dup_applied, wm, up, dups,
                    crashes)))
            if crashes > 0:
                out.append((("crash",), (
                    produced, net, applied, dup_applied, ckpt_wm, False,
                    dups, crashes - 1)))
        else:
            # restart-and-replay is ONE atomic step: the StageReady /
            # StageAssign round trip — restore to the checkpoint, then the
            # neighbor re-ships retained traffic from the cutoff
            restored = frozenset(range(ckpt_wm))
            cutoff = ckpt_wm + (1 if mut == "watermark_off_by_one" else 0)
            reship = [i for i in range(cutoff, produced)
                      if i not in net]
            out.append((("restart", ckpt_wm), (
                produced, tuple(sorted(net + tuple(reship))), restored,
                dup_applied, ckpt_wm, True, dups, crashes)))
        return out

    def invariant(self, st):
        produced, net, applied, dup_applied, ckpt_wm, up, dups, crashes = st
        if dup_applied:
            return "a (step, mb) microbatch was applied twice"
        if up and produced == self.total and not net \
                and len(applied) != self.total:
            holes = sorted(set(range(self.total)) - applied)
            return (f"watermark replay left hole(s) {holes}: the pipeline "
                    "is quiescent below its frontier with microbatches "
                    "missing")
        return None


# =====================================================================
# copt — compressed-push error feedback + decode-before-admission
# =====================================================================

class CompressModel(Model):
    """The compressed optimizer-plane push path (ISSUE 14): one worker
    pushes ``n_updates`` fixed-value updates through a lossy quantizer
    (floor to multiples of ``Q`` — the abstract int8/topk), carrying a
    per-worker error-feedback residual; an SDC budget may silently
    corrupt an in-flight frame into a poison whose DECODED magnitude
    dwarfs the admission gate while its encoded bytes look ordinary; the
    server admission-gates on the decoded value and applies.

    State ::

        (next,        # next update index to push
         residual,    # worker-side error-feedback carry
         net,         # in-flight frames: sorted (idx, decoded_value)
         applied,     # server's applied sum
         sent,        # true sum of raw update values pushed so far
         poisoned,    # sticky: a poison's decoded value was APPLIED
         sdc,         # remaining silent-corruption budget
         sdc_used)    # any corruption happened (disables the EF bound)

    Invariants: *quiescent error bound* — with no corruption, once every
    push is delivered, ``|applied + residual - sent| == 0`` and
    ``residual < Q`` (the error-feedback identity: quantization error is
    deferred, never compounded); *no poison applied* — a frame whose
    decoded magnitude exceeds the gate never reaches the applied sum.

    Mutations: ``no_error_feedback`` (the residual is dropped — each
    push's quantization error is lost forever, the sum drifts past Q);
    ``decode_before_admission`` (the handler gates before/without
    decoding, so compressed traffic slips the gate — the poison's decoded
    value applies). SDC is only enabled at or past frame index
    :data:`_WARMUP` — the real gate's z-score needs admitted history, and
    the replayed chaos schedule bakes the same warmup in.
    """

    name = "copt"

    #: update values and quantization step: 3 // 4 -> 0, so without error
    #: feedback EVERY push quantizes to zero and the drift is maximal
    _VALUES = (3, 3, 3, 3, 3)
    _Q = 4
    _GATE = 100
    _POISON = 1000
    _WARMUP = 2

    def __init__(self, n_updates: int = 5, sdc: int = 1,
                 mutation: Optional[str] = None):
        self.n_updates = min(n_updates, len(self._VALUES))
        self.mutation = mutation
        self.sdc = sdc

    def initial(self):
        return (0, 0, (), 0, 0, False, self.sdc, False)

    def successors(self, st):
        nxt, residual, net, applied, sent, poisoned, sdc, sdc_used = st
        mut = self.mutation
        out = []
        if nxt < self.n_updates:
            v = self._VALUES[nxt]
            if mut == "no_error_feedback":
                q, new_res = (v // self._Q) * self._Q, 0
            else:
                p = v + residual
                q = (p // self._Q) * self._Q
                new_res = p - q
            out.append((("push", nxt, q), (
                nxt + 1, new_res, tuple(sorted(net + ((nxt, q),))),
                applied, sent + v, poisoned, sdc, sdc_used)))
        for frame in sorted(set(net)):
            idx, val = frame
            if sdc > 0 and val != self._POISON and idx >= self._WARMUP:
                lst = list(net)
                lst.remove(frame)
                out.append((("sdc", idx), (
                    nxt, residual,
                    tuple(sorted(lst + [(idx, self._POISON)])),
                    applied, sent, poisoned, sdc - 1, True)))
            lst = list(net)
            lst.remove(frame)
            if mut != "decode_before_admission" and val > self._GATE:
                # admission on the DECODED value: poison quarantined
                out.append((("deliver", idx, "rejected"), (
                    nxt, residual, tuple(lst), applied, sent,
                    poisoned, sdc, sdc_used)))
            else:
                out.append((("deliver", idx, val), (
                    nxt, residual, tuple(lst), applied + val, sent,
                    poisoned or val == self._POISON, sdc, sdc_used)))
        return out

    def invariant(self, st):
        nxt, residual, net, applied, sent, poisoned, sdc, sdc_used = st
        if poisoned:
            return ("poisoned decoded update admitted: the gate never saw "
                    "the decoded norm (compressed traffic slipped it)")
        if not sdc_used and nxt == self.n_updates and not net:
            if applied + residual != sent or not 0 <= residual < self._Q:
                return (f"error-feedback bound violated: applied {applied} "
                        f"+ residual {residual} != sent {sent} at "
                        "quiescence — quantization error was dropped, not "
                        "deferred")
        return None


# =====================================================================
# dpull — delta-encoded pull replies: held-stamp check + restore fence
# =====================================================================

class DeltaPullModel(Model):
    """The delta-encoded ``ShardParams`` pull-reply plane (ISSUE 18,
    ``parallel/async_ps.py``): one worker pulls from one server that
    tracks the worker's last-shipped view and answers with either a FULL
    reply or a DELTA against that tracked base. Replies may be lost or
    arbitrarily delayed; the server may crash-restore, losing its
    un-fsynced tail and then re-filling the SAME version numbers with
    DIFFERENT bytes (a life-1 push adds 2 where a life-0 push added 1).

    State ::

        (pushes, pulls, drops, restores,   # remaining event budgets
         s_epoch,    # server pull epoch (bumped by the restore fence)
         s_ver,      # server apply version
         s_central,  # abstract central value
         life,       # 0 before the crash-restore, 1 after
         base,       # None | (epoch, ver, val): server's mirror of the
                     #   worker's view, updated at every reply cut
         w,          # None | (epoch, ver, val): worker's installed view
         net)        # in-flight replies, sorted tuple of
                     #   ("F", epoch, ver, val) |
                     #   ("D", epoch, base_ver, ver, dval)

    A pull carries the worker's held stamp; the clean server ships a
    delta only when its tracked base matches BOTH the held stamp and the
    current epoch, else it falls back to a full reply. The clean worker
    applies a delta only when its held stamp equals the frame's
    ``(epoch, base_ver)``. A restore always clears the (in-memory) base
    table and — this is the fence — bumps the pull epoch so zombie
    replies cut in the previous life can never be mistaken for current.

    Invariant: *stamp-authenticated view* — a worker whose held stamp
    equals the server's CURRENT ``(epoch, ver)`` holds exactly
    ``s_central``. (A stale stamp is allowed to carry stale bytes; the
    protocol heals it with a full reply on the next pull.)

    Mutations: ``stale_delta_base`` (the server skips the held-stamp
    check and ships a delta against whatever base it tracks — after a
    LOST reply advanced the tracked base past the worker, the delta
    applies onto the wrong base; pairs with the worker trusting the
    server blindly, the real stack's ``delta_trust``);
    ``no_full_fallback_on_restore`` (the restore skips the epoch bump,
    so a zombie delta cut before the crash applies cleanly onto a
    same-numbered-but-different-bytes post-restore history).
    """

    name = "dpull"

    def __init__(self, pushes: int = 3, pulls: int = 3, drops: int = 1,
                 restores: int = 1, mutation: Optional[str] = None):
        self.pushes = pushes
        self.pulls = pulls
        self.drops = drops
        self.restores = restores
        self.mutation = mutation

    def initial(self):
        return (self.pushes, self.pulls, self.drops, self.restores,
                0, 0, 0, 0, None, None, ())

    def successors(self, st):
        (pushes, pulls, drops, restores,
         s_epoch, s_ver, s_central, life, base, w, net) = st
        mut = self.mutation
        out = []
        if pushes > 0:
            # a life-1 push adds 2 where a life-0 push added 1: the
            # re-filled history reuses version NUMBERS with new bytes
            out.append((("push", s_ver + 1), (
                pushes - 1, pulls, drops, restores, s_epoch, s_ver + 1,
                s_central + (2 if life else 1), life, base, w, net)))
        if pulls > 0:
            held = (w[0], w[1]) if w is not None else None
            if mut == "stale_delta_base":
                use_delta = base is not None
            else:
                use_delta = (base is not None and held is not None
                             and held == (base[0], base[1])
                             and base[0] == s_epoch)
            if use_delta:
                frame = ("D", s_epoch, base[1], s_ver,
                         s_central - base[2])
                kind = "delta"
            else:
                frame = ("F", s_epoch, s_ver, s_central)
                kind = "full"
            out.append((("pull", kind, s_ver), (
                pushes, pulls - 1, drops, restores, s_epoch, s_ver,
                s_central, life, (s_epoch, s_ver, s_central), w,
                tuple(sorted(net + (frame,))))))
        for frame in sorted(set(net)):
            lst = list(net)
            lst.remove(frame)
            rest = tuple(lst)
            if drops > 0:
                out.append((("drop_reply", frame[0], frame[2]), (
                    pushes, pulls, drops - 1, restores, s_epoch, s_ver,
                    s_central, life, base, w, rest)))
            if frame[0] == "F":
                new_w = (frame[1], frame[2], frame[3])
            else:
                _, f_epoch, f_base_ver, f_ver, dval = frame
                trust = (mut == "stale_delta_base")
                applies = (w is not None
                           and (trust
                                or (w[0], w[1]) == (f_epoch, f_base_ver)))
                if not applies:
                    # base miss: the frame is discarded, the worker
                    # keeps its view and will full-sync on a later pull
                    out.append((("deliver", "miss", f_ver), (
                        pushes, pulls, drops, restores, s_epoch, s_ver,
                        s_central, life, base, w, rest)))
                    continue
                new_w = (f_epoch, f_ver, w[2] + dval)
            out.append((("deliver", frame[0], frame[2]), (
                pushes, pulls, drops, restores, s_epoch, s_ver,
                s_central, life, base, new_w, rest)))
        if restores > 0:
            # crash-restore to the (initial) checkpoint: the in-memory
            # base table is gone either way; only the FENCE — the epoch
            # bump that invalidates pre-crash stamps — is the mutation
            bump = 0 if mut == "no_full_fallback_on_restore" else 1
            out.append((("restore",), (
                pushes, pulls, drops, restores - 1, s_epoch + bump, 0,
                0, 1, None, w, net)))
        return out

    def invariant(self, st):
        (_pushes, _pulls, _drops, _restores,
         s_epoch, s_ver, s_central, _life, _base, w, _net) = st
        if w is not None and (w[0], w[1]) == (s_epoch, s_ver) \
                and w[2] != s_central:
            return ("delta-reply divergence: the worker's held stamp "
                    f"matches the server's current (epoch {s_epoch}, "
                    f"ver {s_ver}) but its view {w[2]} != central "
                    f"{s_central} — a delta applied onto the wrong base")
        return None


# =====================================================================
# sched — lease + preempt + park/resume exclusivity and durability
# =====================================================================

class SchedModel(Model):
    """The multi-tenant scheduler's preempt/park/resume protocol
    (ISSUE 16, ``coord/sched.py``) over ONE slot and two tenants: a
    training member owns the slot and produces acked deltas; a serving
    tenant's demand peaks, the scheduler parks the member and grants the
    slot; off-peak the grant is revoked and the member resumes from its
    park manifest.

    State ::

        (owner,     # 0 free | 1 training | 2 serving
         tstate,    # training member: 0 running | 1 parked
         produced,  # acked deltas the member has applied (0..N)
         synced,    # deltas durable in the WAL (fsync group commit)
         manifest,  # -1 = no park manifest | deltas the snapshot covers
         demand,    # serving tenant's current want (0/1)
         peaked, offpeaked,   # one-shot diurnal toggles
         viol)      # sticky: 0 ok | 1 double-grant | 2 lost acked state

    The two guards under test, each dropped by one seeded mutation:

    - *require_manifest* — a park is legal only under a snapshot barrier
      manifest (park itself commits the WAL, so a STALE manifest is fine
      — replay covers the gap — but NO manifest leaves nothing to
      restore). ``park_without_manifest`` drops it: the resume of a
      parked member that produced deltas has lost acked state.
    - *exclusive grant* — the slot is granted to the waiting tenant only
      once the victim's park completes (the slot is free).
      ``double_grant_slot`` drops it: the grant fires while the training
      member still holds the slot — two tenants own one slot.

    Both violations latch into ``viol`` at the offending transition
    (sticky, like the lease model) so later legal events cannot mask
    them.
    """

    name = "sched"

    def __init__(self, n_updates: int = 3, mutation: Optional[str] = None):
        self.n_updates = n_updates
        self.mutation = mutation

    _OK, _DOUBLE_GRANT, _LOST_STATE = 0, 1, 2

    def initial(self):
        return (1, 0, 0, 0, -1, 0, 0, 0, self._OK)

    def successors(self, st):
        (owner, tstate, produced, synced, manifest, demand,
         peaked, offpeaked, viol) = st
        mut = self.mutation
        out = []
        if owner == 1 and tstate == 0 and produced < self.n_updates:
            out.append((("push", produced), (
                owner, tstate, produced + 1, synced, manifest, demand,
                peaked, offpeaked, viol)))
        if synced < produced:
            out.append((("fsync",), (
                owner, tstate, produced, produced, manifest, demand,
                peaked, offpeaked, viol)))
        if owner == 1 and tstate == 0 and manifest != produced:
            # snapshot barrier: commit + checkpoint (coordinator-aligned)
            out.append((("snapshot", produced), (
                owner, tstate, produced, produced, produced, demand,
                peaked, offpeaked, viol)))
        if not peaked:
            out.append((("peak",), (
                owner, tstate, produced, synced, manifest, 1,
                1, offpeaked, viol)))
        if peaked and demand == 1 and not offpeaked:
            out.append((("offpeak",), (
                owner, tstate, produced, synced, manifest, 0,
                peaked, 1, viol)))
        if owner == 1 and tstate == 0 and demand == 1 \
                and (mut == "park_without_manifest" or manifest != -1):
            # park: the victim commits its WAL group and stops; the
            # require_manifest guard is what the mutation drops
            out.append((("park",), (
                0, 1, produced, produced, manifest, demand,
                peaked, offpeaked, viol)))
        if demand == 1 and owner != 2:
            if owner == 0:
                out.append((("grant",), (
                    2, tstate, produced, synced, manifest, demand,
                    peaked, offpeaked, viol)))
            elif mut == "double_grant_slot":
                # exclusivity dropped: granted while the training member
                # still holds the slot — the illegal two-owner state
                out.append((("grant",), (
                    2, tstate, produced, synced, manifest, demand,
                    peaked, offpeaked, self._DOUBLE_GRANT)))
        if owner == 2 and demand == 0:
            out.append((("release",), (
                0, tstate, produced, synced, manifest, demand,
                peaked, offpeaked, viol)))
        if owner == 0 and tstate == 1:
            v = viol
            if manifest == -1 and synced > 0:
                v = self._LOST_STATE  # nothing to restore from
            out.append((("resume",), (
                1, 0, produced, synced, manifest, demand,
                peaked, offpeaked, v)))
        return out

    def invariant(self, st):
        viol = st[-1]
        if viol == self._DOUBLE_GRANT:
            return ("slot double-granted: two tenants own one slot (the "
                    "grant fired before the victim's park completed)")
        if viol == self._LOST_STATE:
            return ("resume lost acked state: the member parked without "
                    "a manifest, so its acked deltas are unrecoverable")
        return None


# =====================================================================
# coordfail — coordinator crash/restore, epoch fencing, grace window
# =====================================================================

class CoordFailModel(Model):
    """The control plane's own failure protocol (ISSUE 17,
    ``coord/coordinator.py``): the coordinator crashes (or is partitioned
    into a zombie) mid-epoch with one preemption in flight, a successor
    restores from ckpt+WAL, a blipped member rejoins — bounded
    exhaustive over every interleaving of bump / preempt / grant /
    crash / partition / zombie traffic / rejoin / resume.

    State ::

        (life,      # arbiter life: 1 | 2
         split,     # 1 = life 1 still runs as a ZOMBIE (partition,
                    #     not death)
         wepoch,    # member-side: highest coordinator epoch witnessed
         mver,      # member-side: adopted map version
         cver,      # authority-side: durable map version
         zver,      # zombie's private map version (diverged topology)
         net,       # in-flight control frames: sorted (version, epoch)
         parked,    # the preemption victim is parked
         dur_park,  # the durable park table still holds its ticket
         owners,    # owners of the victim's slot (0 | 1 | 2)
         resumed,   # the victim resumed
         grace,     # successor's re-attach grace window is open
         rejoined,  # the blipped member re-attached to the successor
         viol)      # sticky violation latch

    The three guards under test, each dropped by one seeded mutation:

    - *epoch fence* — members reject control frames stamped with an
      epoch below the highest they have witnessed. ``no_epoch_fence``
      drops it: a partitioned pre-crash coordinator's diverged map is
      adopted over the successor's — map authority stops being
      monotonic across lives.
    - *grace window* — after a restart, lease expiry is suspended until
      the restored member's join-retry traffic re-attaches it.
      ``expire_on_restart`` drops it: the successor evicts a perfectly
      healthy member that merely straddled the control-plane blip.
    - *durable park table* — the restore replays WAL'd park tickets, so
      a crash mid-preemption keeps the victim lease-exempt and its slot
      single-owner. ``forget_parked`` drops it: the victim is stranded
      (lease re-armed) or its slot is granted twice.
    """

    name = "coordfail"

    _VMAX = 2  # map-version bumps per life (state-space bound)

    _OK, _ZOMBIE, _EVICTED, _STRANDED, _DOUBLE_GRANT = 0, 1, 2, 3, 4

    def __init__(self, mutation: Optional[str] = None):
        self.mutation = mutation

    def initial(self):
        return (1, 0, 0, 0, 0, 0, (), 0, 0, 0, 0, 0, 1, self._OK)

    def successors(self, st):
        (life, split, wepoch, mver, cver, zver, net, parked, dur_park,
         owners, resumed, grace, rejoined, viol) = st
        mut = self.mutation
        out = []

        def pack(**kw):
            vals = dict(life=life, split=split, wepoch=wepoch, mver=mver,
                        cver=cver, zver=zver, net=net, parked=parked,
                        dur_park=dur_park, owners=owners, resumed=resumed,
                        grace=grace, rejoined=rejoined, viol=viol)
            vals.update(kw)
            return (vals["life"], vals["split"], vals["wepoch"],
                    vals["mver"], vals["cver"], vals["zver"], vals["net"],
                    vals["parked"], vals["dur_park"], vals["owners"],
                    vals["resumed"], vals["grace"], vals["rejoined"],
                    vals["viol"])

        # the authority WALs a map bump, then broadcasts (epoch-stamped)
        if cver < self._VMAX:
            out.append((("bump", cver + 1), pack(
                cver=cver + 1,
                net=tuple(sorted(net + ((cver + 1, life),))))))
        # preempt: the victim parks; the park ticket is WAL'd atomically
        # (log-then-mutate), freeing its slot
        if not parked and not resumed:
            out.append((("preempt",), pack(parked=1, dur_park=1)))
        # the freed slot is granted to the waiting tenant
        if parked and owners == 0:
            out.append((("grant",), pack(owners=1)))
        # the arbiter dies / is partitioned away; a successor restores
        # from ckpt+WAL. A partition leaves life 1 running as a zombie
        # whose topology now diverges from the successor's.
        if life == 1:
            restore = dict(
                life=2, rejoined=0,
                grace=0 if mut == "expire_on_restart" else 1,
                dur_park=0 if mut == "forget_parked" else dur_park)
            out.append((("crash",), pack(**restore)))
            out.append((("partition",), pack(split=1, zver=cver,
                                             **restore)))
        # the zombie keeps rebalancing its (dead) view of the fleet
        if split and zver < self._VMAX + 1:
            out.append((("zombie_bump", zver + 1), pack(
                zver=zver + 1,
                net=tuple(sorted(net + ((zver + 1, 1),))))))
        # a member consumes one in-flight control frame
        for frame in sorted(set(net)):
            ver, epoch = frame
            lst = list(net)
            lst.remove(frame)
            if wepoch and epoch < wepoch and mut != "no_epoch_fence":
                # the fence: stale-epoch command dropped before dispatch
                out.append((("fence", ver, epoch), pack(net=tuple(lst))))
                continue
            kw = dict(net=tuple(lst), wepoch=max(wepoch, epoch))
            if ver > mver:  # the member's own version gate
                kw["mver"] = ver
                if wepoch and epoch < wepoch:
                    # a dead epoch rebalanced the fleet: authority no
                    # longer monotonic across coordinator lives
                    kw["viol"] = self._ZOMBIE
                    kw["wepoch"] = wepoch
            out.append((("deliver", ver, epoch), pack(**kw)))
        # the blipped member's join-retry re-attaches it (closes grace)
        if life == 2 and not rejoined:
            out.append((("rejoin",), pack(rejoined=1, grace=0)))
        # lease sweep: with the grace window open this is suspended; a
        # member evicted while merely straddling the blip is a violation
        if life == 2 and not grace:
            if not rejoined:
                out.append((("expire_blipped",),
                            pack(viol=self._EVICTED)))
            if parked and not dur_park:
                # the park ticket was forgotten: lease expiry re-armed
                # on a member that is parked, not dead — the strand
                out.append((("expire_parked",),
                            pack(viol=self._STRANDED)))
        # a successor that forgot the park believes the victim still
        # holds its slot — the next grant double-books it
        if life == 2 and parked and not dur_park and owners == 1:
            out.append((("regrant",), pack(owners=2,
                                           viol=self._DOUBLE_GRANT)))
        # off-peak: the durable ticket restores the victim exactly once
        if parked and dur_park:
            out.append((("resume",), pack(parked=0, dur_park=0,
                                          owners=0, resumed=1)))
        return out

    def invariant(self, st):
        viol = st[-1]
        if viol == self._ZOMBIE:
            return ("stale-epoch command adopted: a zombie pre-crash "
                    "coordinator rebalanced the successor's fleet (map "
                    "authority not monotonic across coordinator lives)")
        if viol == self._EVICTED:
            return ("restored member evicted during the control-plane "
                    "blip: no grace window suspended lease expiry until "
                    "its join-retry re-attached it")
        if viol == self._STRANDED:
            return ("parked member stranded: the restart forgot the "
                    "durable park table, so lease expiry re-armed on a "
                    "member that is parked, not dead")
        if viol == self._DOUBLE_GRANT:
            return ("slot double-granted across coordinator restart: the "
                    "forgotten park ticket let the successor re-grant a "
                    "slot whose hand-over was already in flight")
        return None


# =====================================================================
# gray — adaptive suspicion ladder, asymmetric partitions, hysteresis
# =====================================================================

class GrayModel(Model):
    """The gray-failure plane's suspicion ladder (ISSUE 20,
    ``coord/grayhealth.py``) over ONE suspect member that renews its lease
    on time throughout — gray, never dead. The adversary schedules three
    weather shapes and the detector ticks:

    - ``blip`` — one transient SYMMETRIC burst: exactly two consecutive
      anomalous evidence samples, then the weather ends (one-shot). Long
      enough to confirm, too short to be persistent.
    - ``spike`` — a marginal isolated anomaly: one anomalous sample that
      by definition arrives with at least one clean sample on either side
      (arming requires the raise streak to be empty). The slow-but-honest
      member's weather.
    - ``grayline`` — a persistent ONE-WAY gray link: the suspect's own
      evidence stays clean forever (its inbound works; it cannot see the
      loss) and only third-party per-link reports carry the signal. It
      may later ``heal`` (one-shot), after which a quarantined member
      ``resume``s — re-entering the ladder at PROBATION, never straight
      to trusted.

    State ::

        (wi,       # transient burst: anomalous ticks remaining (0..2)
         wg,       # persistent one-way gray link active
         blipped,  # one-shot latch for the burst
         grayed,   # one-shot latch for the gray link
         healed,   # one-shot latch for its heal
         sp,       # a marginal spike is armed for the next tick
         st,       # ladder: 0 OK | 1 PROBATION | 2 QUARANTINED | 3 EVICTED
         rs, cs,   # raise / clear streaks (hysteresis counters)
         pt,       # anomalous ticks spent in probation
         flaps,    # OK -> PROBATION entries (capped)
         gt,       # ticks the persistent gray link ran UNCONTAINED
         viol)     # sticky violation latch

    The three guards under test, each dropped by one seeded mutation:

    - *hysteresis* — raising takes ``confirm=2`` consecutive anomalous
      ticks, clearing takes ``clear=2`` clean ones, so isolated marginal
      spikes never enter the ladder at all. ``no_hysteresis`` collapses
      both to one tick: every spike flaps OK->PROBATION->OK — the flap
      bound (3) latches.
    - *asymmetric detection* — per-link third-party evidence indicts a
      one-way partition its victim's own report launders.
      ``symmetric_probe_only`` ignores link evidence: the persistent gray
      link runs uncontained past the deadline (4 ticks) while the member
      renews cleanly — the blind spot.
    - *the ladder itself* — a confirmed suspicion enters PROBATION
      (route-around), never eviction. ``evict_on_first_suspicion``
      collapses the ladder onto the evict rung: a live renewing member is
      evicted on weather that ends one tick later.

    Containment for a persistent gray link in the CLEAN model is
    probation within ``confirm`` ticks and quarantine after ``pt >= 4``
    sustained-anomalous probation ticks — ``gt`` can never reach the
    deadline. All violations latch sticky, like the sched/coordfail
    models.
    """

    name = "gray"

    _CONFIRM, _CLEAR, _QUAR_AFTER, _DEADLINE, _FLAP_BOUND = 2, 2, 4, 4, 3

    _OK_V, _EVICT_LIVE, _NOT_CONTAINED, _FLAP = 0, 1, 2, 3

    def __init__(self, mutation: Optional[str] = None):
        self.mutation = mutation

    def initial(self):
        return (0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, self._OK_V)

    def successors(self, st_tuple):
        (wi, wg, blipped, grayed, healed, sp, st, rs, cs, pt, flaps, gt,
         viol) = st_tuple
        mut = self.mutation
        out = []
        # one transient two-sample burst (one-shot; exclusive weather)
        if not blipped and wi == 0 and wg == 0:
            out.append((("blip",), (
                2, wg, 1, grayed, healed, sp, st, rs, cs, pt, flaps, gt,
                viol)))
        # a marginal isolated spike: by definition separated from other
        # anomalies by a clean sample (the raise streak must be empty)
        if sp == 0 and wi == 0 and wg == 0 and rs == 0:
            out.append((("spike",), (
                wi, wg, blipped, grayed, healed, 1, st, rs, cs, pt, flaps,
                gt, viol)))
        # the persistent one-way gray link begins (one-shot)
        if wg == 0 and wi == 0 and not grayed:
            out.append((("grayline",), (
                wi, 1, blipped, 1, healed, sp, st, rs, cs, pt, flaps, gt,
                viol)))
        # ... and may heal (one-shot)
        if wg == 1 and not healed:
            out.append((("heal",), (
                wi, 0, blipped, grayed, 1, sp, st, rs, cs, pt, flaps, gt,
                viol)))
        # a quarantined member whose weather healed resumes — re-entering
        # at PROBATION, the earns-its-way-back rung
        if st == 2 and wg == 0:
            out.append((("resume",), (
                wi, wg, blipped, grayed, healed, sp, 1, 0, 0, 0, flaps,
                gt, viol)))
        out.append((("tick",), self._tick(st_tuple, mut)))
        return out

    def _tick(self, st_tuple, mut):
        (wi, wg, blipped, grayed, healed, sp, st, rs, cs, pt, flaps, gt,
         viol) = st_tuple
        # what this evaluation sees: the member's own evidence carries
        # symmetric weather; the one-way link is visible ONLY through
        # third-party link reports — which symmetric_probe_only ignores
        own_anom = wi > 0 or sp == 1
        link_anom = wg == 1 and mut != "symmetric_probe_only"
        anomalous = own_anom or link_anom
        confirm = 1 if mut == "no_hysteresis" else self._CONFIRM
        clear = 1 if mut == "no_hysteresis" else self._CLEAR
        wi2, sp2 = max(0, wi - 1), 0
        st2, rs2, cs2, pt2, flaps2, viol2 = st, rs, cs, pt, flaps, viol
        if st == 0:
            if anomalous:
                rs2, cs2 = min(rs + 1, confirm), 0
                if rs2 >= confirm:
                    if mut == "evict_on_first_suspicion":
                        st2 = 3
                        if wg == 0:
                            # the member renewed its lease throughout and
                            # the weather was transient — it dies anyway
                            viol2 = self._EVICT_LIVE
                    else:
                        st2, rs2, pt2 = 1, 0, 0
                        flaps2 = min(flaps + 1, self._FLAP_BOUND)
                        if flaps2 >= self._FLAP_BOUND:
                            viol2 = self._FLAP
            else:
                rs2, cs2 = 0, min(cs + 1, clear)
        elif st == 1:
            if anomalous:
                cs2, pt2 = 0, min(pt + 1, self._QUAR_AFTER)
                if pt2 >= self._QUAR_AFTER:
                    st2 = 2  # quarantined: contained (park, not kill)
            else:
                cs2 = min(cs + 1, clear)
                if cs2 >= clear:
                    st2, rs2, cs2, pt2 = 0, 0, 0, 0
        # st 2 (quarantined) and 3 (evicted) are absorbing here: resume /
        # rejoin are the drill's territory, not the detection model's
        gt2 = min(gt + 1, self._DEADLINE) if (wg == 1 and st2 == 0) else gt
        if gt2 >= self._DEADLINE:
            viol2 = self._NOT_CONTAINED
        return (wi2, wg, blipped, grayed, healed, sp2, st2, rs2, cs2, pt2,
                flaps2, gt2, viol2)

    def invariant(self, st_tuple):
        viol = st_tuple[-1]
        if viol == self._EVICT_LIVE:
            return ("live renewing member evicted on transient weather: "
                    "the first confirmed suspicion went straight to "
                    "eviction instead of the probation ladder")
        if viol == self._NOT_CONTAINED:
            return ("persistent one-way gray link never contained: the "
                    "victim's own report is clean on an asymmetric "
                    "partition — only third-party link evidence can "
                    "indict it, and the detector ignored it")
        if viol == self._FLAP:
            return ("suspicion flapped OK->probation 3 times on isolated "
                    "marginal spikes: without confirm/clear hysteresis a "
                    "slow-but-honest member oscillates in and out of "
                    "containment")
        return None


# =====================================================================
# registry + counterexample emission
# =====================================================================

MODELS: Dict[str, Callable[..., Model]] = {
    "ps": PSModel, "lease": LeaseModel, "mpmd": MpmdModel,
    "copt": CompressModel, "dpull": DeltaPullModel, "sched": SchedModel,
    "coordfail": CoordFailModel, "gray": GrayModel}

#: mutation name -> the model it breaks (the soundness corpus)
MUTATIONS: Dict[str, str] = {
    "ack_before_fsync": "ps",
    "no_dedup": "ps",
    "no_seed_on_restore": "ps",
    "no_incarnation_gate": "lease",
    "watermark_off_by_one": "mpmd",
    "no_mb_dedup": "mpmd",
    "no_error_feedback": "copt",
    "decode_before_admission": "copt",
    "stale_delta_base": "dpull",
    "no_full_fallback_on_restore": "dpull",
    "park_without_manifest": "sched",
    "double_grant_slot": "sched",
    "no_epoch_fence": "coordfail",
    "expire_on_restart": "coordfail",
    "forget_parked": "coordfail",
    "no_hysteresis": "gray",
    "symmetric_probe_only": "gray",
    "evict_on_first_suspicion": "gray",
}

#: per-model depth the `make distmodel` gate explores to (deep enough to
#: cover every mutation's counterexample; small enough to stay seconds)
DEFAULT_DEPTH = {"ps": 12, "lease": 10, "mpmd": 12, "copt": 12,
                 "dpull": 12, "sched": 12, "coordfail": 10, "gray": 9}


def _chaos_plan_for(result: Result) -> dict:
    """Derive a deterministic windowed :class:`ChaosPlan` from the trace's
    drop/dup events: each becomes a probability-1.0 rule windowed to the
    exact channel send index for data frames (so the fault fires on
    replay exactly where the model placed it) and to the per-worker ack
    ordinal for dropped acks (approximate — ack batching can merge
    frames). Crash/restart events ride the crash script."""
    from distributed_ml_pytorch_tpu.utils.chaos import (
        ChaosPlan,
        FaultRule,
        plan_to_json,
    )
    from distributed_ml_pytorch_tpu.utils.messaging import MessageCode

    rules = []
    sends_per_channel: Dict[Tuple[int, int], int] = {}
    frame_index: Dict[Tuple[int, int], int] = {}
    acks_dropped: Dict[int, int] = {}
    for ev in result.trace or []:
        kind = ev[0]
        if result.model == "ps":
            if kind in ("send", "retransmit"):
                # each model send/retransmit is one wire frame: it OWNS
                # the channel's next send index
                w = int(ev[1]) + 1  # worker ranks are 1..n, server is 0
                chan = (w, 0)
                i = sends_per_channel.get(chan, 0)
                sends_per_channel[chan] = i + 1
                frame_index[(int(ev[1]), int(ev[2]))] = i
            elif kind in ("drop", "dup"):
                # faults act on the frame's ORIGINAL transmission: the
                # FaultyTransport decides at send time, so the rule's
                # window is that send's channel index
                w = int(ev[1]) + 1
                i = frame_index.get((int(ev[1]), int(ev[2])), 0)
                rules.append(FaultRule(
                    src=w, dst=0, code=int(MessageCode.ReliableFrame),
                    **{kind: 1.0}, after=i, until=i + 1))
            elif kind == "drop_ack":
                # windowed to the i-th ack frame toward this worker —
                # approximate (the model does not track the server's ack
                # channel ordinals exactly; batching can merge acks) but
                # never a standing blackhole of the whole return channel.
                # The real-stack replay harnesses drive ack loss
                # imperatively instead of through these rules.
                w = int(ev[1]) + 1
                i = acks_dropped.get(w, 0)
                acks_dropped[w] = i + 1
                for ack_code in (MessageCode.CumAck,
                                 MessageCode.ReliableAck):
                    rules.append(FaultRule(
                        src=0, dst=w, code=int(ack_code), drop=1.0,
                        after=i, until=i + 1))
        elif result.model == "mpmd" and kind in ("dup",):
            rules.append(FaultRule(
                src=0, dst=1, code=int(MessageCode.ActivationShip),
                dup=1.0, after=int(ev[1]), until=int(ev[1]) + 1))
    gray_rules = []
    if result.model == "gray":
        from distributed_ml_pytorch_tpu.utils.chaos import GrayRule

        # the trace's weather events become scheduled GrayRules windowed
        # on the tick ordinal they struck at (suspect rank 1's outbound
        # channel toward reporter rank 2 — the drill topology convention):
        # a grayline is an unbounded one-way partition, a blip a two-tick
        # full-loss window, a spike a one-tick window
        ticks = 0
        for ev in result.trace or []:
            if ev[0] == "tick":
                ticks += 1
            elif ev[0] == "grayline":
                gray_rules.append(GrayRule(
                    kind="partition", src=1, dst=2, after=ticks))
            elif ev[0] == "blip":
                gray_rules.append(GrayRule(
                    kind="lossy", src=1, dst=2, p=1.0, after=ticks,
                    until=ticks + 2))
            elif ev[0] == "spike":
                gray_rules.append(GrayRule(
                    kind="lossy", src=1, dst=2, p=1.0, after=ticks,
                    until=ticks + 1))
    sdc_rules = []
    if result.model == "copt":
        from distributed_ml_pytorch_tpu.utils.chaos import SDCRule
        from distributed_ml_pytorch_tpu.utils.compress import HEAD_LEN

        for ev in result.trace or []:
            if ev[0] == "sdc":
                # scale the BODY (skip = the 12-float compressed head) by
                # a huge factor: decoded norm explodes, the frame stays
                # wire-perfect (chaos re-stamps body + envelope CRCs) —
                # only a gate on the DECODED norm can see it. Windowed to
                # the poisoned push's envelope seq on the worker->server
                # channel, exactly like the model's frame index.
                i = int(ev[1])
                sdc_rules.append(SDCRule(
                    src=1, dst=0, code=int(MessageCode.CompressedUpdate),
                    p=1.0, kind="scale", factor=1e30, skip=HEAD_LEN,
                    after=i, until=i + 1))
    return plan_to_json(ChaosPlan(rules=rules, seed=0, sdc=sdc_rules,
                                  gray=gray_rules))


_STUB_REAL = '''\
"""Auto-generated distmodel counterexample repro ({model}/{mutation}).

Replays the model-checker trace against the real ReliableTransport /
ParameterServer / WAL stack: FAILS with the mutated configuration,
passes on the correct one (delete once the defect is fixed)."""

import json
import os

from distributed_ml_pytorch_tpu.analysis import distmodel


def test_counterexample_replays(tmp_path):
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, {json_name!r})) as fh:
        ce = json.load(fh)
    violations = distmodel.replay_counterexample(
        ce, str(tmp_path), mutated=True)
    assert not violations, violations
'''

_STUB_MODEL = '''\
"""Auto-generated distmodel counterexample validity check
({model}/{mutation}).

This family has no real-stack replay harness — the model-level trace IS
the evidence. The test re-walks the recorded trace through the model's
transition relation and asserts it still reaches the recorded violation:
it fails only when the model rules changed and this artifact went stale
(regenerate with `distmodel --mutate {mutation} --out <dir>`)."""

import json
import os

from distributed_ml_pytorch_tpu.analysis import distmodel


def test_trace_still_reaches_the_violation():
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, {json_name!r})) as fh:
        ce = json.load(fh)
    violations = distmodel.replay_trace_on_model(ce)
    assert violations == [ce["invariant"]], violations
'''


def counterexample_artifact(result: Result) -> dict:
    """The JSON interchange form of one counterexample: model identity,
    violated invariant, the event trace, the derived chaos plan, and the
    crash script (crash/restart positions within the trace)."""
    assert not result.ok and result.trace is not None
    # ps/mpmd traces script crash/restart positions; sched/coordfail
    # traces script the control plane's own state transitions (the chaos
    # schedule a replay drives against the real coordinator)
    if result.model == "sched":
        ops = ("park", "resume", "grant", "release", "peak", "offpeak")
    elif result.model == "dpull":
        ops = ("push", "pull", "deliver", "drop_reply", "restore")
    elif result.model == "coordfail":
        ops = ("preempt", "grant", "crash", "partition", "zombie_bump",
               "rejoin", "resume", "regrant", "expire_blipped",
               "expire_parked")
    elif result.model == "gray":
        ops = ("blip", "spike", "grayline")
    else:
        ops = ("crash", "restart")
    script = [
        {"after_event": i, "op": ev[0],
         "rank": 0 if result.model == "ps" else 1}
        for i, ev in enumerate(result.trace)
        if ev[0] in ops]
    return {
        "model": result.model,
        "mutation": result.mutation,
        "invariant": result.invariant,
        "trace": [_fmt(e) for e in result.trace],
        "chaos_plan": _chaos_plan_for(result),
        "crash_script": script,
        "states_explored": result.states,
        "depth": result.depth,
    }


def write_counterexample(result: Result, out_dir: str) -> Tuple[str, str]:
    """Persist one counterexample as ``<model>_<mutation>.json`` plus a
    pytest repro stub; returns both paths. Families with a real-stack
    replay harness get the fails-while-the-defect-exists stub; the rest
    get a model-trace validity check (the trace is their evidence)."""
    os.makedirs(out_dir, exist_ok=True)
    tag = f"{result.model}_{result.mutation or 'unmutated'}"
    json_path = os.path.join(out_dir, f"{tag}.json")
    with open(json_path, "w") as fh:
        json.dump(counterexample_artifact(result), fh, indent=2,
                  sort_keys=True)
        fh.write("\n")
    stub = (_STUB_REAL if (result.model, result.mutation) in _REPLAYS
            else _STUB_MODEL)
    stub_path = os.path.join(out_dir, f"test_repro_{tag}.py")
    with open(stub_path, "w") as fh:
        fh.write(stub.format(model=result.model,
                             mutation=result.mutation,
                             json_name=os.path.basename(json_path)))
    return json_path, stub_path


def replay_trace_on_model(ce: dict) -> List[str]:
    """Deterministically re-walk a counterexample's recorded event trace
    through the (mutated) model's transition relation and return the
    violation the final state exhibits — the validity check behind the
    model-level repro stubs. An empty list means the trace is STALE: some
    recorded event is no longer enabled, or the final state no longer
    violates (the model rules changed; regenerate the artifact)."""
    model = MODELS[ce["model"]](mutation=ce.get("mutation"))
    state = model.initial()
    for rendered in ce.get("trace", []):
        for label, succ in model.successors(state):
            if _fmt(label) == rendered:
                state = succ
                break
        else:
            return []  # event no longer enabled here: stale artifact
    v = model.invariant(state)
    return [v] if v else []


# =====================================================================
# replay against the real stack
# =====================================================================

def _drain(rt, timeout: float = 0.5):
    """Pump one delivered message out of a ReliableTransport (bounded)."""
    return rt.recv(timeout=timeout)


def _mk_ps(tmp_path: str, transport, n: int = 4):
    import numpy as np

    from distributed_ml_pytorch_tpu.parallel.async_ps import ParameterServer

    return ParameterServer(params=np.zeros(n, np.float32),
                           transport=transport, ckpt_dir=tmp_path,
                           ckpt_every=0, wal=True)


def replay_counterexample(ce: dict, workdir: str,
                          mutated: bool = True) -> List[str]:
    """Drive the REAL transport/server stack through a counterexample's
    schedule. Returns the invariant violations observed (empty = the real
    stack upholds the invariant under this schedule).

    ``mutated=True`` reproduces the model's mutation with the real
    stack's own configuration surface (``ack_on_delivery`` for
    ack-before-fsync, an un-enveloped wire for dedup-key removal, a
    skipped ``seed_dedup`` for restore-without-seed); ``mutated=False``
    runs the correct configuration under the SAME schedule — the repro
    must fail mutated and pass clean.
    """
    handler = _REPLAYS.get((ce.get("model"), ce.get("mutation")))
    if handler is None:
        raise ValueError(
            f"no real-stack replay for {ce.get('model')}/"
            f"{ce.get('mutation')} — the model-level trace is the "
            "evidence for this family (replay_trace_on_model validates "
            "it)")
    return handler(ce, workdir, mutated)


def _sync_size(ps) -> int:
    """Bytes of the WAL that are fsync-durable right now (everything, when
    nothing is pending — append is an unbuffered write, so the in-process
    crash simulation must explicitly truncate the un-synced tail)."""
    ps.wal._f.flush()
    return os.path.getsize(ps.wal.path)


def _replay_ack_before_fsync(ce: dict, workdir: str,
                             mutated: bool) -> List[str]:
    """One worker pushes; the server applies + WAL-appends; the process
    dies BEFORE the group fsync (the un-synced tail is truncated away,
    as power loss would). Mutated (acks at delivery) the worker holds an
    ack for an update the restored server never saw."""
    import numpy as np

    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
        ReliableTransport,
    )

    world = InProcessTransport.create_world(2)
    srv = ReliableTransport(world[0], ack_on_delivery=mutated,
                            ack_timeout=0.05)
    # the worker's RTO is huge so the only retransmit in this schedule is
    # the explicit one below — a timer-driven retry slipping into the
    # mailbox pre-crash would nondeterministically heal the loss
    wrk = ReliableTransport(world[1], ack_timeout=5.0, max_backoff=10.0)
    ps = _mk_ps(workdir, srv)
    durable = _sync_size(ps)

    delta = np.ones(4, np.float32)
    wrk.send(MessageCode.GradientUpdate, delta, dst=0)
    msg = _drain(srv)
    assert msg is not None
    ps._envelope = srv.last_delivery
    ps.handle(msg[0], msg[1], msg[2])
    # let any at-delivery ack actually reach the worker BEFORE the crash
    # (mutated: the batched cum-ack flushes on the server's retry tick;
    # correct: the ack stays deferred behind the never-run group fsync,
    # so this bounded flush simply times out with nothing acked)
    wrk.flush(timeout=0.8)
    got_ack = wrk.acked_count(0, MessageCode.GradientUpdate) > 0
    # CRASH before ps.commit(): power loss drops the un-fsync'd WAL tail
    os.truncate(ps.wal.path, durable)
    srv.detach()
    while world[0].recv(timeout=0.05) is not None:
        pass  # discard any stray frames addressed to the dead life

    srv2 = ReliableTransport(world[0].attach_rank(0),
                             ack_on_delivery=mutated, ack_timeout=0.05)
    ps2 = _mk_ps(workdir, srv2)
    ps2.maybe_restore()
    # the sender's retry heals an UNacked loss — and an acked sender has
    # nothing pending, so nothing arrives and the loss is permanent
    with wrk._lock:
        pend = list(wrk._pending.values())
    for p in pend:
        wrk.inner.sendv(MessageCode.ReliableFrame, p.parts, dst=p.dst)
    deadline, idle = 20, 0
    while deadline > 0 and idle < 3:
        msg = _drain(srv2, timeout=0.1)
        if msg is None:
            idle += 1
            deadline -= 1
            continue
        idle = 0
        ps2._envelope = srv2.last_delivery
        ps2.handle(msg[0], msg[1], msg[2])
        ps2.commit()
        deadline -= 1
    violations = []
    if got_ack and ps2._apply_seq < 1:
        violations.append(
            "acked => applied violated: the worker holds an ack but the "
            "restored server lost the update (ack released before the "
            "group fsync)")
    srv2.detach()
    wrk.detach()
    for t in world.values():
        t.close()
    return violations


def _replay_no_dedup(ce: dict, workdir: str, mutated: bool) -> List[str]:
    """The counterexample's dup fires on the wire. Mutated = the dedup
    key is removed by sending OUTSIDE the reliability envelope (no seq,
    no dedup — exactly what the schema's dedup_key declares away);
    correct = the enveloped wire under the SAME plan applies once."""
    import numpy as np

    from distributed_ml_pytorch_tpu.utils.chaos import plan_from_json
    from distributed_ml_pytorch_tpu.utils.messaging import (
        MessageCode,
        make_world,
    )

    plan = plan_from_json(ce["chaos_plan"])
    if mutated:
        # dedup removed: raw chaos world, no envelope — dup rules must
        # target the bare GradientUpdate frames instead of envelopes
        from distributed_ml_pytorch_tpu.utils.chaos import (
            ChaosPlan,
            FaultRule,
        )

        rules = tuple(dataclasses.replace(
            r, code=int(MessageCode.GradientUpdate))
            for r in plan.rules if r.dup)
        world, _log = make_world(2, plan=ChaosPlan(rules=rules))
    else:
        world, _log = make_world(
            2, plan=plan, reliable=True,
            reliable_opts={"ack_timeout": 0.05})
    ps = _mk_ps(workdir, world[0])
    delta = np.ones(4, np.float32)
    world[1].send(MessageCode.GradientUpdate, delta, dst=0)
    deadline, idle = 30, 0
    while deadline > 0 and idle < 3:
        msg = world[0].recv(timeout=0.1)
        if msg is None:
            idle += 1 if ps._apply_seq >= 1 else 0
            deadline -= 1
            continue
        idle = 0
        ps._envelope = getattr(world[0], "last_delivery", None)
        ps.handle(msg[0], msg[1], msg[2])
        ps.commit()
        deadline -= 1
    violations = []
    if ps._apply_seq != 1:
        violations.append(
            f"exactly-once violated: one logical GradientUpdate applied "
            f"{ps._apply_seq} time(s) under a duplicating wire")
    for t in world.values():
        t.close()
    return violations


def _replay_no_seed_on_restore(ce: dict, workdir: str,
                               mutated: bool) -> List[str]:
    """Applied + fsync'd + ack LOST + server restart + sender retry: the
    restored server must re-seed dedup from the WAL's envelope identities
    (``seed_dedup``), or the retry re-applies an applied update."""
    import numpy as np

    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
        ReliableTransport,
    )

    world = InProcessTransport.create_world(2)
    srv = ReliableTransport(world[0], ack_on_delivery=False,
                            ack_timeout=0.05)
    # the worker's acks are blackholed: give its frames a huge RTO so the
    # deterministic retry below is OURS, not the timer's
    wrk = ReliableTransport(world[1], ack_timeout=5.0, max_backoff=10.0)
    ps = _mk_ps(workdir, srv)
    delta = np.ones(4, np.float32)
    wrk.send(MessageCode.GradientUpdate, delta, dst=0)
    msg = _drain(srv)
    assert msg is not None
    ps._envelope = srv.last_delivery
    ps.handle(msg[0], msg[1], msg[2])
    ps.commit()  # fsync'd + ack released...
    # ...but the ack frame dies with the old server life: drain it away
    # from the worker's inbox path by detaching before the worker pumps
    srv.detach()
    while world[1].recv(timeout=0.05) is not None:
        pass  # discard the in-flight ack (the counterexample's drop_ack)

    srv2 = ReliableTransport(world[0].attach_rank(0), ack_on_delivery=False,
                             ack_timeout=0.05)
    ps2 = _mk_ps(workdir, srv2)
    if mutated:
        srv2.seed_dedup = lambda entries: None  # the mutation: no re-seed
    ps2.maybe_restore()
    # the sender's retry of the applied-but-unacked frame
    with wrk._lock:
        pend = list(wrk._pending.values())
    for p in pend:
        wrk.inner.sendv(MessageCode.ReliableFrame, p.parts, dst=p.dst)
    deadline, idle = 20, 0
    while deadline > 0 and idle < 3:
        msg = _drain(srv2, timeout=0.1)
        if msg is None:
            idle += 1
            deadline -= 1
            continue
        idle = 0
        ps2._envelope = srv2.last_delivery
        ps2.handle(msg[0], msg[1], msg[2])
        ps2.commit()
        deadline -= 1
    violations = []
    if ps2._apply_seq != 1:
        violations.append(
            f"exactly-once violated across restart: apply seq is "
            f"{ps2._apply_seq}, the retry of an applied-but-unacked "
            "frame was re-applied (dedup not re-seeded from the WAL)")
    srv2.detach()
    wrk.detach()
    for t in world.values():
        t.close()
    return violations


def _replay_no_error_feedback(ce: dict, workdir: str,
                              mutated: bool) -> List[str]:
    """The compressed-push stack end to end: a worker pushes the SAME
    update 8 times through a top-1 sparsifier over the reliability
    envelope into a WAL'd server. With error feedback the exact identity
    ``sum(decoded) == sum(raw) - residual`` bounds the drift by one
    residual (<= 12 per coordinate here, by construction); mutated
    (residual dropped) only the single largest coordinate ever ships and
    the others drift by the full 8-push sum (32) — the model's
    quiescent-error-bound violation on the real wire."""
    import numpy as np

    from distributed_ml_pytorch_tpu.utils.compress import (
        CompressingEncoder,
        make_codec,
    )
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
        ReliableTransport,
    )

    world = InProcessTransport.create_world(2)
    srv = ReliableTransport(world[0], ack_on_delivery=False,
                            ack_timeout=0.05)
    wrk = ReliableTransport(world[1], ack_timeout=5.0, max_backoff=10.0)
    ps = _mk_ps(workdir, srv)
    enc = CompressingEncoder(4, make_codec("topk", k_frac=0.25),
                             error_feedback=not mutated)
    u = np.asarray([8.0, 4.0, 2.0, 1.0], np.float32)
    n_push = 8
    for _ in range(n_push):
        head, body = enc.encode_range(u, 0, 4)
        wrk.sendv(MessageCode.CompressedUpdate, (head, body), dst=0)
        msg = _drain(srv)
        assert msg is not None
        ps._envelope = srv.last_delivery
        ps.handle(msg[0], msg[1], msg[2])
        ps.commit()
    true_total = n_push * u
    drift = float(np.max(np.abs(true_total - ps.central)))
    violations = []
    if drift > 12.0:
        violations.append(
            f"error-feedback bound violated on the real stack: applied "
            f"sum drifts {drift:.0f} from the raw sum after {n_push} "
            "compressed pushes (quantization error dropped, not deferred)")
    srv.detach()
    wrk.detach()
    for t in world.values():
        t.close()
    return violations


def _replay_decode_before_admission(ce: dict, workdir: str,
                                    mutated: bool) -> List[str]:
    """The counterexample's SDC schedule against the real compressed-push
    stack: chaos silently scales one push's compressed BODY by 1e30
    (body + envelope CRCs re-stamped — bit-perfect on the wire), after
    enough clean pushes to warm the gate's per-worker statistics. Correct
    config: the server DECODES first, the z-score on the decoded norm
    quarantines the poison, the central vector stays sane. Mutated (the
    gate never sees compressed traffic — the forgotten-gate bug the
    schema's decoded-norm contract exists to prevent): the poison
    applies and the central norm explodes."""
    import numpy as np

    from distributed_ml_pytorch_tpu.utils.chaos import (
        FaultyTransport,
        plan_from_json,
    )
    from distributed_ml_pytorch_tpu.utils.compress import (
        CompressingEncoder,
        make_codec,
    )
    from distributed_ml_pytorch_tpu.utils.health import GradientAdmission
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
        ReliableTransport,
    )

    plan = plan_from_json(ce["chaos_plan"])
    poison_at = max((r.after for r in plan.sdc), default=2)
    world = InProcessTransport.create_world(2)
    chaos, _log = FaultyTransport.wrap_world(world, plan)
    srv = ReliableTransport(chaos[0], ack_timeout=0.05)
    wrk = ReliableTransport(chaos[1], ack_timeout=5.0, max_backoff=10.0)
    gate = GradientAdmission(z_max=6.0, warmup=2)
    ps = _mk_ps(workdir, srv)
    ps.admission = None if mutated else gate
    enc = CompressingEncoder(4, make_codec("int8", block=4))
    rng = np.random.default_rng(7)
    for _i in range(poison_at + 2):
        u = rng.normal(scale=1.0, size=4).astype(np.float32)
        head, body = enc.encode_range(u, 0, 4)
        wrk.sendv(MessageCode.CompressedUpdate, (head, body), dst=0)
        msg = _drain(srv)
        assert msg is not None
        ps._envelope = srv.last_delivery
        ps.handle(msg[0], msg[1], msg[2])
        ps.commit()
    violations = []
    central_norm = float(np.linalg.norm(
        ps.central.astype(np.float64)))
    if not np.isfinite(ps.central).all() or central_norm > 1e6:
        violations.append(
            f"poisoned decoded update admitted: central norm "
            f"{central_norm:.3g} after the SDC push — the gate never saw "
            "the decoded norm")
    if not mutated and ps.quarantined < 1:
        violations.append(
            "clean config did not quarantine the SDC push — the decoded-"
            "norm gate is not wired where the schema promises")
    srv.detach()
    wrk.detach()
    for t in world.values():
        t.close()
    return violations


def _replay_park_without_manifest(ce: dict, workdir: str,
                                  mutated: bool) -> List[str]:
    """The counterexample's park-then-resume schedule against the FULL
    real stack: ``coord.drill.sched_drill`` runs coordinator + scheduler
    + WAL'd elastic shards + DownPour workers through the model's event
    sequence (peak -> park -> grant -> offpeak -> release -> resume).
    Correct config (``require_manifest=True``): the preempt first drives
    a snapshot barrier, the resume restores checkpoint + WAL replay
    bit-for-bit — no violations. Mutated (the guard dropped): the member
    parks without any manifest and the resume finds nothing to restore —
    the model's lost-acked-state violation on the real coordinator."""
    from distributed_ml_pytorch_tpu.coord.drill import (
        default_drill_plan,
        sched_drill,
    )

    out = sched_drill(base_dir=workdir, seed=0,
                      plan=default_drill_plan(0),
                      require_manifest=not mutated)
    violations = list(out["violations"])
    if not mutated and out["sched"]["preempts_done"] < 1:
        violations.append(
            "clean config never parked the victim — the preempt path is "
            "not wired where the schedule expects")
    return violations


def _replay_double_grant_slot(ce: dict, workdir: str,
                              mutated: bool) -> List[str]:
    """The model's grant-before-park-completes schedule against the real
    scheduler + coordinator, driven synchronously with a fake clock (the
    coordinator's handle()/tick() test surface). Two shard members join;
    the serving tenant's demand spikes. Correct config: the ledger's
    exclusivity gate defers the grant until the victim's PreemptDone
    frees the slot, so ``audit()`` stays clean. Mutated
    (``enforce_exclusive=False``): the grant fires immediately over the
    still-held slot and the ledger audit reports the two-owner state."""
    from distributed_ml_pytorch_tpu.coord.coordinator import (
        KIND_SHARD,
        Coordinator,
        encode_join,
    )
    from distributed_ml_pytorch_tpu.coord.sched import FleetScheduler
    from distributed_ml_pytorch_tpu.coord.tenants import (
        TENANT_SERVING,
        Tenant,
        TenantRegistry,
    )
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
    )

    fake_now = [0.0]
    world = InProcessTransport.create_world(3)
    coord = Coordinator(world[0], 8, lease=60.0, speculation=False,
                        clock=lambda: fake_now[0])
    registry = TenantRegistry()
    registry.register(Tenant(1, "train", priority=1, demand=2, min_slots=1))
    registry.register(Tenant(2, "serve", kind=TENANT_SERVING, priority=5,
                             demand=0))
    sched = FleetScheduler(coord, registry=registry, require_manifest=True,
                           enforce_exclusive=not mutated)
    for rank in (1, 2):
        coord.handle(rank, MessageCode.CoordJoin,
                     encode_join(KIND_SHARD, rank))
        sched.register_member_slot(rank, 1)
    # replay the schedule: peak, then the scheduler's own pack passes
    # (the grant either defers on the exclusivity gate or fires over the
    # still-held slot — no PreemptDone ever arrives in this harness, so
    # a premature grant can ONLY come from the dropped gate)
    registry.set_demand(2, 1)
    for _ in range(3):
        fake_now[0] += 1.0
        sched.tick(fake_now[0])
    violations = list(sched.ledger.audit())
    if not mutated and any(
            2 in s.owners for s in sched.ledger.slots.values()):
        violations.append(
            "clean config granted a held slot before the victim parked — "
            "the exclusivity gate is not wired where the ledger promises")
    for t in world.values():
        t.close()
    return violations


def _replay_no_epoch_fence(ce: dict, workdir: str,
                           mutated: bool) -> List[str]:
    """The zombie-coordinator schedule against the real ``CoordClient``:
    a successor (epoch 2) ships its map, then a partitioned pre-crash
    coordinator's diverged high-version map arrives stamped epoch 1.
    Correct config: the client's epoch fence drops the zombie frame and
    the successor's next map still lands. Mutated (``epoch_fence=False``):
    the zombie map is adopted — and the version gate then locks the
    member onto a dead coordinator's topology forever."""
    from distributed_ml_pytorch_tpu.coord.member import CoordClient
    from distributed_ml_pytorch_tpu.coord.shardmap import (
        ShardEntry,
        ShardMap,
    )
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
        stamp_epoch,
    )

    world = InProcessTransport.create_world(2)
    client = CoordClient(world[1], "shard", renew_interval=30.0,
                         epoch_fence=not mutated)

    def frame(version, epoch):
        m = ShardMap(version, 8, [ShardEntry(1, 0, 8)])
        return stamp_epoch(m.encode(), epoch)

    violations = []
    try:
        client._handle(MessageCode.ShardMapUpdate, frame(3, 2))
        client._handle(MessageCode.ShardMapUpdate, frame(9, 1))  # zombie
        if client.current_map().version == 9:
            violations.append(
                "stale-epoch command adopted on the real client: the "
                "zombie coordinator's map v9 (epoch 1) displaced the "
                "successor's v3 (epoch 2)")
        client._handle(MessageCode.ShardMapUpdate, frame(4, 2))
        if client.current_map().version not in (4, 9):
            violations.append(
                "the successor's follow-up map was refused: the member "
                f"is wedged on v{client.current_map().version}")
        if not mutated and client.stale_epoch_dropped < 1:
            violations.append(
                "clean config never fenced the zombie frame — the epoch "
                "fence is not wired where the schema promises")
    finally:
        client.stop()
        for t in world.values():
            t.close()
    return violations


def _replay_expire_on_restart(ce: dict, workdir: str,
                              mutated: bool) -> List[str]:
    """The restart-blip schedule against the real durable coordinator: a
    life-1 coordinator admits two shard members and dies; its successor
    restores them from ckpt+WAL and the clock jumps past every lease
    before any join-retry arrives. Correct config: the grace window
    suspends expiry, the members rejoin and survive. Mutated
    (``grace=0``): the successor mass-evicts the restored fleet."""
    from distributed_ml_pytorch_tpu.coord.coordinator import (
        KIND_SHARD,
        Coordinator,
        encode_join,
    )
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
    )

    fake_now = [0.0]
    world = InProcessTransport.create_world(4)
    violations = []
    try:
        coord = Coordinator(world[0], 8, lease=2.0, speculation=False,
                            clock=lambda: fake_now[0], durable_dir=workdir)
        for rank in (1, 2):
            coord.handle(rank, MessageCode.CoordJoin,
                         encode_join(KIND_SHARD, rank))
        # the crash; the successor restores and the blip outlives the lease
        coord2 = Coordinator(world[0], 8, lease=2.0, speculation=False,
                             clock=lambda: fake_now[0], durable_dir=workdir,
                             grace=0.0 if mutated else 30.0)
        fake_now[0] = 3.0  # one lease past the restore, nobody rejoined yet
        coord2.tick()
        evicted = {1, 2} - set(coord2.members)
        if evicted:
            violations.append(
                f"restored member(s) {sorted(evicted)} evicted during "
                "the control-plane blip: lease expiry was not suspended "
                "for the re-attach grace window")
        # the join-retry traffic arrives; survivors must re-attach cleanly
        for rank in (1, 2):
            coord2.handle(rank, MessageCode.CoordJoin,
                          encode_join(KIND_SHARD, rank))
        if not mutated and set(coord2.members) != {1, 2}:
            violations.append(
                "clean config did not re-admit the fleet after the blip")
    finally:
        for t in world.values():
            t.close()
    return violations


def _replay_forget_parked(ce: dict, workdir: str,
                          mutated: bool) -> List[str]:
    """The crash-mid-preemption schedule against the real coordinator +
    scheduler: a serving-demand spike parks a live training member
    (PreemptDone lands, the park ticket is WAL'd), then the coordinator
    dies. Correct config: the successor replays the durable park table —
    the victim stays lease-exempt and its slot restores as PARKED with a
    clean audit. Mutated (``restore_parked=False``): the ticket is
    forgotten, lease expiry re-arms on the parked member and it is
    evicted — the strand-forever bug."""
    from distributed_ml_pytorch_tpu.coord.coordinator import (
        KIND_SHARD,
        Coordinator,
        encode_join,
        encode_preempt_done,
    )
    from distributed_ml_pytorch_tpu.coord.sched import FleetScheduler
    from distributed_ml_pytorch_tpu.coord.tenants import (
        TENANT_SERVING,
        Tenant,
        TenantRegistry,
    )
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
    )

    def registry():
        reg = TenantRegistry()
        reg.register(Tenant(1, "train", priority=1, demand=2, min_slots=1))
        reg.register(Tenant(2, "serve", kind=TENANT_SERVING, priority=5,
                            demand=0))
        return reg

    fake_now = [0.0]
    world = InProcessTransport.create_world(4)
    violations = []
    try:
        coord = Coordinator(world[0], 8, lease=2.0, speculation=False,
                            clock=lambda: fake_now[0], durable_dir=workdir)
        sched = FleetScheduler(coord, registry=registry(),
                               require_manifest=False)
        for rank in (1, 2):
            coord.handle(rank, MessageCode.CoordJoin,
                         encode_join(KIND_SHARD, rank))
            sched.register_member_slot(rank, 1)
        sched.registry.set_demand(2, 1)
        sched.tick(fake_now[0])  # the demand spike: PreemptRequest out
        pending = sched._pending
        assert pending is not None, "the preempt never started"
        victim = pending["slot"].rank
        coord.handle(victim, MessageCode.PreemptDone,
                     encode_preempt_done(pending["grant_id"], 0, 4, 8, 17))
        coord.tick()  # the periodic checkpoint covers the ledger state
        # the coordinator dies mid-preemption; a successor restores
        coord2 = Coordinator(world[0], 8, lease=2.0, speculation=False,
                             clock=lambda: fake_now[0], durable_dir=workdir,
                             restore_parked=not mutated)
        sched2 = FleetScheduler(coord2, registry=registry(),
                                require_manifest=False)
        coord2.handle(1, MessageCode.CoordJoin, encode_join(KIND_SHARD, 1))
        fake_now[0] = 50.0  # past every lease AND the grace window
        coord2.tick()
        if victim not in coord2.members:
            violations.append(
                f"parked member {victim} stranded: the successor forgot "
                "the durable park table and lease expiry evicted it")
        if not mutated:
            # the restore ticket must survive the restart (the slot may
            # already be RESUMING — off-peak, the successor legitimately
            # starts the resume — but the ticket itself is the evidence)
            ticketed = [s for s in sched2.ledger.slots.values()
                        if s.parked is not None
                        and s.parked["rank"] == victim]
            if len(ticketed) != 1:
                violations.append(
                    "clean config lost the park ticket across restart — "
                    "no slot still carries the victim's restore ticket")
            if sched2.ledger.audit():
                violations.extend(sched2.ledger.audit())
    finally:
        for t in world.values():
            t.close()
    return violations


def _replay_stale_delta_base(ce: dict, workdir: str,
                             mutated: bool) -> List[str]:
    """The dpull stale-base schedule against the real ``ParameterServer``
    / ``Listener`` delta-reply plane: a worker full-syncs, then a delta
    reply is LOST while the server's tracked base advances past it, then
    the worker pulls again with its (now stale) held stamp. Mutated —
    ``_delta_check_held`` off and a blindly-trusting worker — the server
    ships a delta against the advanced base and the worker's view
    diverges from central; clean, the held-stamp miss forces a full
    dense install and the views stay bitwise identical."""
    import numpy as np

    from distributed_ml_pytorch_tpu.parallel.async_ps import Listener
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
    )

    world = InProcessTransport.create_world(2)
    try:
        ps = _mk_ps(workdir, world[0])
        lst = Listener(transport=world[1])  # receive() driven inline
        if mutated:
            ps._delta_check_held = False
            lst.delta_trust = True

        def pull(deliver: bool = True):
            ps.handle(1, MessageCode.ParameterRequest, lst.held_stamp())
            msg = world[1].recv(timeout=0.5)
            if msg is not None and deliver:
                lst.receive(msg[0], msg[1], msg[2])
            return msg

        pull()  # first pull: full dense install seeds the worker's view
        ps.handle(1, MessageCode.GradientUpdate, np.ones(4, np.float32))
        ps.commit()
        # this pull's (delta) reply is LOST in flight — but the server's
        # tracked base has ALREADY advanced to the view it never shipped
        pull(deliver=False)
        ps.handle(1, MessageCode.GradientUpdate,
                  np.full(4, 2.0, np.float32))
        ps.commit()
        pull()  # stale held stamp: clean full-falls-back, mutated deltas
        violations = []
        if lst._view is None or not np.array_equal(lst._view, ps.central):
            violations.append(
                "delta-reply divergence: the server shipped a delta "
                "against a base the worker never pulled and the worker's "
                "view no longer matches central")
        if not mutated:
            if lst.full_installs < 2:
                violations.append(
                    "clean config never took the full fallback — the "
                    "held-stamp check is not wired")
            if ps.delta_replies < 1:
                violations.append(
                    "clean config never shipped a delta — the delta "
                    "plane is not wired")
    finally:
        for t in world.values():
            t.close()
    return violations


def _replay_no_full_fallback_on_restore(ce: dict, workdir: str,
                                        mutated: bool) -> List[str]:
    """The dpull zombie-across-restore schedule against the real stack: a
    delta reply is cut just before a crash that loses the un-fsynced WAL
    tail; the restored server re-fills the SAME version number with
    DIFFERENT bytes; the zombie reply then lands. Clean, the restore
    bumps the pull epoch so the worker's resulting stamp can never match
    the new life's; mutated (``_delta_reset_on_restore`` off) the stamps
    collide and the worker claims the current version with stale bytes."""
    import numpy as np

    from distributed_ml_pytorch_tpu.parallel.async_ps import Listener
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
    )

    world = InProcessTransport.create_world(2)
    try:
        ps = _mk_ps(workdir, world[0])
        lst = Listener(transport=world[1])
        # life 0: one durable push, then a full install at its version
        ps.handle(1, MessageCode.GradientUpdate, np.ones(4, np.float32))
        ps.commit()
        durable = _sync_size(ps)
        ps.handle(1, MessageCode.ParameterRequest, lst.held_stamp())
        msg = world[1].recv(timeout=0.5)
        assert msg is not None
        lst.receive(msg[0], msg[1], msg[2])  # worker holds (epoch 0, v1)
        # an un-fsynced push, and the delta reply cut from it — the reply
        # is DELAYED in flight (the zombie)
        ps.handle(1, MessageCode.GradientUpdate, np.ones(4, np.float32))
        ps.handle(1, MessageCode.ParameterRequest, lst.held_stamp())
        zombie = world[1].recv(timeout=0.5)
        # CRASH before the covering fsync: power loss drops the tail push
        os.truncate(ps.wal.path, durable)

        ps2 = _mk_ps(workdir, world[0])
        if mutated:
            ps2._delta_reset_on_restore = False
        ps2.maybe_restore()  # back to v1; the FENCE is the epoch bump
        # life 1 re-fills version number 2 with different bytes
        ps2.handle(1, MessageCode.GradientUpdate,
                   np.full(4, 5.0, np.float32))
        ps2.commit()
        if zombie is not None:
            lst.receive(zombie[0], zombie[1], zombie[2])
        violations = []
        if lst._held == (ps2._pull_epoch, ps2._apply_seq) \
                and not np.array_equal(lst._view, ps2.central):
            violations.append(
                "zombie delta reply crossed the restore: the worker "
                "claims the server's current (epoch, version) while "
                "holding the dead life's bytes")
        if not mutated:
            if ps2._pull_epoch < 1:
                violations.append(
                    "clean config did not bump the pull epoch on restore "
                    "— the fence is not wired")
            # the worker's stale-epoch stamp must heal via full fallback
            ps2.handle(1, MessageCode.ParameterRequest, lst.held_stamp())
            msg = world[1].recv(timeout=0.5)
            if msg is not None:
                lst.receive(msg[0], msg[1], msg[2])
            if lst._view is None \
                    or not np.array_equal(lst._view, ps2.central):
                violations.append(
                    "clean config's post-restore pull did not full-sync "
                    "the worker bitwise")
    finally:
        for t in world.values():
            t.close()
    return violations


def _gray_rig(workdir, mutated_knobs, ranks=(1, 2)):
    """A real Coordinator + GrayHealth under a fake clock: the gray
    replay harnesses drive LeaseRenew frames (with gray-health tails)
    through ``Coordinator.handle`` and the suspicion ladder through
    ``Coordinator.tick`` — the same dispatch the live serve thread runs.
    ``raise_threshold=2.5`` (not the 3.0 default) keeps the harnesses off
    a knife edge: the FIRST anomalous sample after a calm-trained
    baseline lands at z = sqrt((1-alpha)/alpha) = 3.0 exactly (the
    EW-update identity), so discriminating at 3.0 would hang the verdict
    on float rounding."""
    from distributed_ml_pytorch_tpu.coord.coordinator import (
        KIND_SHARD,
        Coordinator,
        encode_join,
    )
    from distributed_ml_pytorch_tpu.coord.grayhealth import GrayHealth
    from distributed_ml_pytorch_tpu.utils.messaging import (
        InProcessTransport,
        MessageCode,
    )

    fake_now = [0.0]
    world = InProcessTransport.create_world(5)
    coord = Coordinator(world[0], 8, lease=8.0, speculation=False,
                        clock=lambda: fake_now[0], durable_dir=workdir)
    gray = GrayHealth(coord, raise_threshold=2.5, confirm_ticks=2,
                      clear_ticks=2, **mutated_knobs)
    for rank in ranks:
        coord.handle(rank, MessageCode.CoordJoin,
                     encode_join(KIND_SHARD, 0))
    return world, coord, gray, fake_now


def _gray_renew(coord, rank, retrans=0.01, links=()):
    from distributed_ml_pytorch_tpu.coord.coordinator import encode_renew
    from distributed_ml_pytorch_tpu.utils.messaging import MessageCode

    coord.handle(rank, MessageCode.LeaseRenew,
                 encode_renew(0, retrans_rate=retrans, links=links))


def _replay_evict_on_first_suspicion(ce: dict, workdir: str,
                                     mutated: bool) -> List[str]:
    """The transient-burst schedule against the real coordinator + gray
    plane: a shard member renews every 0.25s throughout; after the
    baseline warm-up its reported retransmit rate spikes for exactly two
    windows (the model's ``blip``), then calms. Clean: two confirmed
    anomalous ticks put it on PROBATION, the hysteresis clears it back to
    OK when the weather passes — nobody dies. Mutated
    (``evict_on_first_suspicion=True``): the first confirmed suspicion
    revokes its lease while it is still renewing — a live member killed
    by weather that ended one window later."""
    from distributed_ml_pytorch_tpu.coord.grayhealth import OK, PROBATION

    world, coord, gray, fake_now = _gray_rig(
        workdir, {"evict_on_first_suspicion": mutated})
    violations = []
    try:
        def rnd(retrans):
            fake_now[0] += 0.25
            _gray_renew(coord, 1, retrans=retrans)
            _gray_renew(coord, 2)
            coord.tick()

        for _ in range(10):
            rnd(0.01)       # calm: trains the adaptive baseline
        for _ in range(2):
            rnd(2.0)        # the two-window transient burst
        if 1 not in coord.members:
            violations.append(
                "live renewing member evicted on transient weather: rank "
                "1 renewed every 0.25s yet its lease was revoked at the "
                "first confirmed suspicion")
        if not mutated:
            if gray.state_of(1) != PROBATION:
                violations.append(
                    "clean config did not reach probation on the "
                    "confirmed burst — detection is not wired")
            for _ in range(6):
                rnd(0.01)   # weather passed: the ladder must unwind
            if gray.state_of(1) != OK or 1 not in coord.members:
                violations.append(
                    "clean config did not clear back to OK after the "
                    "transient weather passed")
    finally:
        for t in world.values():
            t.close()
    return violations


def _replay_symmetric_probe_only(ce: dict, workdir: str,
                                 mutated: bool) -> List[str]:
    """The one-way-partition schedule against the real coordinator + gray
    plane: the suspect's OWN renewals stay clean the whole time (an
    asymmetric partition's victim cannot see its outbound loss) while two
    reporters' renew tails carry per-link evidence naming it. Clean
    (asymmetric detection on): the third-party indictments put the
    suspect on PROBATION — contained, still a member. Mutated
    (``asymmetric=False``): link evidence is ignored and the gray link
    runs forever undetected."""
    from distributed_ml_pytorch_tpu.coord.grayhealth import OK, PROBATION

    world, coord, gray, fake_now = _gray_rig(
        workdir, {"asymmetric": not mutated}, ranks=(1, 2, 3))
    violations = []
    try:
        def rnd(link_rate):
            fake_now[0] += 0.25
            _gray_renew(coord, 1)   # the victim reports clean, always
            for rank in (2, 3):
                _gray_renew(coord, rank, links=((1, link_rate, 0.0),))
            coord.tick()

        for _ in range(10):
            rnd(0.01)       # link baselines warm on calm reports
        for _ in range(4):
            rnd(1.0)        # the persistent one-way loss, both reporters
        if gray.state_of(1) == OK:
            violations.append(
                "persistent one-way gray link never contained: two "
                "reporters named rank 1 for four windows and the "
                "detector never left OK")
        if not mutated:
            if gray.state_of(1) != PROBATION:
                violations.append(
                    "clean config did not put the one-way partition's "
                    "victim on probation")
            if 1 not in coord.members:
                violations.append(
                    "clean config killed the suspect instead of "
                    "containing it — probation must degrade, not evict")
    finally:
        for t in world.values():
            t.close()
    return violations


def _replay_no_hysteresis(ce: dict, workdir: str,
                          mutated: bool) -> List[str]:
    """The marginal-weather schedule against the real coordinator + gray
    plane: a slow-but-honest member usually renews every 0.25s but is
    occasionally LATE (isolated 2s gaps — the model's ``spike``), each
    late window followed by prompt renewals. The phi-accrual gap score
    spikes for exactly one evaluation per episode. Clean: one marginal
    tick never meets ``confirm_ticks=2``, so the member never flaps.
    Mutated (``hysteresis=False``): every episode flaps it
    OK->probation->OK — containment churn on a member that was never
    gray."""
    from distributed_ml_pytorch_tpu.coord.grayhealth import OK

    world, coord, gray, fake_now = _gray_rig(
        workdir, {"hysteresis": not mutated})
    violations = []
    try:
        def prompt():
            fake_now[0] += 0.25
            _gray_renew(coord, 1)
            _gray_renew(coord, 2)
            coord.tick()

        for _ in range(24):
            prompt()        # a deep on-time arrival history
        for _ in range(4):  # four isolated late-renewal episodes
            fake_now[0] += 2.0
            coord.tick()    # the one marginal evaluation mid-gap
            _gray_renew(coord, 1)   # the renewal lands — late, but lands
            _gray_renew(coord, 2)
            prompt()        # and the next window is clean again
        if gray.flaps_of(1) >= 3:
            violations.append(
                f"suspicion flapped OK->probation {gray.flaps_of(1)} "
                "times on isolated late renewals: no confirm/clear "
                "hysteresis, so every marginal evaluation churns the "
                "containment ladder")
        if not mutated:
            if gray.flaps_of(1) != 0 or gray.state_of(1) != OK:
                violations.append(
                    "clean config flapped on marginal weather — the "
                    "hysteresis streaks are not wired")
            if 1 not in coord.members:
                violations.append("clean config lost the member entirely")
    finally:
        for t in world.values():
            t.close()
    return violations


_REPLAYS = {
    ("ps", "ack_before_fsync"): _replay_ack_before_fsync,
    ("ps", "no_dedup"): _replay_no_dedup,
    ("ps", "no_seed_on_restore"): _replay_no_seed_on_restore,
    ("copt", "no_error_feedback"): _replay_no_error_feedback,
    ("copt", "decode_before_admission"): _replay_decode_before_admission,
    ("dpull", "stale_delta_base"): _replay_stale_delta_base,
    ("dpull", "no_full_fallback_on_restore"):
        _replay_no_full_fallback_on_restore,
    ("sched", "park_without_manifest"): _replay_park_without_manifest,
    ("sched", "double_grant_slot"): _replay_double_grant_slot,
    ("coordfail", "no_epoch_fence"): _replay_no_epoch_fence,
    ("coordfail", "expire_on_restart"): _replay_expire_on_restart,
    ("coordfail", "forget_parked"): _replay_forget_parked,
    ("gray", "no_hysteresis"): _replay_no_hysteresis,
    ("gray", "symmetric_probe_only"): _replay_symmetric_probe_only,
    ("gray", "evict_on_first_suspicion"):
        _replay_evict_on_first_suspicion,
}


# =====================================================================
# CLI
# =====================================================================

def run(models: Optional[List[str]] = None, depth: Optional[int] = None,
        mutation: Optional[str] = None,
        max_states: int = 400_000) -> List[Result]:
    """Programmatic entry: explore the named models (default: all), with
    an optional mutation applied to ITS model."""
    names = models or sorted(MODELS)
    results = []
    for name in names:
        mut = mutation if mutation and MUTATIONS.get(mutation) == name \
            else None
        model = MODELS[name](mutation=mut)
        d = depth if depth is not None else DEFAULT_DEPTH[name]
        results.append(explore(model, max_depth=d, max_states=max_states))
    return results


def main(argv=None) -> int:
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="distmodel",
        description="bounded explicit-state model checking of the "
                    "extracted wire protocol (exactly-once / lease / "
                    "watermark-replay invariants)")
    parser.add_argument("--model", action="append", choices=sorted(MODELS),
                        help="model(s) to explore (default: all)")
    parser.add_argument("--depth", type=int, default=None,
                        help="exploration depth bound (default: per-model)")
    parser.add_argument("--mutate", choices=sorted(MUTATIONS), default=None,
                        help="remove one protocol guard; the run then "
                             "EXPECTS a counterexample (exit 0 iff found)")
    parser.add_argument("--out", default=None, metavar="DIR",
                        help="write counterexample JSON + pytest stubs "
                             "here")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable verdicts on stdout")
    args = parser.parse_args(argv)

    names = args.model or ([MUTATIONS[args.mutate]] if args.mutate
                           else sorted(MODELS))
    results = run(names, depth=args.depth, mutation=args.mutate)
    payload = {"results": [r.to_json() for r in results]}
    artifacts = []
    for r in results:
        if not r.ok and args.out:
            artifacts.append(write_counterexample(r, args.out))
    if artifacts:
        payload["artifacts"] = [list(a) for a in artifacts]
    if args.json:
        json.dump(payload, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        for r in results:
            tag = f"{r.model}" + (f"[{r.mutation}]" if r.mutation else "")
            if r.ok:
                cap = ("" if r.complete
                       else " [state cap hit — search truncated, verdict "
                            "is bounded-only]")
                print(f"distmodel: {tag}: OK — invariants hold over "
                      f"{r.states} states (depth {r.depth}){cap}")
            else:
                print(f"distmodel: {tag}: VIOLATION — {r.invariant}")
                print("  trace: " + " -> ".join(
                    _fmt(e) for e in r.trace or []))
        for jp, sp in artifacts:
            print(f"  wrote {jp}\n  wrote {sp}")
    if args.mutate:
        # a mutated run is SOUND when the checker caught the seeded bug
        caught = any(not r.ok and r.mutation == args.mutate
                     for r in results)
        if not caught:
            print(f"distmodel: mutation {args.mutate!r} was NOT caught",
                  file=sys.stderr)
        return 0 if caught else 1
    return 0 if all(r.ok for r in results) else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
