"""distcheck core — findings, suppressions, and the analyzed-package model.

The analyzer is a pure function of source text: every checker works on the
``ast`` of the package files, never on imported runtime objects, so the same
engine runs over the real tree and over the seeded-bug fixture corpora in
``tests/test_distcheck.py`` (a checker that needed to import its target
could not be tested against deliberately-broken twins).

Vocabulary:

- :class:`Finding` — one diagnostic, with a stable per-checker code
  (``DC1xx`` wire protocol, ``DC2xx`` concurrency, ``DC3xx`` tracing
  hygiene, ``DC0xx`` for the analyzer's own hygiene rules). The
  :meth:`~Finding.baseline_key` deliberately omits the line number so the
  checked-in baseline survives unrelated edits above a finding.
- Suppressions — ``# distcheck: ignore[DC201] <reason>`` on the flagged
  line or the line directly above it. The reason is REQUIRED: a bare
  ignore is itself a finding (DC001), and a suppression that matches
  nothing is flagged too (DC002) so stale ignores rot away instead of
  hiding future regressions.
- :class:`SourceFile` / :class:`Package` — parsed files plus the repo-
  relative paths every finding and baseline entry is keyed by.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One diagnostic: ``path:line: CODE message``."""

    path: str
    line: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def baseline_key(self) -> str:
        """Line-number-free identity used by the checked-in baseline (a
        finding that merely moved is not 'new')."""
        return f"{self.path} | {self.code} | {self.message}"


_SUPPRESS_RE = re.compile(
    r"#\s*distcheck:\s*ignore\[([A-Za-z0-9_,\s]+)\]\s*(.*)$")


@dataclasses.dataclass
class Suppression:
    line: int
    codes: Tuple[str, ...]
    reason: str
    end_line: int = 0  # last line of the contiguous comment block
    used: bool = False

    def covers(self, line: int) -> bool:
        """A suppression silences findings on its own line(s) and on the
        first code line after its comment block."""
        return self.line <= line <= max(self.end_line, self.line) + 1


class SourceFile:
    """One parsed source file: AST + suppression comments + plane."""

    def __init__(self, path: str, abspath: str, text: str):
        self.path = path  # repo-relative, forward slashes (baseline key)
        self.abspath = abspath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=abspath)
        # suppressions come from real COMMENT tokens only — the same text
        # inside a docstring (e.g. documentation of the syntax) is not one
        self.suppressions: Dict[int, Suppression] = {}
        comment_lines = set()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(text).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                comment_lines.add(tok.start[0])
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    codes = tuple(
                        c.strip() for c in m.group(1).split(",") if c.strip())
                    self.suppressions[tok.start[0]] = Suppression(
                        line=tok.start[0], codes=codes,
                        reason=m.group(2).strip(" -—:\t"))
        except tokenize.TokenError:
            pass  # unterminated constructs: AST parse above already raised
        # a multi-line suppression comment covers its whole block: the
        # reason may wrap, and the silenced line is the first CODE line
        # after the block
        for sup in self.suppressions.values():
            end = sup.line
            while end + 1 in comment_lines:
                end += 1
            sup.end_line = end

    @property
    def plane(self) -> str:
        return plane_of(self.path)


def plane_of(path: str) -> str:
    """Module path → protocol plane (which side of the wire it serves)."""
    parts = path.replace(os.sep, "/").split("/")
    for part in parts[:-1]:
        if part == "serving":
            return "serving"
        if part == "coord":
            return "coord"
        if part in ("parallel", "training"):
            return "ps"
        if part in ("utils", "native"):
            return "transport"
    return "misc"


class Package:
    """The set of files one analyzer run covers."""

    def __init__(self, files: Sequence[SourceFile]):
        self.files = list(files)

    def __iter__(self):
        return iter(self.files)


def load_package(root: str, rel_base: Optional[str] = None) -> Package:
    """Parse every ``*.py`` under ``root`` (a package directory).

    Paths are reported relative to ``rel_base`` (default: the parent of
    ``root``), so findings over the installed package read
    ``distributed_ml_pytorch_tpu/utils/messaging.py:…``.
    """
    root = os.path.abspath(root)
    base = os.path.abspath(rel_base) if rel_base else os.path.dirname(root)
    files = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__" and not d.startswith("."))
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            abspath = os.path.join(dirpath, name)
            rel = os.path.relpath(abspath, base).replace(os.sep, "/")
            with open(abspath, "r", encoding="utf-8") as fh:
                text = fh.read()
            files.append(SourceFile(rel, abspath, text))
    return Package(files)


def apply_suppressions(
    pkg: Package, findings: Iterable[Finding]
) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (active, suppressed) and append the analyzer's
    own hygiene findings: DC001 (suppression without a reason) and DC002
    (suppression that matched nothing)."""
    by_path = {f.path: f for f in pkg.files}
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for finding in sorted(findings):
        src = by_path.get(finding.path)
        sup = None
        if src is not None:
            for cand in src.suppressions.values():
                if finding.code in cand.codes and cand.covers(finding.line):
                    sup = cand
                    break
        if sup is not None and sup.reason:
            sup.used = True
            suppressed.append(finding)
        else:
            if sup is not None:
                sup.used = True  # matched, but unusable: DC001 below says why
            active.append(finding)
    for src in pkg.files:
        for sup in src.suppressions.values():
            if not sup.reason:
                active.append(Finding(
                    src.path, sup.line, "DC001",
                    "suppression without a reason — write WHY after the "
                    "bracket: # distcheck: ignore[%s] <reason>"
                    % ",".join(sup.codes)))
            elif not sup.used:
                active.append(Finding(
                    src.path, sup.line, "DC002",
                    "unused suppression for %s — the finding it silenced is "
                    "gone; delete the comment" % ",".join(sup.codes)))
    return sorted(active), suppressed


def baseline_keys(findings: Sequence[Finding]) -> List[str]:
    """Baseline keys for a (sorted) finding list, with duplicates numbered.

    Several findings in one file can share a constant message (two
    undisciplined threads, two ``.inner`` bypasses); numbering the 2nd+
    occurrence (``… | #2``) means a parked baseline entry covers exactly
    ONE occurrence — a new instance of the same defect still fails lint.
    The first occurrence keeps the plain key, so removing a duplicate
    never invalidates the surviving entry."""
    counts: Dict[str, int] = {}
    out = []
    for f in findings:
        base = f.baseline_key()
        n = counts.get(base, 0) + 1
        counts[base] = n
        out.append(base if n == 1 else f"{base} | #{n}")
    return out


def read_baseline(path: str) -> frozenset:
    if not path or not os.path.exists(path):
        return frozenset()
    with open(path) as fh:
        return frozenset(
            line.strip() for line in fh
            if line.strip() and not line.startswith("#"))


# --------------------------------------------------------------- AST helpers

def walk_list(node: ast.AST) -> list:
    """``list(ast.walk(node))`` memoized ON the node — the checkers walk
    the same functions many times (sends, locals, handlers, locks), and
    without the cache a package run re-traverses ~70x. The cache rides the
    node's ``__dict__``, so it lives exactly as long as the tree."""
    cached = getattr(node, "_distcheck_walk", None)
    if cached is None:
        cached = list(ast.walk(node))
        try:
            node._distcheck_walk = cached
        except AttributeError:
            pass  # nodes without __dict__: walk uncached
    return cached


def call_name(node: ast.Call) -> str:
    """Last dotted segment of a call target (``jax.jit`` → ``jit``)."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def dotted_name(node: ast.AST) -> str:
    """Render a Name/Attribute chain (``np.random.default_rng``); empty
    string when the expression is not a plain dotted chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def const_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = const_int(node.operand)
        return None if inner is None else -inner
    return None


def self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` → ``"X"``; anything else → None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def message_code_names(node: ast.AST) -> List[Tuple[str, int]]:
    """Every ``MessageCode.<Name>`` attribute inside ``node`` with its line."""
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and \
                isinstance(sub.value, ast.Name) and \
                sub.value.id == "MessageCode":
            out.append((sub.attr, sub.lineno))
    return out
