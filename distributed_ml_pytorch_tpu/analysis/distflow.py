"""distcheck DC5xx — interprocedural dataflow checks over receive paths.

The DC4xx family checks protocol *points* (a WAL append exists, an
incarnation compare exists somewhere). This family checks *flow*: what
actually reaches state, and in what order, following the payload one
call level deep (the DC404 follow discipline) and the lock graph the
DC2xx pass already builds.

- **DC501** — receive-path ordering. For every handler of a
  ``WIRE_SCHEMAS`` code that declares a codec or CRC contract (a
  ``codec``/``crc_lo`` field in the schema head), the payload value is
  tainted and tracked through local assignments and one level of
  ``self.m(...)`` delegation. Raw (undecoded) bytes reaching a WAL
  append or ``self`` state mutation is the bug: the schema says the
  decode/CRC/admission gate comes first. Constant-index head reads
  (``payload[0]`` — the codec id, sizes, CRC words ride the head in
  clear) and values produced *by* a gate call (``decode*``, ``*crc*``,
  ``admit*``, ``validate*``, ``check*``, ``verify*``) are clean.
- **DC502** — fenced-mutation gating. A handler of a ``fenced=True``
  schema that mutates ``self`` state with no epoch evidence dominating
  it — neither a ``strip_epoch`` call nor an epoch/fence comparison in
  the enclosing dispatch function or the one-level followed body. Pure
  counters (``+= <const>``) are exempt: dropping a stale frame *into a
  stat* is the fence working, not the fence missing.
- **DC503** — unbounded-state growth. A container attribute of a
  Thread-target / serve-loop / handler class that grows under per-key
  indexing (``d[k] = …``, ``.append``, ``.add``, ``.setdefault``) with
  no prune anywhere in the class. Exempt: bounded constructors
  (``deque(maxlen=…)``, ``Bounded*``/``Ring*``), attrs that are pruned
  (``pop``/``del``/``clear``/rebuild-assignment outside ``__init__`` or
  a ``prune``/``trim``/``evict`` helper call), WAL attrs (durable logs
  are truncated by the checkpoint protocol, not the handler), keyed
  upserts whose RHS reads the same container (rewrite-in-place
  accumulators), presence-gated memos (``k in self.m`` / ``.get`` before
  the insert — bounded by the key domain), and containers admission-
  capped by an explicit ``len(self.m) < cap`` check. All exemptions
  except the bounded constructor are *fallible* — they are exported via
  :func:`bounded_exemptions` so the runtime witness can sample the real
  containers at scenario teardown (the same static/runtime pairing the
  lock witness does for DC202). Growth sites are a class's own; the
  clearing evidence is searched over the package-internal inheritance
  lineage.
- **DC504** — blocking while holding a lock. ``sleep``/``fsync``/
  ``wal.sync``/indefinite ``join()``/``wait()``/bare ``recv()``
  reached while a ``with self._lock:`` scope is open, transitively
  through same-class calls (the DC2xx ``calls``/``held_calls`` graph).
  A ``wait()`` on a lock that is itself held is a condition-variable
  wait (it releases) and is exempt.

All four follow the opt-in discipline: DC501 needs a codec/CRC schema,
DC502 needs a ``fenced=True`` schema, DC503/DC504 need thread or
handler classes and locks — a tree without those shapes sees nothing.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from distributed_ml_pytorch_tpu.analysis import concurrency, wire
from distributed_ml_pytorch_tpu.analysis.core import (
    Finding,
    Package,
    SourceFile,
    call_name,
    dotted_name,
    message_code_names,
    self_attr,
    walk_list,
)

#: call names that count as the schema's decode/admission/integrity gate
_GATE_RE = re.compile(r"decode|crc|admit|validate|verify|check", re.I)

#: growth mutators for DC503 (per-key adds; AugAssign ``d[k] += 1`` needs
#: an existing key and is a counter, not growth)
_GROWERS = frozenset({"append", "appendleft", "add", "setdefault"})

_PRUNERS = frozenset({
    "pop", "popleft", "popitem", "clear", "remove", "discard",
})

_PRUNE_HELPER_RE = re.compile(r"prune|trim|evict|drop_after|truncat", re.I)

_BOUNDED_CTOR_RE = re.compile(r"bounded|ring", re.I)


@dataclasses.dataclass(frozen=True)
class ExemptContainer:
    """A container DC503 saw growing but cleared via a fallible
    exemption — the runtime witness samples these at teardown."""

    path: str
    cls: str
    attr: str
    line: int
    reason: str


# ------------------------------------------------------------ shared helpers

def _enclosing_function(tree: ast.AST, line: int) -> Optional[ast.AST]:
    best = None
    for node in walk_list(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= line <= end and \
                (best is None or node.lineno > best.lineno):
            best = node
    return best


def _last_param(fn: Optional[ast.AST]) -> Optional[str]:
    """The payload is the last parameter by convention
    (``handle(self, sender, code, payload)``) — the fallback when the
    dispatch test carries no ``payload.size`` guard to name it."""
    if fn is None or not getattr(fn, "args", None):
        return None
    args = fn.args.args
    if not args:
        return None
    name = args[-1].arg
    return None if name == "self" else name


def _file_functions(src: SourceFile) -> Dict[str, ast.FunctionDef]:
    out: Dict[str, ast.FunctionDef] = {}
    for node in walk_list(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _is_const_index(sl: ast.AST) -> bool:
    return isinstance(sl, ast.Constant) and \
        isinstance(sl.value, (int, str)) and not isinstance(sl.value, bool)


def _collect_classes(pkg: Package) -> Dict[str, concurrency.ClassInfo]:
    """The DC2xx class model (methods, locks, calls, thread entries) —
    rebuilt here so DC503/DC504 see the same graph DC202/DC205 do.

    Deliberately NOT merged (``_merge_inherited``): merging attributes a
    base class's growth sites to every subclass (duplicate findings with
    the wrong path) and loses bounded-ctor evidence whenever a subclass
    shadows the base ``__init__``. DC503/DC504 instead analyze each
    class's OWN methods and union the *evidence* over the lineage via
    :func:`_lineage`."""
    classes: Dict[str, concurrency.ClassInfo] = {}
    for src in pkg:
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                classes[node.name] = concurrency._collect_class(src, node)
    # registers thread_entries as a side effect; DC203 findings are the
    # concurrency pass's to report, not ours
    concurrency._find_thread_targets(pkg, classes)
    return classes


def _lineage(classes: Dict[str, concurrency.ClassInfo],
             info: concurrency.ClassInfo) -> List[concurrency.ClassInfo]:
    """``info`` plus its transitive package-internal base classes."""
    out: List[concurrency.ClassInfo] = []
    seen: Set[str] = set()
    queue = [info.name]
    while queue:
        name = queue.pop()
        if name in seen or name not in classes:
            continue
        seen.add(name)
        out.append(classes[name])
        queue.extend(classes[name].bases)
    return out


def _class_spans(pkg: Package) -> Dict[Tuple[str, str], Tuple[int, int]]:
    spans: Dict[Tuple[str, str], Tuple[int, int]] = {}
    for src in pkg:
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                spans[(src.path, node.name)] = (
                    node.lineno, node.end_lineno or node.lineno)
    return spans


# ------------------------------------------------- DC501: receive ordering

def _is_gate_call(node: ast.Call) -> bool:
    return bool(_GATE_RE.search(call_name(node)))


def _raw(expr: Optional[ast.AST], tainted: Set[str]) -> bool:
    """Whether the VALUE of ``expr`` still carries raw payload bytes.
    Gate-call results, comparisons and constant-index head reads are
    clean; everything derived from a tainted name otherwise is raw."""
    if expr is None:
        return False
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, ast.Subscript):
        if _is_const_index(expr.slice):
            return False  # head-field read: codec id / sizes / crc words
        return _raw(expr.value, tainted)
    if isinstance(expr, ast.Attribute):
        return False  # metadata (.size, .shape); method calls via Call
    if isinstance(expr, ast.Call):
        if _is_gate_call(expr):
            return False
        if any(_raw(a, tainted) for a in expr.args):
            return True
        if any(_raw(kw.value, tainted) for kw in expr.keywords):
            return True
        if isinstance(expr.func, ast.Attribute):
            return _raw(expr.func.value, tainted)
        return False
    if isinstance(expr, ast.BinOp):
        return _raw(expr.left, tainted) or _raw(expr.right, tainted)
    if isinstance(expr, ast.BoolOp):
        return any(_raw(v, tainted) for v in expr.values)
    if isinstance(expr, ast.UnaryOp):
        return _raw(expr.operand, tainted)
    if isinstance(expr, ast.IfExp):
        return _raw(expr.body, tainted) or _raw(expr.orelse, tainted)
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        return any(_raw(e, tainted) for e in expr.elts)
    if isinstance(expr, ast.Dict):
        return any(_raw(v, tainted) for v in expr.values if v is not None)
    if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        return _raw(expr.elt, tainted) or \
            any(_raw(g.iter, tainted) for g in expr.generators)
    if isinstance(expr, ast.DictComp):
        return _raw(expr.value, tainted) or \
            any(_raw(g.iter, tainted) for g in expr.generators)
    if isinstance(expr, ast.Starred):
        return _raw(expr.value, tainted)
    if isinstance(expr, ast.NamedExpr):
        return _raw(expr.value, tainted)
    return False  # Compare, Constant, JoinedStr, Lambda, ...


def _target_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in target.elts:
            out.extend(_target_names(e))
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    return []


def _self_target(target: ast.AST) -> Optional[str]:
    """``self.X``, ``self.X[...]`` or ``self.X.Y`` as a mutation of X."""
    attr = self_attr(target)
    if attr is not None:
        return attr
    if isinstance(target, ast.Subscript):
        return _self_target(target.value)
    if isinstance(target, ast.Attribute):
        return self_attr(target.value)
    return None


class _TaintWalker:
    """Order-sensitive taint propagation over one handler body, with one
    level of same-file ``self.m(raw_arg)`` follow (the DC404 budget)."""

    def __init__(self, site: wire.HandlerSite, src: SourceFile,
                 functions: Dict[str, ast.FunctionDef]):
        self.site = site
        self.src = src
        self.functions = functions
        self.sinks: List[Tuple[int, str]] = []  # (line, description)
        self.followed: Set[str] = set()

    def run(self, payload: str) -> List[Tuple[int, str]]:
        self._stmts(self.site.body or [], {payload}, depth=0)
        return self.sinks

    # ------------------------------------------------------------ statements
    def _stmts(self, stmts: Sequence[ast.stmt], tainted: Set[str],
               depth: int) -> None:
        for stmt in stmts:
            self._stmt(stmt, tainted, depth)

    def _stmt(self, stmt: ast.stmt, tainted: Set[str], depth: int) -> None:
        for call in [n for n in ast.walk(stmt) if isinstance(n, ast.Call)]:
            self._call(call, tainted, depth)
        if isinstance(stmt, ast.Assign):
            raw = _raw(stmt.value, tainted)
            for target in stmt.targets:
                self._assign_target(target, raw, tainted, stmt.lineno)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign_target(
                stmt.target, _raw(stmt.value, tainted), tainted, stmt.lineno)
        elif isinstance(stmt, ast.AugAssign):
            raw = _raw(stmt.value, tainted)
            attr = _self_target(stmt.target)
            if raw and attr is not None:
                self.sinks.append((stmt.lineno, f"self.{attr}"))
            if raw:
                for name in _target_names(stmt.target):
                    tainted.add(name)
        elif isinstance(stmt, (ast.If,)):
            self._stmts(stmt.body, tainted, depth)
            self._stmts(stmt.orelse, tainted, depth)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            if _raw(stmt.iter, tainted):
                for name in _target_names(stmt.target):
                    tainted.add(name)
            self._stmts(stmt.body, tainted, depth)
            self._stmts(stmt.orelse, tainted, depth)
        elif isinstance(stmt, ast.While):
            self._stmts(stmt.body, tainted, depth)
            self._stmts(stmt.orelse, tainted, depth)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._stmts(stmt.body, tainted, depth)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body, tainted, depth)
            for handler in stmt.handlers:
                self._stmts(handler.body, tainted, depth)
            self._stmts(stmt.orelse, tainted, depth)
            self._stmts(stmt.finalbody, tainted, depth)

    def _assign_target(self, target: ast.AST, raw: bool,
                       tainted: Set[str], line: int) -> None:
        attr = _self_target(target)
        if raw and attr is not None:
            self.sinks.append((line, f"self.{attr}"))
        for name in _target_names(target):
            if raw:
                tainted.add(name)
            else:
                tainted.discard(name)  # reassigned from a gated value

    # ----------------------------------------------------------------- calls
    def _call(self, node: ast.Call, tainted: Set[str], depth: int) -> None:
        if _is_gate_call(node):
            return
        args_raw = [_raw(a, tainted) for a in node.args]
        kw_raw = {kw.arg: _raw(kw.value, tainted)
                  for kw in node.keywords if kw.arg}
        if not (any(args_raw) or any(kw_raw.values())):
            return
        if isinstance(node.func, ast.Attribute):
            # mutator on self state (or a WAL receiver): raw bytes land
            if node.func.attr in concurrency.MUTATORS:
                base = _self_target(node.func.value)
                recv = dotted_name(node.func.value) or ""
                if base is not None or "wal" in recv:
                    self.sinks.append(
                        (node.lineno,
                         f"self.{base}" if base is not None else recv))
                    return
            # one-level follow: self.m(raw, ...) delegates the gate
            target = self_attr(node.func)
            if target is not None and depth == 0 and \
                    target not in self.followed and target in self.functions:
                self.followed.add(target)
                fn = self.functions[target]
                params = [a.arg for a in fn.args.args if a.arg != "self"]
                inner: Set[str] = set()
                for i, is_raw in enumerate(args_raw):
                    if is_raw and i < len(params):
                        inner.add(params[i])
                for name, is_raw in kw_raw.items():
                    if is_raw and name in params:
                        inner.add(name)
                if inner:
                    self._stmts(fn.body, inner, depth=1)


def _check_receive_order(pkg: Package) -> List[Finding]:
    schemas = wire.extract_schemas(pkg)
    codec_codes = {c for c, s in schemas.items()
                   if "codec" in s.fields or "crc_lo" in s.fields}
    if not codec_codes:
        return []
    by_path = {src.path: src for src in pkg}
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for site in wire.extract_handlers(pkg):
        if site.code not in codec_codes or site.body is None:
            continue
        src = by_path[site.path]
        payload = site.payload_name or _last_param(
            _enclosing_function(src.tree, site.line))
        if payload is None:
            continue
        walker = _TaintWalker(site, src, _file_functions(src))
        for line, desc in walker.run(payload):
            key = (site.path, line, desc)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                site.path, line, "DC501",
                f"MessageCode.{site.code} declares a codec/CRC contract "
                f"but raw (undecoded) payload bytes reach {desc} here — "
                "the decode/CRC/admission gate must come first"))
    return findings


# ----------------------------------------------- DC502: fenced-mutation gate

def _fenced_codes(pkg: Package) -> Set[str]:
    fenced: Set[str] = set()
    for src in pkg:
        for node in walk_list(src.tree):
            if not (wire._is_schema_table(node)
                    and isinstance(node.value, ast.Dict)):
                continue
            for key, val in zip(node.value.keys, node.value.values):
                names = message_code_names(key) if key is not None else []
                if len(names) != 1 or not isinstance(val, ast.Call):
                    continue
                for kw in val.keywords:
                    if kw.arg == "fenced" and \
                            isinstance(kw.value, ast.Constant) and \
                            kw.value.value is True:
                        fenced.add(names[0][0])
    return fenced


def _followed_nodes(site: wire.HandlerSite, src: SourceFile) -> List[ast.AST]:
    """Handler body plus one level of same-file self-method delegation
    (protomodel's DC404 follow)."""
    nodes: List[ast.AST] = []
    called: Set[str] = set()
    for stmt in site.body or []:
        for node in ast.walk(stmt):
            nodes.append(node)
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self":
                called.add(node.func.attr)
    if called:
        for node in walk_list(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in called:
                nodes.extend(walk_list(node))
    return nodes


def _has_epoch_evidence(nodes: Sequence[ast.AST]) -> bool:
    for node in nodes:
        if isinstance(node, ast.Call) and \
                "epoch" in call_name(node).lower():
            return True  # strip_epoch / check_epoch — the fence plumbing
        if isinstance(node, ast.Compare):
            for side in (node.left, *node.comparators):
                name = dotted_name(side)
                if name and ("epoch" in name.lower()
                             or "fence" in name.lower()):
                    return True
    return False


def _counter_augassign(node: ast.AST) -> bool:
    return isinstance(node, ast.AugAssign) and \
        isinstance(node.value, ast.Constant) and \
        isinstance(node.value.value, (int, float))


def _check_fenced_gate(pkg: Package) -> List[Finding]:
    fenced = _fenced_codes(pkg)
    if not fenced:
        return []
    by_path = {src.path: src for src in pkg}
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for site in wire.extract_handlers(pkg):
        if site.code not in fenced or site.body is None:
            continue
        src = by_path[site.path]
        fn = _enclosing_function(src.tree, site.line)
        scope: List[ast.AST] = list(walk_list(fn)) if fn is not None else []
        scope += _followed_nodes(site, src)
        if _has_epoch_evidence(scope):
            continue
        for stmt in site.body:
            for node in ast.walk(stmt):
                attr = None
                if isinstance(node, (ast.Assign,)):
                    for target in node.targets:
                        attr = attr or _self_target(target)
                elif isinstance(node, ast.AugAssign) and \
                        not _counter_augassign(node):
                    attr = _self_target(node.target)
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in concurrency.MUTATORS:
                    attr = _self_target(node.func.value)
                if attr is None:
                    continue
                key = (site.path, node.lineno)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    site.path, node.lineno, "DC502",
                    f"MessageCode.{site.code} is a fenced frame but "
                    f"self.{attr} is mutated with no epoch comparison "
                    "dominating it — a zombie coordinator's stale command "
                    "can rewrite live state"))
    return findings


# --------------------------------------------- DC503: unbounded state growth

def _grow_sites(info: concurrency.ClassInfo) -> Dict[str, List[Tuple[int, bool]]]:
    """attr → [(line, is_upsert)] growth sites outside construction."""
    sites: Dict[str, List[Tuple[int, bool]]] = {}
    for name, fn in info.methods.items():
        if name in ("__init__", "__post_init__"):
            continue
        for node in walk_list(fn):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if not (isinstance(target, ast.Subscript)
                            and not _is_const_index(target.slice)):
                        continue
                    attr = self_attr(target.value)
                    if attr is None or "wal" in attr:
                        continue
                    upsert = any(
                        isinstance(sub, ast.Attribute)
                        and self_attr(sub) == attr
                        or isinstance(sub, ast.Attribute)
                        and self_attr(sub.value) == attr
                        for sub in ast.walk(node.value))
                    sites.setdefault(attr, []).append((node.lineno, upsert))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _GROWERS:
                attr = self_attr(node.func.value)
                if attr is None or "wal" in attr:
                    continue
                sites.setdefault(attr, []).append((node.lineno, False))
    return sites


def _bounded_ctor_attrs(info: concurrency.ClassInfo) -> Set[str]:
    out: Set[str] = set()
    for fn in info.methods.values():
        for node in walk_list(fn):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            ctor = call_name(node.value)
            bounded = _BOUNDED_CTOR_RE.search(ctor) or any(
                kw.arg == "maxlen" for kw in node.value.keywords)
            if not bounded:
                continue
            for target in node.targets:
                attr = self_attr(target)
                if attr is not None:
                    out.add(attr)
    return out


def _method_aliases(fn: ast.AST) -> Dict[str, Set[str]]:
    """local name → the ``self`` attrs it may alias: ``d = self.m`` and
    the batch-cleanup idiom ``for d in (self.a, self.b): d.pop(k)``."""
    out: Dict[str, Set[str]] = {}
    for node in walk_list(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            attr = self_attr(node.value)
            if attr is not None:
                out.setdefault(node.targets[0].id, set()).add(attr)
        elif isinstance(node, (ast.For, ast.AsyncFor)) and \
                isinstance(node.target, ast.Name) and \
                isinstance(node.iter, (ast.Tuple, ast.List)):
            attrs = {self_attr(e) for e in node.iter.elts} - {None}
            if attrs:
                out.setdefault(node.target.id, set()).update(attrs)
    return out


def _recv_attrs(expr: ast.AST, aliases: Dict[str, Set[str]]) -> Set[str]:
    """The self attrs a receiver expression denotes (directly or via a
    local alias)."""
    attr = self_attr(expr)
    if attr is not None:
        return {attr}
    if isinstance(expr, ast.Name):
        return aliases.get(expr.id, set())
    return set()


def _pruned_attrs(info: concurrency.ClassInfo) -> Dict[str, int]:
    """attr → line of the prune evidence (pop/del/clear/rebuild/helper),
    seen through one level of local aliasing."""
    out: Dict[str, int] = {}
    for name, fn in info.methods.items():
        in_init = name in ("__init__", "__post_init__")
        aliases = _method_aliases(fn)
        for node in walk_list(fn):
            attrs: Set[str] = set()
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _PRUNERS:
                    attrs = _recv_attrs(node.func.value, aliases)
                elif _PRUNE_HELPER_RE.search(call_name(node)):
                    for arg in node.args:
                        attrs |= _recv_attrs(arg, aliases)
            elif isinstance(node, ast.Delete):
                for target in node.targets:
                    if isinstance(target, ast.Subscript):
                        attrs |= _recv_attrs(target.value, aliases)
            elif isinstance(node, ast.Assign) and not in_init:
                # a rebuild (`self.m = {k: v for ... if fresh}`) IS the
                # frontier prune idiom — but only outside construction
                for target in node.targets:
                    a = self_attr(target)
                    if a is not None:
                        attrs.add(a)
            for a in attrs:
                out.setdefault(a, node.lineno)
    return out


def _handler_classes(pkg: Package,
                     spans: Dict[Tuple[str, str], Tuple[int, int]]
                     ) -> Set[Tuple[str, str]]:
    out: Set[Tuple[str, str]] = set()
    for site in wire.extract_handlers(pkg):
        for (path, cls), (lo, hi) in spans.items():
            if path == site.path and lo <= site.line <= hi:
                out.add((path, cls))
    return out


def _memo_gated_attrs(info: concurrency.ClassInfo) -> Set[str]:
    """Attrs whose inserts are presence-gated (``self.m.get(k)`` /
    ``k in self.m`` before the write): a memo keyed by a finite domain
    (peer rank, message code), not an open-ended log."""
    out: Set[str] = set()
    for fn in info.methods.values():
        for node in walk_list(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("get", "__contains__"):
                attr = self_attr(node.func.value)
                if attr is not None:
                    out.add(attr)
            elif isinstance(node, ast.Compare) and any(
                    isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
                for side in node.comparators:
                    attr = self_attr(side)
                    if attr is not None:
                        out.add(attr)
    return out


def _len_gated_attrs(info: concurrency.ClassInfo) -> Set[str]:
    """Attrs compared through ``len(self.m) < cap`` somewhere in the
    class — an explicit admission cap on the container's size."""
    out: Set[str] = set()
    for fn in info.methods.values():
        for node in walk_list(fn):
            if not isinstance(node, ast.Compare):
                continue
            for side in (node.left, *node.comparators):
                if isinstance(side, ast.Call) and \
                        call_name(side) == "len" and side.args:
                    attr = self_attr(side.args[0])
                    if attr is not None:
                        out.add(attr)
    return out


def _bounded_analysis(
    pkg: Package, classes: Dict[str, concurrency.ClassInfo]
) -> Tuple[List[Finding], List[ExemptContainer]]:
    spans = _class_spans(pkg)
    handler_cls = _handler_classes(pkg, spans)
    findings: List[Finding] = []
    exemptions: List[ExemptContainer] = []
    for info in classes.values():
        lineage = _lineage(classes, info)
        long_running = any(c.thread_entries for c in lineage) or any(
            (c.path, c.name) in handler_cls for c in lineage)
        if not long_running:
            continue
        # growth sites come from this class's OWN methods; the evidence
        # that clears them (bounded ctor, prune, gate) may live anywhere
        # in the lineage — a base __init__ bounding what a subclass fills
        grow = _grow_sites(info)
        if not grow:
            continue
        bounded: Set[str] = set()
        pruned: Dict[str, int] = {}
        memo_gated: Set[str] = set()
        len_gated: Set[str] = set()
        for c in lineage:
            bounded |= _bounded_ctor_attrs(c)
            for a, ln in _pruned_attrs(c).items():
                pruned.setdefault(a, ln)
            memo_gated |= _memo_gated_attrs(c)
            len_gated |= _len_gated_attrs(c)
        for attr in sorted(grow):
            line = grow[attr][0][0]
            if attr in bounded:
                continue  # deque(maxlen)/Bounded*: structurally bounded
            if attr in pruned:
                exemptions.append(ExemptContainer(
                    info.path, info.name, attr, line,
                    "pruned elsewhere in the class"))
                continue
            if all(upsert for _, upsert in grow[attr]):
                exemptions.append(ExemptContainer(
                    info.path, info.name, attr, line,
                    "keyed upsert rewrites in place"))
                continue
            if attr in memo_gated:
                exemptions.append(ExemptContainer(
                    info.path, info.name, attr, line,
                    "presence-gated memo (bounded by its key domain)"))
                continue
            if attr in len_gated:
                exemptions.append(ExemptContainer(
                    info.path, info.name, attr, line,
                    "admission-capped by an explicit length check"))
                continue
            findings.append(Finding(
                info.path, line, "DC503",
                f"{info.name}.{attr} grows under per-key indexing/append "
                f"with no prune, pop, maxlen or ring anywhere in "
                f"{info.name} — long-running handler state leaks"))
    return findings, exemptions


def bounded_exemptions(pkg: Package) -> List[ExemptContainer]:
    """The fallible DC503 exemptions — what the runtime bounded-state
    witness watches at scenario teardown."""
    return _bounded_analysis(pkg, _collect_classes(pkg))[1]


# ------------------------------------------- DC504: blocking while locked

def _blocking_desc(node: ast.Call, held: Tuple[str, ...]) -> Optional[str]:
    name = call_name(node)
    if name == "sleep":
        return "sleep()"
    if name == "fsync":
        return "fsync()"
    if name == "sync" and isinstance(node.func, ast.Attribute) and \
            "wal" in (dotted_name(node.func.value) or ""):
        return "wal.sync() (group fsync)"
    if name == "join" and concurrency._is_thread_join(node) and \
            not node.args and not node.keywords:
        return "join() with no timeout"
    timeout_kw = next(
        (kw.value for kw in node.keywords if kw.arg == "timeout"), None)
    none_timeout = isinstance(timeout_kw, ast.Constant) and \
        timeout_kw.value is None
    if name == "wait" and isinstance(node.func, ast.Attribute):
        recv = self_attr(node.func.value)
        if recv is not None and recv in held:
            return None  # condition wait on the held lock: it releases
        first_none = bool(node.args) and \
            isinstance(node.args[0], ast.Constant) and \
            node.args[0].value is None
        if (not node.args and timeout_kw is None) or none_timeout \
                or first_none:
            return "wait() with no timeout"
        return None
    if name == "recv":
        if (not node.args and not node.keywords) or none_timeout:
            return "recv() with no timeout"
        return None
    if name == "sendall":
        return "sendall()"
    return None


class _BlockFinder(ast.NodeVisitor):
    """Track held ``with self.<lock>:`` scopes through one method and
    record blocking calls (mirrors the DC2xx walker's lock scoping)."""

    def __init__(self, lock_attrs: Dict[str, int]):
        self.lock_attrs = lock_attrs
        self.held: Tuple[str, ...] = ()
        self.blocking: List[Tuple[Tuple[str, ...], str, int]] = []

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            lock = concurrency._with_lock_attr(item, self.lock_attrs)
            if lock is not None:
                acquired.append(lock)
            else:
                self.visit(item.context_expr)
        self.held = self.held + tuple(acquired)
        for stmt in node.body:
            self.visit(stmt)
        if acquired:
            self.held = self.held[: len(self.held) - len(acquired)]

    def visit_Call(self, node: ast.Call) -> None:
        desc = _blocking_desc(node, self.held)
        if desc is not None:
            self.blocking.append((self.held, desc, node.lineno))
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for stmt in node.body:  # nested defs share the creating scope
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


def _check_blocking_locked(
    classes: Dict[str, concurrency.ClassInfo]
) -> List[Finding]:
    findings: List[Finding] = []
    for info in classes.values():
        lineage = _lineage(classes, info)
        lock_attrs: Dict[str, int] = {}
        for c in lineage:
            lock_attrs.update(c.lock_attrs)
        if not lock_attrs:
            continue
        # direct findings come from this class's OWN methods only (a base
        # class reports its own sites in its own pass), but the held
        # scope recognizes inherited locks
        direct: Dict[str, List[Tuple[str, int]]] = {}
        for c in lineage:
            for name, fn in c.methods.items():
                if name in ("__init__", "__post_init__") or \
                        (c is not info and name in info.methods):
                    continue
                finder = _BlockFinder(lock_attrs)
                for stmt in fn.body:
                    finder.visit(stmt)
                for held, desc, line in finder.blocking:
                    direct.setdefault(name, []).append((desc, line))
                    if c is not info:
                        continue
                    for lock in held:
                        findings.append(Finding(
                            info.path, line, "DC504",
                            f"{info.name}.{name}() does {desc} while "
                            f"holding {info.name}.{lock} — every thread "
                            "contending on that lock stalls behind the "
                            "block"))
        # transitive: a held call into a (chain of) blocking method(s),
        # the call graph unioned over the lineage
        blocks: Dict[str, Set[str]] = {
            m: {d for d, _ in recs} for m, recs in direct.items()}
        calls: Dict[str, Set[str]] = {}
        for c in lineage:
            for m, callees in c.calls.items():
                calls.setdefault(m, set()).update(callees)
        changed = True
        while changed:
            changed = False
            for m, callees in calls.items():
                for callee in callees:
                    extra = blocks.get(callee, set()) - blocks.get(m, set())
                    if extra:
                        blocks.setdefault(m, set()).update(extra)
                        changed = True
        for held, callee, line in info.held_calls:
            if not held or not blocks.get(callee):
                continue
            desc = sorted(blocks[callee])[0]
            for lock in held:
                findings.append(Finding(
                    info.path, line, "DC504",
                    f"{info.name} calls {callee}() while holding "
                    f"{info.name}.{lock}, and {callee} (transitively) "
                    f"does {desc} — the lock is held across the block"))
    return findings


# ------------------------------------------------------------------- entry

def check(pkg: Package) -> List[Finding]:
    findings = _check_receive_order(pkg)
    findings += _check_fenced_gate(pkg)
    classes = _collect_classes(pkg)
    findings += _bounded_analysis(pkg, classes)[0]
    findings += _check_blocking_locked(classes)
    return findings
