"""distcheck DC1xx — wire-protocol consistency across the whole stack.

The protocol's ground truth is data, not prose: the ``MessageCode`` enum
and the declarative ``WIRE_SCHEMAS`` table in ``utils/messaging.py``
(ISSUE 4 satellite — payload layouts moved out of comments). This checker
extracts both FROM THE AST (so the seeded-bug corpora can carry their own
broken registries) plus every send site and handler site package-wide, and
cross-checks them:

- **DC101** — two ``MessageCode`` members share an int value. ``IntEnum``
  silently aliases the second onto the first, so its frames dispatch to the
  wrong handler; only a static check sees it.
- **DC102** — a code is sent somewhere but no module of its declared
  ``handled_by`` plane(s) compares against it: frames that arrive and rot
  in a mailbox (or hit a default-drop branch) forever.
- **DC103** — a handler exists for a code nothing ever sends or even
  references: dead protocol surface that will silently diverge.
- **DC104** — pack/unpack arity drift against the schema: a send site
  whose fixed head has the wrong number of fields, a handler guard
  (``code == X and payload.size >= K``) checking the wrong K, or a handler
  body indexing past the declared head / slicing the rest at the wrong
  offset.
- **DC105** — a module that opted into reliability (it wraps transports in
  ``ReliableTransport`` or passes ``reliable=`` to ``make_transport``)
  constructs a raw TCP transport it never wraps, or reaches through the
  wrapper with ``x.inner.send(...)`` — frames that silently skip the
  seq/CRC/ack service the rest of the module negotiated.
- **DC106** — a ``MessageCode`` with no ``WIRE_SCHEMAS`` entry (or a
  schema for a name the enum does not define): the table must stay total
  or every other check here has holes.
- **DC107** — a module that opted into the durability discipline (it
  references ``utils.durability.atomic_write``) still hand-rolls a
  ``open(..., "w"/"wb")`` + ``os.replace``/``os.rename`` persistence pair
  in some function: a write that is atomic but NOT power-loss durable (no
  fsync of data or rename), silently weaker than the module's own
  contract. Same opt-in style as DC105; the module that *defines*
  ``atomic_write`` is the raw path itself and is exempt.
- **DC108** — a module that opted into the shared jittered-backoff policy
  (it references ``utils.backoff.Backoff`` / ``jittered_backoff``) still
  hard-codes a literal retry sleep — ``time.sleep(<constant>)`` inside a
  loop: flat retry constants are how timed-out senders re-synchronize into
  retry storms, exactly what the policy exists to prevent (ISSUE 7). Same
  opt-in style as DC105/DC107; the module that *defines* ``Backoff`` is
  the policy's own plumbing and is exempt.

Send-site payload arity is resolved structurally: literal
``np.asarray([...])`` heads (``*_split16(x)`` counts as 2 — the documented
uint16-halves idiom), ``np.concatenate([head, tail])`` splits head/rest,
and one level of local-variable / builder-function indirection
(``encode_join(...)`` and friends) is followed.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from distributed_ml_pytorch_tpu.analysis.core import (
    Finding,
    Package,
    SourceFile,
    call_name,
    const_int,
    message_code_names,
    walk_list,
)

#: helpers known to expand to N wire fields when splatted into a head list
_SPLAT_ARITY = {"_split16": 2, "split16": 2}

#: the module that IS the reliability layer (its raw sends are the layer)
_LAYER_MODULE = "utils/messaging.py"


@dataclasses.dataclass
class SchemaInfo:
    code: str
    fields: Tuple[str, ...]
    rest: Optional[str]
    rest_min: int
    handled_by: Tuple[str, ...]
    path: str
    line: int
    # protocol-model annotations (ISSUE 13) — consumed by the DC4xx
    # checkers in analysis/protomodel.py; defaults mirror PayloadSchema
    dedup_key: Optional[str] = None
    durability: str = "none"
    delivery: str = "reliable"
    rest_sections: Tuple[str, ...] = ()
    rest_separator: Optional[float] = None

    @property
    def head(self) -> int:
        return len(self.fields)

    @property
    def min_size(self) -> int:
        return self.head + self.rest_min


@dataclasses.dataclass
class SendSite:
    code: str
    path: str
    line: int
    head: Optional[int]  # fixed-head arity when statically resolvable
    has_rest: Optional[bool]


@dataclasses.dataclass
class HandlerSite:
    code: str
    path: str
    line: int
    plane: str
    guard_min: Optional[int]  # K from `payload.size >= K` in the same test
    body: Optional[List[ast.stmt]]
    payload_name: Optional[str]


# --------------------------------------------------------------- extraction

def _is_schema_table(node: ast.AST) -> bool:
    """``WIRE_SCHEMAS = {…}`` as a plain or annotated assignment."""
    if isinstance(node, ast.Assign):
        return len(node.targets) == 1 and \
            isinstance(node.targets[0], ast.Name) and \
            node.targets[0].id == "WIRE_SCHEMAS"
    if isinstance(node, ast.AnnAssign):
        return isinstance(node.target, ast.Name) and \
            node.target.id == "WIRE_SCHEMAS" and node.value is not None
    return False


def extract_enum(pkg: Package) -> Tuple[Dict[str, int], List[Finding]]:
    """The ``MessageCode`` members, plus DC101 collisions."""
    values: Dict[str, int] = {}
    findings: List[Finding] = []
    by_value: Dict[int, Tuple[str, str, int]] = {}
    for src in pkg:
        for node in walk_list(src.tree):
            if not (isinstance(node, ast.ClassDef)
                    and node.name == "MessageCode"):
                continue
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    val = const_int(stmt.value)
                    if val is None:
                        continue
                    name = stmt.targets[0].id
                    values[name] = val
                    prev = by_value.get(val)
                    if prev is not None:
                        findings.append(Finding(
                            src.path, stmt.lineno, "DC101",
                            f"MessageCode.{name} = {val} collides with "
                            f"MessageCode.{prev[0]} — IntEnum aliases them "
                            "and frames dispatch to the wrong handler"))
                    else:
                        by_value[val] = (name, src.path, stmt.lineno)
    return values, findings


def _const_num(node: ast.AST) -> Optional[float]:
    """A literal int/float, including a unary-minus one (``-1.0``)."""
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)) and not isinstance(node.value, bool):
        return float(node.value)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _const_num(node.operand)
        return None if inner is None else -inner
    return None


def extract_schemas(pkg: Package) -> Dict[str, SchemaInfo]:
    schemas: Dict[str, SchemaInfo] = {}
    for src in pkg:
        for node in walk_list(src.tree):
            if not (_is_schema_table(node) and isinstance(node.value, ast.Dict)):
                continue
            for key, val in zip(node.value.keys, node.value.values):
                names = message_code_names(key) if key is not None else []
                if len(names) != 1 or not isinstance(val, ast.Call):
                    continue
                code = names[0][0]
                fields: Tuple[str, ...] = ()
                rest = None
                rest_min = 0
                handled_by: Tuple[str, ...] = ()
                info = SchemaInfo(code, fields, rest, rest_min, handled_by,
                                  src.path, val.lineno)
                for kw in val.keywords:
                    if kw.arg == "fields" and isinstance(
                            kw.value, (ast.Tuple, ast.List)):
                        info.fields = tuple(
                            e.value for e in kw.value.elts
                            if isinstance(e, ast.Constant))
                    elif kw.arg == "rest" and isinstance(kw.value, ast.Constant):
                        info.rest = kw.value.value
                    elif kw.arg == "rest_min":
                        info.rest_min = const_int(kw.value) or 0
                    elif kw.arg == "handled_by" and isinstance(
                            kw.value, (ast.Tuple, ast.List)):
                        info.handled_by = tuple(
                            e.value for e in kw.value.elts
                            if isinstance(e, ast.Constant))
                    elif kw.arg == "dedup_key" and isinstance(
                            kw.value, ast.Constant):
                        info.dedup_key = kw.value.value
                    elif kw.arg == "durability" and isinstance(
                            kw.value, ast.Constant):
                        info.durability = kw.value.value
                    elif kw.arg == "delivery" and isinstance(
                            kw.value, ast.Constant):
                        info.delivery = kw.value.value
                    elif kw.arg == "rest_sections" and isinstance(
                            kw.value, (ast.Tuple, ast.List)):
                        info.rest_sections = tuple(
                            e.value for e in kw.value.elts
                            if isinstance(e, ast.Constant))
                    elif kw.arg == "rest_separator":
                        info.rest_separator = _const_num(kw.value)
                schemas[code] = info
    return schemas


def _count_head(elts: List[ast.expr]) -> Optional[int]:
    """Arity of a literal payload head list; None when not resolvable."""
    n = 0
    for e in elts:
        if isinstance(e, ast.Starred):
            if isinstance(e.value, ast.Call) and \
                    call_name(e.value) in _SPLAT_ARITY:
                n += _SPLAT_ARITY[call_name(e.value)]
            else:
                return None
        else:
            n += 1
    return n


def _local_assignments(fn: ast.AST) -> Dict[str, ast.expr]:
    """name → last simple-RHS assignment within a function (one level of
    indirection for payload heads built in a local variable)."""
    out: Dict[str, ast.expr] = {}
    for node in walk_list(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
    return out


def _payload_shape(
    expr: Optional[ast.expr],
    local: Dict[str, ast.expr],
    builders: Dict[str, Tuple[Optional[int], Optional[bool]]],
    depth: int = 0,
) -> Tuple[Optional[int], Optional[bool]]:
    """(head_arity, has_rest) of a payload expression, or (None, None)."""
    if expr is None or depth > 3:
        return None, None
    if isinstance(expr, ast.Name):
        if expr.id in local:
            return _payload_shape(local[expr.id], local, builders, depth + 1)
        return None, None
    if isinstance(expr, ast.Call):
        name = call_name(expr)
        if name in ("asarray", "array") and expr.args:
            inner = expr.args[0]
            if isinstance(inner, (ast.List, ast.Tuple)):
                return _count_head(inner.elts), False
            if isinstance(inner, ast.Name) and inner.id in local:
                resolved = local[inner.id]
                if isinstance(resolved, (ast.List, ast.Tuple)):
                    return _count_head(resolved.elts), False
            return None, None
        if name == "zeros" and expr.args:
            n = const_int(expr.args[0])
            return (n, False) if n is not None else (None, None)
        if name == "concatenate" and expr.args and \
                isinstance(expr.args[0], (ast.List, ast.Tuple)):
            parts = expr.args[0].elts
            if not parts:
                return None, None
            head, _ = _payload_shape(parts[0], local, builders, depth + 1)
            if head is None:
                return None, None
            return head, len(parts) > 1
        if name in builders:
            return builders[name]
    return None, None


def extract_builders(
    pkg: Package,
) -> Dict[str, Tuple[Optional[int], Optional[bool]]]:
    """Payload-builder functions (``encode_join`` …): name → (head, rest)
    resolved from their return expression."""
    builders: Dict[str, Tuple[Optional[int], Optional[bool]]] = {}
    # two passes so builders may reference other builders defined later
    for _ in range(2):
        for src in pkg:
            for node in walk_list(src.tree):
                if not isinstance(node, ast.FunctionDef):
                    continue
                returns = [s for s in walk_list(node)
                           if isinstance(s, ast.Return) and s.value is not None]
                if len(returns) != 1:
                    continue
                local = _local_assignments(node)
                shape = _payload_shape(returns[0].value, local, builders)
                if shape[0] is not None:
                    builders[node.name] = shape
    return builders


def _code_args(call: ast.Call) -> List[Tuple[str, int, int]]:
    """Positional args that are (possibly wrapped) ``MessageCode.X``:
    list of (code_name, arg_index, line)."""
    out = []
    for i, arg in enumerate(call.args):
        names = message_code_names(arg)
        if len(names) == 1:
            out.append((names[0][0], i, names[0][1]))
    return out


def extract_sends(
    pkg: Package,
    builders: Dict[str, Tuple[Optional[int], Optional[bool]]],
) -> List[SendSite]:
    sends: List[SendSite] = []
    for src in pkg:
        for fn in walk_list(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            local = _local_assignments(fn)
            for node in walk_list(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if "send" not in name.lower():
                    continue
                for code, idx, line in _code_args(node):
                    payload = node.args[idx + 1] \
                        if idx + 1 < len(node.args) else None
                    head, rest = _payload_shape(payload, local, builders)
                    sends.append(SendSite(code, src.path, line, head, rest))
    return sends


def _size_guard(test: ast.expr) -> Dict[str, int]:
    """``payload.size >= K`` comparisons in a test: payload-name → K."""
    out: Dict[str, int] = {}
    for node in walk_list(test):
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.ops[0], ast.GtE)):
            continue
        left = node.left
        if isinstance(left, ast.Attribute) and left.attr == "size" and \
                isinstance(left.value, ast.Name):
            k = const_int(node.comparators[0])
            if k is not None:
                out[left.value.id] = k
    return out


def _handler_codes(test: ast.expr) -> List[Tuple[str, int, bool]]:
    """Codes a dispatch test selects: (name, line, is_positive_match).

    Positive matches are ``x == MessageCode.C`` and
    ``x in (MessageCode.A, …)``; ``!=`` / ``not in`` still count as handler
    *evidence* (the code is dispatched on) but carry no body to arity-check.
    """
    out = []
    for node in walk_list(test):
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1):
            continue
        positive = isinstance(node.ops[0], (ast.Eq, ast.In))
        if not isinstance(node.ops[0], (ast.Eq, ast.In, ast.NotEq, ast.NotIn)):
            continue
        for side in (node.left, *node.comparators):
            for name, line in message_code_names(side):
                out.append((name, line, positive))
    return out


def extract_handlers(pkg: Package) -> List[HandlerSite]:
    handlers: List[HandlerSite] = []
    for src in pkg:
        for node in walk_list(src.tree):
            if not isinstance(node, ast.If):
                continue
            codes = _handler_codes(node.test)
            if not codes:
                continue
            guards = _size_guard(node.test)
            payload_name = next(iter(guards), None)
            guard = guards.get(payload_name) if payload_name else None
            positive = [c for c in codes if c[2]]
            for name, line, is_pos in codes:
                handlers.append(HandlerSite(
                    name, src.path, line, src.plane,
                    guard_min=guard if is_pos else None,
                    body=node.body if is_pos else None,
                    payload_name=payload_name if is_pos and positive else None))
    return handlers


def _non_handler_references(pkg: Package) -> Set[str]:
    """Codes referenced outside dispatch comparisons, schema table and the
    enum definition itself — 'someone constructs/assigns this code'."""
    refs: Set[str] = set()
    for src in pkg:
        skip_spans: List[Tuple[int, int]] = []
        for node in walk_list(src.tree):
            if isinstance(node, ast.ClassDef) and node.name == "MessageCode":
                skip_spans.append((node.lineno, node.end_lineno or node.lineno))
            if _is_schema_table(node):
                skip_spans.append((node.lineno, node.end_lineno or node.lineno))
            if isinstance(node, ast.Compare):
                skip_spans.append((node.lineno, node.end_lineno or node.lineno))
        for name, line in message_code_names(src.tree):
            if not any(lo <= line <= hi for lo, hi in skip_spans):
                refs.add(name)
    return refs


# ----------------------------------------------------------------- checking

def _check_handler_body(
    site: HandlerSite, schema: SchemaInfo
) -> List[Finding]:
    """Constant subscripts / rest slices inside one positive handler body."""
    findings: List[Finding] = []
    if site.body is None or site.payload_name is None:
        return findings
    for stmt in site.body:
        for node in walk_list(stmt):
            if not (isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == site.payload_name):
                continue
            sl = node.slice
            idx = const_int(sl)
            if idx is not None:
                limit = schema.head if schema.rest is not None \
                    else schema.min_size
                if idx >= limit:
                    findings.append(Finding(
                        site.path, node.lineno, "DC104",
                        f"handler for MessageCode.{site.code} reads "
                        f"payload[{idx}] but the schema declares "
                        f"{schema.head} fixed field(s)"
                        + (f" before the '{schema.rest}' tail"
                           if schema.rest else "")))
            elif isinstance(sl, ast.Slice) and sl.lower is not None \
                    and sl.upper is None and sl.step is None:
                lower = const_int(sl.lower)
                if lower is None:
                    continue
                if schema.rest is None:
                    findings.append(Finding(
                        site.path, node.lineno, "DC104",
                        f"handler for MessageCode.{site.code} slices a "
                        f"payload[{lower}:] tail but the schema declares "
                        "no variable tail"))
                elif lower != schema.head:
                    findings.append(Finding(
                        site.path, node.lineno, "DC104",
                        f"handler for MessageCode.{site.code} slices the "
                        f"'{schema.rest}' tail at payload[{lower}:] but the "
                        f"schema puts it at payload[{schema.head}:]"))
    return findings


def check(pkg: Package) -> List[Finding]:
    enum, findings = extract_enum(pkg)
    if not enum:
        return findings  # nothing protocol-shaped in this tree
    schemas = extract_schemas(pkg)
    builders = extract_builders(pkg)
    sends = extract_sends(pkg, builders)
    handlers = extract_handlers(pkg)
    other_refs = _non_handler_references(pkg)

    # DC106 — the schema table must be total over the enum (both ways);
    # missing entries anchor at the table itself
    table_loc = None
    for src in pkg:
        for node in walk_list(src.tree):
            if _is_schema_table(node):
                table_loc = (src.path, node.lineno)
                break
        if table_loc:
            break
    for name in sorted(enum):
        if schemas and name not in schemas:
            findings.append(Finding(
                table_loc[0], table_loc[1], "DC106",
                f"MessageCode.{name} has no WIRE_SCHEMAS entry — declare "
                "its payload layout so the wire checks cover it"))
    for name, info in sorted(schemas.items()):
        if name not in enum:
            findings.append(Finding(
                info.path, info.line, "DC106",
                f"WIRE_SCHEMAS declares MessageCode.{name} but the enum "
                "does not define it"))

    sends_by_code: Dict[str, List[SendSite]] = {}
    for s in sends:
        sends_by_code.setdefault(s.code, []).append(s)
    handlers_by_code: Dict[str, List[HandlerSite]] = {}
    for h in handlers:
        handlers_by_code.setdefault(h.code, []).append(h)

    # DC102 — every sent code needs a handler on its declared plane(s)
    for code, sites in sorted(sends_by_code.items()):
        if code not in enum:
            continue
        hs = handlers_by_code.get(code, [])
        schema = schemas.get(code)
        planes = schema.handled_by if schema and schema.handled_by else ()
        ok = any(h.plane in planes for h in hs) if planes else bool(hs)
        if not ok:
            where = " or ".join(planes) if planes else "any plane"
            first = min(sites, key=lambda s: (s.path, s.line))
            findings.append(Finding(
                first.path, first.line, "DC102",
                f"MessageCode.{code} is sent here but no module of the "
                f"{where} handles it — frames arrive and rot"))

    # DC103 — a handler for a code nothing sends or references
    for code, hs in sorted(handlers_by_code.items()):
        if code not in enum:
            continue
        if code not in sends_by_code and code not in other_refs:
            first = min(hs, key=lambda h: (h.path, h.line))
            findings.append(Finding(
                first.path, first.line, "DC103",
                f"handler for MessageCode.{code} but nothing in the "
                "package ever sends or references it — dead protocol "
                "surface"))

    # DC104 — pack arity at send sites
    for code, sites in sorted(sends_by_code.items()):
        schema = schemas.get(code)
        if schema is None:
            continue
        for s in sites:
            if s.head is None:
                continue
            if schema.rest is None:
                if s.has_rest:
                    findings.append(Finding(
                        s.path, s.line, "DC104",
                        f"MessageCode.{code} sent with a variable tail but "
                        "the schema declares a fixed payload of "
                        f"{schema.head} field(s)"))
                elif s.head != schema.head:
                    findings.append(Finding(
                        s.path, s.line, "DC104",
                        f"MessageCode.{code} sent with {s.head} field(s) "
                        f"but the schema declares {schema.head}"))
            elif s.head != schema.head and not (
                    s.head == 0 and not s.has_rest and schema.rest_min == 0):
                findings.append(Finding(
                    s.path, s.line, "DC104",
                    f"MessageCode.{code} sent with a {s.head}-field head "
                    f"but the schema declares {schema.head} field(s) before "
                    f"the '{schema.rest}' tail"))

    # DC104 — unpack guards and body subscripts at handler sites
    for code, hs in sorted(handlers_by_code.items()):
        schema = schemas.get(code)
        if schema is None:
            continue
        for h in hs:
            if h.guard_min is not None:
                expected = schema.min_size
                # a guard shared by several codes must fit the smallest
                shared = [schemas[c].min_size
                          for c, sibs in handlers_by_code.items()
                          if c in schemas
                          for sib in sibs
                          if sib.path == h.path and sib.line != h.line
                          and sib.guard_min == h.guard_min
                          and abs(sib.line - h.line) <= 1]
                candidates = {expected, *shared}
                if h.guard_min not in candidates:
                    findings.append(Finding(
                        h.path, h.line, "DC104",
                        f"handler guard for MessageCode.{code} checks "
                        f"payload.size >= {h.guard_min} but the schema "
                        f"requires {expected}"))
            findings.extend(_check_handler_body(h, schema))

    findings.extend(_check_reliability_bypass(pkg))
    findings.extend(_check_durability_bypass(pkg))
    findings.extend(_check_backoff_bypass(pkg))
    return findings


# --------------------------------------------------------------- DC105

_RAW_TRANSPORTS = ("TCPTransport", "NativeTCPTransport")


def _reliable_aliases(src: SourceFile) -> Set[str]:
    """Local names bound to ReliableTransport: import aliases, plus the
    bare name for direct/attribute-qualified CODE references. Prose
    mentions in comments or docstrings do not count (the AST never sees
    them), so a suppression comment cannot opt a module in."""
    names: Set[str] = set()
    referenced = False
    for node in walk_list(src.tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "ReliableTransport":
                    names.add(alias.asname or alias.name)
        elif isinstance(node, ast.Name) and node.id == "ReliableTransport":
            referenced = True
        elif isinstance(node, ast.Attribute) and \
                node.attr == "ReliableTransport":
            referenced = True
    if referenced:
        names.add("ReliableTransport")
    return names


def _opted_in(src: SourceFile) -> bool:
    if _reliable_aliases(src):
        return True
    for node in walk_list(src.tree):
        if isinstance(node, ast.Call) and call_name(node) == "make_transport":
            if any(kw.arg == "reliable" for kw in node.keywords):
                return True
    return False


def _check_reliability_bypass(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    for src in pkg:
        if src.path.endswith(_LAYER_MODULE):
            continue  # the layer's own plumbing IS the raw path
        if not _opted_in(src):
            continue
        rel_names = _reliable_aliases(src)
        raw_aliases: Set[str] = {n for n in _RAW_TRANSPORTS}
        for node in walk_list(src.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name in _RAW_TRANSPORTS:
                        raw_aliases.add(alias.asname or alias.name)
        # (a) reaching under the wrapper: x.inner.send(...)
        for node in walk_list(src.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "send" and \
                    isinstance(node.func.value, ast.Attribute) and \
                    node.func.value.attr == "inner":
                findings.append(Finding(
                    src.path, node.lineno, "DC105",
                    "send through .inner bypasses the ReliableTransport "
                    "this module otherwise negotiates"))
        # (b) raw transport construction never handed to the wrapper
        for fn in walk_list(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            wrapped_names: Set[str] = set()
            raw_ctors: List[Tuple[Optional[str], int, str]] = []
            for node in walk_list(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name in rel_names:
                    for arg in node.args:
                        if isinstance(arg, ast.Name):
                            wrapped_names.add(arg.id)
                        elif isinstance(arg, ast.Call) and \
                                call_name(arg) in raw_aliases:
                            wrapped_names.add(f"@{arg.lineno}")
                elif name in raw_aliases:
                    raw_ctors.append((None, node.lineno, name))
            for node in walk_list(fn):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call) \
                        and call_name(node.value) in raw_aliases:
                    raw_ctors = [
                        (t if t is not None or line != node.value.lineno
                         else node.targets[0].id, line, cname)
                        for t, line, cname in raw_ctors
                    ]
            for target, line, cname in raw_ctors:
                if target in wrapped_names or f"@{line}" in wrapped_names:
                    continue
                findings.append(Finding(
                    src.path, line, "DC105",
                    f"raw {cname}(...) in a module that opted into "
                    "reliability — wrap it in ReliableTransport or via "
                    "make_transport(reliable=...)"))
    return findings


# --------------------------------------------------------------- DC107

_DURABILITY_HELPER = "atomic_write"


def _durability_aliases(src: SourceFile) -> Set[str]:
    """Local names bound to atomic_write — import aliases plus the bare
    name for direct / attribute-qualified CODE references (AST only, so a
    prose mention in a comment cannot opt a module in; DC105 precedent)."""
    names: Set[str] = set()
    referenced = False
    for node in walk_list(src.tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == _DURABILITY_HELPER:
                    names.add(alias.asname or alias.name)
        elif isinstance(node, ast.Name) and node.id == _DURABILITY_HELPER:
            referenced = True
        elif isinstance(node, ast.Attribute) and \
                node.attr == _DURABILITY_HELPER:
            referenced = True
    if referenced:
        names.add(_DURABILITY_HELPER)
    return names


def _defines_durability_helper(src: SourceFile) -> bool:
    return any(
        isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and node.name == _DURABILITY_HELPER
        for node in walk_list(src.tree))


def _open_write_mode(node: ast.Call) -> bool:
    """``open(..., "w"/"wb"/...)`` with a literal write mode (positional or
    ``mode=``); append modes are WAL-style and exempt."""
    if call_name(node) != "open":
        return False
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (isinstance(mode, ast.Constant) and isinstance(mode.value, str)
            and mode.value.startswith("w"))


def _is_os_replace(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr in ("replace", "rename")
            and isinstance(f.value, ast.Name) and f.value.id == "os")


def _check_durability_bypass(pkg: Package) -> List[Finding]:
    """DC107: hand-rolled ``open(.., "w") + os.replace`` persistence in a
    module that otherwise routes writes through ``utils.atomic_write`` —
    atomic, but not power-loss durable (no data fsync, no directory
    fsync), silently weaker than the module's own discipline."""
    findings: List[Finding] = []
    for src in pkg:
        if _defines_durability_helper(src):
            continue  # the helper's own plumbing IS the raw path
        if not _durability_aliases(src):
            continue  # not opted in to the durability discipline
        for fn in walk_list(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            opens = [n for n in walk_list(fn)
                     if isinstance(n, ast.Call) and _open_write_mode(n)]
            if not opens:
                continue
            if not any(isinstance(n, ast.Call) and _is_os_replace(n)
                       for n in walk_list(fn)):
                continue
            for n in opens:
                findings.append(Finding(
                    src.path, n.lineno, "DC107",
                    f"direct open(.., 'w') + os.replace persistence in "
                    f"{fn.name}() bypasses utils.atomic_write() — atomic "
                    "but not power-loss durable (no fsync of data or "
                    "rename)"))
    return findings


# --------------------------------------------------------------- DC108

_BACKOFF_HELPERS = ("Backoff", "jittered_backoff")


def _backoff_aliases(src: SourceFile) -> Set[str]:
    """Local names bound to the shared backoff policy — import aliases plus
    bare-name CODE references (AST only: prose mentions cannot opt a module
    in; DC105/DC107 precedent)."""
    names: Set[str] = set()
    referenced: Set[str] = set()
    for node in walk_list(src.tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name in _BACKOFF_HELPERS:
                    names.add(alias.asname or alias.name)
        elif isinstance(node, ast.Name) and node.id in _BACKOFF_HELPERS:
            referenced.add(node.id)
        elif isinstance(node, ast.Attribute) and \
                node.attr in _BACKOFF_HELPERS:
            referenced.add(node.attr)
    return names | referenced


def _defines_backoff_helper(src: SourceFile) -> bool:
    return any(
        isinstance(node, (ast.ClassDef, ast.FunctionDef,
                          ast.AsyncFunctionDef))
        and node.name in _BACKOFF_HELPERS
        for node in walk_list(src.tree))


def _is_literal_time_sleep(node: ast.Call) -> bool:
    """``time.sleep(<numeric constant>)`` or bare ``sleep(<constant>)``."""
    f = node.func
    named = (isinstance(f, ast.Attribute) and f.attr == "sleep"
             and isinstance(f.value, ast.Name) and f.value.id == "time")
    bare = isinstance(f, ast.Name) and f.id == "sleep"
    if not (named or bare):
        return False
    if len(node.args) != 1:
        return False
    arg = node.args[0]
    return isinstance(arg, ast.Constant) and isinstance(
        arg.value, (int, float))


def _check_backoff_bypass(pkg: Package) -> List[Finding]:
    """DC108: a hard-coded literal retry sleep inside a loop, in a module
    that otherwise adopted the shared jittered-backoff policy — a flat
    constant re-synchronizes every peer that timed out together (the retry
    storm the policy exists to break up)."""
    findings: List[Finding] = []
    for src in pkg:
        if _defines_backoff_helper(src):
            continue  # the policy's own plumbing IS the raw path
        if not _backoff_aliases(src):
            continue  # not opted in to the backoff discipline
        loops = [n for n in walk_list(src.tree)
                 if isinstance(n, (ast.While, ast.For, ast.AsyncFor))]
        for loop in loops:
            for node in walk_list(loop):
                if isinstance(node, ast.Call) and \
                        _is_literal_time_sleep(node):
                    findings.append(Finding(
                        src.path, node.lineno, "DC108",
                        "hard-coded retry sleep "
                        "inside a loop in a module that adopted the shared "
                        "backoff policy — use Backoff.sleep()/attempts() "
                        "(jittered, capped) instead of a flat constant"))
    return findings
