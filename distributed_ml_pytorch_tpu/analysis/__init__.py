"""distcheck — AST-based static analysis for the whole stack (ISSUE 4).

Four checker families over one findings engine:

- ``analysis.wire`` (DC1xx): the ``MessageCode`` registry, the declarative
  ``WIRE_SCHEMAS`` payload table, and every send/handler site cross-checked
  package-wide — collisions, sends without handlers, dead handlers,
  pack/unpack arity drift, and reliability-layer bypasses.
- ``analysis.concurrency`` (DC2xx): a static lock-acquisition graph plus
  guarded-by inference across the threaded PS / serving / coord classes —
  lock-order cycles, attributes mutated or read outside their owning lock,
  cross-thread shared state with no lock, and thread join/daemon
  discipline. Cross-validated at runtime by ``analysis.witness``.
- ``analysis.tracing_hygiene`` (DC3xx): inside jit/shard_map programs —
  Python branching on traced values, host-state reads frozen at trace
  time, PRNG key reuse without split/fold_in, donated-buffer reuse.
- ``analysis.protomodel`` (DC4xx, ISSUE 13): the wire protocol as a
  checkable artifact — dedup-key / durability / delivery annotations on
  ``WIRE_SCHEMAS`` cross-checked against the real send, handler, WAL and
  ack sites (reliable-send-without-dedup, apply-before-WAL,
  ack-before-fsync, ungated incarnation updates, separator-less tail
  evolution). The same extracted model feeds ``analysis.distmodel``, the
  bounded explicit-state checker behind ``make distmodel``.

Run it: ``python -m distributed_ml_pytorch_tpu.analysis`` or ``make lint``.
Suppress a finding: ``# distcheck: ignore[DC2xx] <required reason>``.
Baseline: ``tests/distcheck_baseline.txt`` (regen via
``tests/regen_distcheck_baseline.py``); tier-1 asserts no new findings.
"""

from distributed_ml_pytorch_tpu.analysis.cli import (  # noqa: F401
    analyze,
    analyze_path,
    main,
)
from distributed_ml_pytorch_tpu.analysis.core import (  # noqa: F401
    Finding,
    Package,
    load_package,
)
