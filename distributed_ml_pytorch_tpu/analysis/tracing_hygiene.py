"""distcheck DC3xx — tracing hygiene inside jit/shard_map programs.

A traced function runs ONCE at trace time; anything Python-level inside it
is baked into the compiled program. The PR-3 dp×pp×tp divergence was
exactly this class of bug (sharding-dependent init under a traced code
path), and these checks make the discipline mechanical:

- **DC301** — Python branching (``if``/``while``) on a traced value.
  Tracing either crashes (TracerBoolConversionError) or, worse, silently
  specializes on the tracer's first value. Shape-derived tests
  (``x.shape``, ``x.ndim``, ``len(x)``, ``is None``, ``isinstance``) are
  static and exempt.
- **DC302** — host-state reads (``time.*``, ``random.*``, ``np.random.*``,
  ``datetime.*``, ``os.environ``/``os.getenv``) inside a traced function:
  the value observed at trace time is frozen into every execution.
- **DC303** — a PRNG key consumed by more than one ``jax.random`` sampler
  without an intervening ``split``/``fold_in``: identical randomness where
  independence was intended.
- **DC304** — a buffer passed at a ``donate_argnums`` position used again
  after the call: donation invalidates the buffer; XLA may have already
  reused its memory.
- **DC305** — a host-device sync on a traced value inside a jit/scan step
  body: ``.block_until_ready()`` / ``.item()`` on a traced value, or
  ``np.asarray``/``np.array``/``jax.device_get`` applied to one. The perf
  twin of the correctness checks above: at best these concretization
  attempts crash at trace time; where they survive (e.g. inside code that
  is only *sometimes* jitted) they serialize the device pipeline — the
  exact dispatch-stall class the scanned trainers exist to avoid.

Traced functions are found structurally: ``@jax.jit`` / ``@jit`` /
``@partial(jax.jit, …)`` decorations, ``jax.jit(f, …)`` /
``jax.shard_map(f, …)`` wrapping of a locally defined ``f``, and every
``def`` nested inside a traced function (scan bodies, loss closures).
Parameters listed in ``static_argnums`` are not traced. Taint propagates
through simple assignments; anything derived from ``.shape``/``len`` is
demoted back to static.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from distributed_ml_pytorch_tpu.analysis.core import (
    Finding,
    Package,
    SourceFile,
    call_name,
    const_int,
    dotted_name,
    walk_list,
)

#: jax.random functions that DERIVE keys (consuming none of the stream)
_KEY_DERIVERS = frozenset({
    "key", "PRNGKey", "split", "fold_in", "wrap_key_data", "key_data",
    "clone",
})

#: dotted prefixes whose calls read host state
_HOST_STATE_PREFIXES = (
    "time.", "random.", "datetime.", "np.random.", "numpy.random.",
)
_HOST_STATE_CALLS = frozenset({"os.getenv", "os.environ.get", "open"})

_KEY_PARAM_HINTS = ("rng", "key", "prng")

#: method calls that force a device->host sync on their receiver (DC305)
_SYNC_ATTR_CALLS = frozenset({"block_until_ready", "item"})
#: functions that pull a device value to host when given one (DC305)
_SYNC_FN_CALLS = frozenset({
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.device_get",
})


def _jit_call_info(call: ast.Call) -> Optional[dict]:
    """``jax.jit`` / ``partial(jax.jit, …)`` call → its static/donate
    argnums (literal tuples/ints only); None if not a jit expression."""
    name = dotted_name(call.func)
    args = list(call.args)
    if name in ("partial", "functools.partial") and args:
        inner = dotted_name(args[0])
        if inner in ("jax.jit", "jit"):
            return _argnums(call.keywords)
        return None
    if name in ("jax.jit", "jit"):
        return _argnums(call.keywords)
    return None


def _argnums(keywords) -> dict:
    out = {"static": set(), "donate": set()}
    for kw in keywords:
        if kw.arg not in ("static_argnums", "donate_argnums"):
            continue
        key = "static" if kw.arg == "static_argnums" else "donate"
        val = kw.value
        if isinstance(val, (ast.Tuple, ast.List)):
            for e in val.elts:
                n = const_int(e)
                if n is not None:
                    out[key].add(n)
        else:
            n = const_int(val)
            if n is not None:
                out[key].add(n)
    return out


class TracedFn:
    def __init__(self, fn: ast.FunctionDef, static: Set[int],
                 donate: Set[int], outer_taint: Set[str]):
        self.fn = fn
        self.static = static
        self.donate = donate
        self.outer_taint = outer_taint

    @property
    def param_names(self) -> List[str]:
        return [a.arg for a in self.fn.args.args]

    def traced_params(self) -> Set[str]:
        return {name for i, name in enumerate(self.param_names)
                if i not in self.static}


def _scope_walk(nodes: List[ast.AST]):
    """Every node reachable from ``nodes`` without entering a nested
    ``def`` scope: FunctionDef bodies stay unexpanded, but their
    decorators and default-arg expressions — which evaluate in THIS
    scope — are visited, and class bodies, control-flow blocks, and
    lambda bodies are transparent (a ``jax.jit(f)`` / ``lax.scan(body,…)``
    call sited inside a lambda resolves ``f``/``body`` through the same
    lexical chain; lambda params cannot shadow a ``def``)."""
    queue = list(nodes)
    i = 0
    while i < len(queue):
        n = queue[i]
        i += 1
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            queue.extend(n.decorator_list)
            queue.extend(n.args.defaults)
            queue.extend(d for d in n.args.kw_defaults if d is not None)
        else:
            queue.extend(ast.iter_child_nodes(n))


def find_traced(src: SourceFile) -> List[TracedFn]:
    """Every traced function in a file (decorated, wrapped, or nested)."""
    traced: Dict[ast.FunctionDef, TracedFn] = {}

    def mark(fn: ast.FunctionDef, static=(), donate=(), outer=frozenset()):
        if fn not in traced:
            traced[fn] = TracedFn(fn, set(static), set(donate), set(outer))

    def process_scope(children: List[ast.AST],
                      scopes: List[Dict[str, ast.FunctionDef]]) -> None:
        # a callback name at a call site resolves LEXICALLY — innermost
        # scope first — not through a file-wide name map: ``def body`` is
        # this repo's convention for scan bodies and host-only helpers
        # alike, so first-def-wins by bare name marks the wrong function
        local: Dict[str, ast.FunctionDef] = {}
        for n in _scope_walk(children):
            if isinstance(n, ast.FunctionDef):
                local.setdefault(n.name, n)
        stack = scopes + [local]

        def resolve(name: str) -> Optional[ast.FunctionDef]:
            for scope in reversed(stack):
                if name in scope:
                    return scope[name]
            return None

        for node in _scope_walk(children):
            # async bodies are a scope like any other — a jitted helper
            # nested in an ``async def`` must still be found
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if isinstance(node, ast.FunctionDef):
                    for dec in node.decorator_list:
                        if isinstance(dec, ast.Call):
                            info = _jit_call_info(dec)
                            if info is not None:
                                mark(node, info["static"], info["donate"])
                        elif dotted_name(dec) in ("jax.jit", "jit"):
                            mark(node)
                process_scope(node.body, stack)
            if isinstance(node, ast.Call):
                info = _jit_call_info(node)
                wrapped = None
                if info is not None and node.args:
                    wrapped = node.args[0]
                elif dotted_name(node.func) in ("jax.shard_map",
                                                "shard_map") \
                        and node.args:
                    wrapped, info = node.args[0], {"static": set(),
                                                   "donate": set()}
                else:
                    # scan/loop step bodies trace even when the enclosing
                    # function is not itself jitted (ISSUE 9 / DC305):
                    # scan(body, …) at args[0]; fori_loop(lo, hi, body, …)
                    # at args[2]; while_loop(cond, body, …) traces BOTH
                    body_positions = {
                        "jax.lax.scan": (0,), "lax.scan": (0,),
                        "jax.lax.fori_loop": (2,), "lax.fori_loop": (2,),
                        "jax.lax.while_loop": (0, 1),
                        "lax.while_loop": (0, 1),
                    }.get(dotted_name(node.func), ())
                    for pos in body_positions:
                        if pos < len(node.args) and \
                                isinstance(node.args[pos], ast.Name):
                            target = resolve(node.args[pos].id)
                            if target is not None:
                                mark(target)
                if wrapped is None:
                    continue
                # unwrap jax.jit(jax.shard_map(f, …), …)
                while isinstance(wrapped, ast.Call) and dotted_name(
                        wrapped.func) in ("jax.shard_map", "shard_map") \
                        and wrapped.args:
                    wrapped = wrapped.args[0]
                if isinstance(wrapped, ast.Name):
                    target = resolve(wrapped.id)
                    if target is not None:
                        mark(target, info["static"], info["donate"])

    process_scope(list(src.tree.body), [])

    # nested defs inside traced functions are traced with the outer taint.
    # A body may already be directly marked (a lax.scan callback inside a
    # jitted fn): UNION the outer taint in and re-process — taint only
    # grows, so the loop terminates
    frontier = list(traced.values())
    while frontier:
        tf = frontier.pop()
        outer = tf.traced_params() | tf.outer_taint
        for node in walk_list(tf.fn):
            if isinstance(node, ast.FunctionDef) and node is not tf.fn:
                if node not in traced:
                    inner = TracedFn(node, set(), set(), set(outer))
                    traced[node] = inner
                    frontier.append(inner)
                elif not (outer <= traced[node].outer_taint):
                    traced[node].outer_taint |= outer
                    frontier.append(traced[node])
    return list(traced.values())


def _shape_derived(expr: ast.expr) -> bool:
    """Static even when built from traced names: shapes, dims, lengths."""
    for node in walk_list(expr):
        if isinstance(node, ast.Attribute) and node.attr in (
                "shape", "ndim", "dtype"):
            return True
        if isinstance(node, ast.Call) and call_name(node) in (
                "len", "isinstance", "hasattr", "type"):
            return True
    return False


def _is_none_test(test: ast.expr) -> bool:
    return isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)


def _names(expr: ast.expr) -> Set[str]:
    return {n.id for n in walk_list(expr) if isinstance(n, ast.Name)}


def _check_one(src: SourceFile, tf: TracedFn) -> List[Finding]:
    findings: List[Finding] = []
    fn = tf.fn
    taint = tf.traced_params() | set(tf.outer_taint)
    keys: Set[str] = {
        name for name in tf.traced_params()
        if any(h in name.lower() for h in _KEY_PARAM_HINTS)}
    consumed: Dict[str, int] = {}

    nested = {n for n in walk_list(fn)
              if isinstance(n, ast.FunctionDef) and n is not fn}
    nested_spans = [(n.lineno, n.end_lineno or n.lineno) for n in nested]

    def skip(node: ast.AST) -> bool:
        # nested defs are their own TracedFn — don't double-report
        return any(lo < node.lineno <= hi or
                   (lo == node.lineno and isinstance(node, ast.FunctionDef))
                   for lo, hi in nested_spans)

    for node in walk_list(fn):
        if node is fn or not hasattr(node, "lineno") or skip(node):
            continue
        # --- taint propagation through simple assignments
        if isinstance(node, ast.Assign):
            rhs_tainted = bool(_names(node.value) & taint) and \
                not _shape_derived(node.value)
            for target in node.targets:
                for name_node in walk_list(target):
                    if isinstance(name_node, ast.Name):
                        if rhs_tainted:
                            taint.add(name_node.id)
                        else:
                            taint.discard(name_node.id)
                        consumed.pop(name_node.id, None)
                        keys.discard(name_node.id)
            if isinstance(node.value, ast.Call) and \
                    dotted_name(node.value.func).startswith("jax.random."):
                der = dotted_name(node.value.func).rsplit(".", 1)[-1]
                if der in _KEY_DERIVERS:
                    for target in node.targets:
                        for name_node in walk_list(target):
                            if isinstance(name_node, ast.Name):
                                keys.add(name_node.id)
        # --- DC301: Python control flow on traced values
        if isinstance(node, (ast.If, ast.While)):
            test = node.test
            if _names(test) & taint and not _shape_derived(test) \
                    and not _is_none_test(test):
                findings.append(Finding(
                    src.path, node.lineno, "DC301",
                    f"Python {'while' if isinstance(node, ast.While) else 'if'}"
                    " on a traced value inside a jit/shard_map function — "
                    "use jnp.where / lax.cond, or mark the argument static"))
        # --- DC302 / DC303 / DC305: calls
        if isinstance(node, ast.Call):
            # DC305: sync methods on a traced receiver (x.block_until_ready()
            # / loss.item()), including subscripted receivers (losses[-1])
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SYNC_ATTR_CALLS and \
                    _names(node.func.value) & taint and \
                    not _shape_derived(node.func.value):
                findings.append(Finding(
                    src.path, node.lineno, "DC305",
                    f".{node.func.attr}() on a traced value inside a "
                    "jit/scan body — a host-device sync in the step hot "
                    "path; fetch AFTER the jitted call returns"))
            dname = dotted_name(node.func)
            if dname:
                if dname in _SYNC_FN_CALLS and any(
                        _names(a) & taint and not _shape_derived(a)
                        for a in node.args):
                    findings.append(Finding(
                        src.path, node.lineno, "DC305",
                        f"{dname}(...) on a traced value inside a jit/scan "
                        "body — a device->host transfer in the step hot "
                        "path; use jnp ops inside, convert outside"))
                if any(dname.startswith(p) for p in _HOST_STATE_PREFIXES) \
                        or dname in _HOST_STATE_CALLS:
                    findings.append(Finding(
                        src.path, node.lineno, "DC302",
                        f"host-state read {dname}(...) inside a traced "
                        "function — its value is frozen at trace time"))
                if dname.startswith("jax.random."):
                    sampler = dname.rsplit(".", 1)[-1]
                    if sampler not in _KEY_DERIVERS and node.args:
                        first = node.args[0]
                        if isinstance(first, ast.Name) and first.id in keys:
                            consumed[first.id] = consumed.get(first.id, 0) + 1
                            if consumed[first.id] == 2:
                                findings.append(Finding(
                                    src.path, node.lineno, "DC303",
                                    f"PRNG key '{first.id}' consumed by more "
                                    "than one jax.random sampler without "
                                    "split/fold_in — identical randomness "
                                    "where independence was intended"))
            # bare key names passed as rngs={...} values count as consumption
            for kw in node.keywords:
                if kw.arg == "rngs" and isinstance(kw.value, ast.Dict):
                    for val in kw.value.values:
                        if isinstance(val, ast.Name) and val.id in keys:
                            consumed[val.id] = consumed.get(val.id, 0) + 1
                            if consumed[val.id] == 2:
                                findings.append(Finding(
                                    src.path, val.lineno, "DC303",
                                    f"PRNG key '{val.id}' reused as an rngs "
                                    "value after already being consumed — "
                                    "split or fold_in first"))
    return findings


def _check_donation(src: SourceFile) -> List[Finding]:
    """DC304: a donated argument used after the donating call."""
    findings: List[Finding] = []
    donated: Dict[str, Set[int]] = {}
    for node in walk_list(src.tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    info = _jit_call_info(dec)
                    if info and info["donate"]:
                        donated[node.name] = info["donate"]
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            info = _jit_call_info(node.value)
            if info and info["donate"]:
                donated[node.targets[0].id] = info["donate"]
    if not donated:
        return findings
    for fn in walk_list(src.tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_block(src, fn.body, [], donated, findings)
    return findings


def _child_blocks(stmt: ast.stmt) -> List[List[ast.stmt]]:
    """The statement lists nested in a compound statement (loop/branch/try
    bodies) — where most real donating calls actually live."""
    blocks = []
    for field in ("body", "orelse", "finalbody"):
        sub = getattr(stmt, field, None)
        if isinstance(sub, list) and sub and isinstance(sub[0], ast.stmt):
            blocks.append(sub)
    for handler in getattr(stmt, "handlers", []) or []:
        blocks.append(handler.body)
    return blocks


def _scan_block(src: SourceFile, body: List[ast.stmt], tail: List[ast.stmt],
                donated: Dict[str, Set[int]],
                findings: List[Finding]) -> None:
    """Scan one statement block for donate-then-reuse. ``tail`` carries the
    statements that follow this block in every enclosing block, so a call
    inside an ``if``/``for`` body is still checked against the code after
    the compound statement — without cross-matching sibling branches."""
    for i, stmt in enumerate(body):
        later = body[i + 1:] + tail
        call = _stmt_call(stmt)
        if call is not None:
            cname = call_name(call)
            if cname in donated:
                rebound = _assigned_names(stmt)
                for idx in donated[cname]:
                    if idx >= len(call.args):
                        continue
                    arg = call.args[idx]
                    if not isinstance(arg, ast.Name) or arg.id in rebound:
                        continue
                    for after in later:
                        if arg.id in _assigned_names(after):
                            break
                        used = [n for n in walk_list(after)
                                if isinstance(n, ast.Name) and n.id == arg.id
                                and isinstance(n.ctx, ast.Load)]
                        if used:
                            findings.append(Finding(
                                src.path, used[0].lineno, "DC304",
                                f"'{arg.id}' was donated to {cname}(...) at "
                                f"line {call.lineno} (donate_argnums) and "
                                "is used again here — the buffer may "
                                "already be reused by XLA"))
                            break
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested defs run later, not in this flow
        for block in _child_blocks(stmt):
            _scan_block(src, block, later, donated, findings)


def _stmt_call(stmt: ast.stmt) -> Optional[ast.Call]:
    if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
        return stmt.value
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        return stmt.value
    return None


def _assigned_names(stmt: ast.stmt) -> Set[str]:
    out: Set[str] = set()
    if isinstance(stmt, ast.Assign):
        for target in stmt.targets:
            for node in walk_list(target):
                if isinstance(node, ast.Name):
                    out.add(node.id)
    elif isinstance(stmt, ast.AugAssign) and isinstance(stmt.target, ast.Name):
        out.add(stmt.target.id)
    return out


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    for src in pkg:
        for tf in find_traced(src):
            findings.extend(_check_one(src, tf))
        findings.extend(_check_donation(src))
    return findings
