"""distcheck DC2xx — static concurrency checks over the threaded planes.

The PS, serving and coord planes are all "threads around queues and locks":
listener/pump/retry/renew threads mutating state the main loop reads. The
invariants here are machine-checkable from the AST:

- **DC201** — an attribute mutated both under and outside its owning lock.
  The owning lock is *inferred from majority use*: an attribute with >= 2
  mutation sites under one ``self``-lock and fewer unguarded ones is
  treated as guarded-by that lock, and each unguarded mutation is flagged.
- **DC202** — a cycle in the static lock-acquisition graph. Edges come
  from lexically nested ``with self.A: … with self.B:`` blocks and from
  same-class method calls made while a lock is held (transitively closed).
- **DC203** — a thread created without a join/daemon discipline: neither
  ``daemon=True`` at construction (directly, or inherited from a
  ``Thread`` subclass whose ``__init__`` passes it), nor a ``.join(`` in
  the creating scope. Such threads strand interpreter shutdown.
- **DC204** — an attribute whose every mutation is under one lock (clearly
  lock-owned) read without that lock. Reads are where torn state actually
  escapes — a resize swap observed halfway, a dict iterated mid-update.
- **DC205** — cross-thread shared state with no lock at all: a class whose
  method is a ``threading.Thread`` target (directly, via an instance
  variable, or by subclassing ``Thread``) where an attribute is mutated on
  one side of the thread boundary and referenced on the other, with no
  lock anywhere near it.

Noise control, so the checks stay sharp on this codebase's idioms:
``__init__`` never counts (construction happens-before the thread start);
attributes held in thread-safe containers (``Event``/``Queue``/``Lock``/
``Condition``/``Semaphore``/``deque``) are exempt; attributes only ever
assigned boolean constants are exempt from DC205 (a monotonic flag store
is atomic under the GIL — the revive/degrade flags are this on purpose);
and any attribute with at least one guarded access is left to the
sharper DC201/DC204 rules instead of DC205.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from distributed_ml_pytorch_tpu.analysis.core import (
    Finding,
    Package,
    SourceFile,
    call_name,
    self_attr,
    walk_list,
)

#: method names that mutate their receiver in place
MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "discard", "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "push", "heappush",
})

#: constructors whose instances are safe to share without a lock
_SAFE_CTORS = frozenset({
    "Event", "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue", "Lock",
    "RLock", "Condition", "Semaphore", "BoundedSemaphore", "Barrier",
    "deque", "local",
})

_LOCK_CTORS = frozenset({"Lock", "RLock"})


@dataclasses.dataclass
class Access:
    attr: str
    line: int
    locks: frozenset  # self-lock attrs held at this point
    method: str


@dataclasses.dataclass
class ClassInfo:
    name: str
    path: str
    line: int
    src: SourceFile
    bases: List[str]
    methods: Dict[str, ast.FunctionDef] = dataclasses.field(default_factory=dict)
    lock_attrs: Dict[str, int] = dataclasses.field(default_factory=dict)
    safe_attrs: Set[str] = dataclasses.field(default_factory=set)
    bool_attrs: Set[str] = dataclasses.field(default_factory=set)
    nonbool_assigned: Set[str] = dataclasses.field(default_factory=set)
    mutations: List[Access] = dataclasses.field(default_factory=list)
    reads: List[Access] = dataclasses.field(default_factory=list)
    #: method → same-class methods it calls
    calls: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)
    #: (held_locks, acquired_lock, line) triples for the lock graph
    acquires: List[Tuple[frozenset, str, int]] = dataclasses.field(
        default_factory=list)
    #: (held_locks, called_method, line) for transitive lock-graph edges
    held_calls: List[Tuple[frozenset, str, int]] = dataclasses.field(
        default_factory=list)
    #: methods driven by a thread (Thread targets / Thread-subclass run)
    thread_entries: Set[str] = dataclasses.field(default_factory=set)
    daemonic: bool = False  # Thread subclass passing daemon=True upward


def _is_thread_ctor(node: ast.Call) -> bool:
    return call_name(node) == "Thread"


def _is_thread_join(node: ast.AST) -> bool:
    """A ``.join(...)`` call that plausibly joins a THREAD — not
    ``", ".join(parts)``. String receivers (constants, f-strings) are
    excluded, and any positional argument must look like a timeout (a
    numeric constant), since ``str.join`` always takes an iterable."""
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"):
        return False
    if isinstance(node.func.value, (ast.Constant, ast.JoinedStr)):
        return False
    if len(node.args) > 1:
        return False
    if node.args:
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, (int, float))):
            return False
    return all(kw.arg == "timeout" for kw in node.keywords)


def _with_lock_attr(item: ast.withitem, lock_attrs: Dict[str, int]) -> Optional[str]:
    attr = self_attr(item.context_expr)
    if attr is not None and attr in lock_attrs:
        return attr
    return None


class _MethodWalker(ast.NodeVisitor):
    """Collect accesses/locks/calls for one method body."""

    def __init__(self, info: ClassInfo, method: str):
        self.info = info
        self.method = method
        self.held: Tuple[str, ...] = ()

    # ----------------------------------------------------------- lock scope
    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            lock = _with_lock_attr(item, self.info.lock_attrs)
            if lock is not None:
                self.info.acquires.append(
                    (frozenset(self.held), lock, node.lineno))
                acquired.append(lock)
            else:
                self.visit(item.context_expr)
        self.held = self.held + tuple(acquired)
        for stmt in node.body:
            self.visit(stmt)
        if acquired:
            self.held = self.held[: len(self.held) - len(acquired)]

    # ------------------------------------------------------------ accesses
    def _note_mut(self, attr: str, line: int) -> None:
        self.info.mutations.append(
            Access(attr, line, frozenset(self.held), self.method))

    def _note_read(self, attr: str, line: int) -> None:
        self.info.reads.append(
            Access(attr, line, frozenset(self.held), self.method))

    def _mut_target(self, target: ast.expr) -> None:
        attr = self_attr(target)
        if attr is not None:
            self._note_mut(attr, target.lineno)
            return
        if isinstance(target, ast.Subscript):
            self._mut_target(target.value)
        elif isinstance(target, ast.Attribute):
            # self.a.b = … mutates the object held in self.a
            base = self_attr(target.value)
            if base is not None:
                self._note_mut(base, target.lineno)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._mut_target(elt)
        elif isinstance(target, ast.Starred):
            self._mut_target(target.value)

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._mut_target(target)
        # track bool-flag attrs (exempted from DC205 as GIL-atomic stores)
        is_bool = isinstance(node.value, ast.Constant) and \
            isinstance(node.value.value, bool)
        for target in node.targets:
            attr = self_attr(target)
            if attr is not None:
                if is_bool:
                    self.info.bool_attrs.add(attr)
                else:
                    self.info.nonbool_assigned.add(attr)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._mut_target(node.target)
        attr = self_attr(node.target)
        if attr is not None:
            self.info.nonbool_assigned.add(attr)
        self.visit(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in MUTATORS:
                base = self_attr(node.func.value)
                if base is not None:
                    self._note_mut(base, node.lineno)
                elif isinstance(node.func.value, ast.Attribute):
                    root = self_attr(node.func.value.value)
                    if root is not None:
                        self._note_mut(root, node.lineno)
            # same-class method call: self.m(...)
            target = self_attr(node.func)
            if target is not None and target in self.info.methods:
                self.info.calls.setdefault(self.method, set()).add(target)
                self.info.held_calls.append(
                    (frozenset(self.held), target, node.lineno))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self._note_read(attr, node.lineno)
        self.generic_visit(node)

    # nested defs (listener closures): same thread context as creator —
    # unless they are Thread targets, which collect() handles separately
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)


def _collect_class(src: SourceFile, node: ast.ClassDef) -> ClassInfo:
    info = ClassInfo(
        name=node.name, path=src.path, line=node.lineno, src=src,
        bases=[b.attr if isinstance(b, ast.Attribute) else
               b.id if isinstance(b, ast.Name) else "" for b in node.bases])
    for stmt in node.body:
        if isinstance(stmt, ast.FunctionDef):
            info.methods[stmt.name] = stmt
    # first pass: lock / safe attrs (any method, __init__ included)
    for name, fn in info.methods.items():
        for sub in walk_list(fn):
            if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
                ctor = call_name(sub.value)
                for target in sub.targets:
                    attr = self_attr(target)
                    if attr is None:
                        continue
                    if ctor in _LOCK_CTORS:
                        info.lock_attrs[attr] = sub.lineno
                    if ctor in _SAFE_CTORS:
                        info.safe_attrs.add(attr)
    # Thread subclass passing daemon=True to super().__init__
    init = info.methods.get("__init__")
    if init is not None:
        for sub in walk_list(init):
            if isinstance(sub, ast.Call) and call_name(sub) == "__init__":
                if any(kw.arg == "daemon" and
                       isinstance(kw.value, ast.Constant) and
                       kw.value.value is True for kw in sub.keywords):
                    info.daemonic = True
    # second pass: accesses per method (construction is happens-before)
    for name, fn in info.methods.items():
        if name in ("__init__", "__post_init__"):
            continue
        walker = _MethodWalker(info, name)
        for stmt in fn.body:
            walker.visit(stmt)
    return info


def _merge_inherited(classes: Dict[str, ClassInfo]) -> None:
    """Pull package-internal base-class state into subclasses so closure
    and guarded-by analysis see inherited methods (Listener ← MessageListener)."""
    def bases_of(info: ClassInfo) -> List[ClassInfo]:
        return [classes[b] for b in info.bases if b in classes]

    # simple one-level-at-a-time fixpoint (hierarchies here are shallow)
    for _ in range(3):
        for info in classes.values():
            for base in bases_of(info):
                for name, fn in base.methods.items():
                    info.methods.setdefault(name, fn)
                info.lock_attrs.update(
                    {k: v for k, v in base.lock_attrs.items()
                     if k not in info.lock_attrs})
                info.safe_attrs |= base.safe_attrs
                info.bool_attrs |= base.bool_attrs
                info.nonbool_assigned |= base.nonbool_assigned
                for acc in base.mutations:
                    if acc not in info.mutations:
                        info.mutations.append(acc)
                for acc in base.reads:
                    if acc not in info.reads:
                        info.reads.append(acc)
                for m, callees in base.calls.items():
                    info.calls.setdefault(m, set()).update(callees)


def _is_thread_subclass(info: ClassInfo, classes: Dict[str, ClassInfo]) -> bool:
    seen = set()
    stack = [info]
    while stack:
        cur = stack.pop()
        if cur.name in seen:
            continue
        seen.add(cur.name)
        for base in cur.bases:
            if base == "Thread":
                return True
            if base in classes:
                stack.append(classes[base])
    return False


def _class_daemonic(info: ClassInfo, classes: Dict[str, ClassInfo]) -> bool:
    seen = set()
    stack = [info]
    while stack:
        cur = stack.pop()
        if cur.name in seen:
            continue
        seen.add(cur.name)
        if cur.daemonic:
            return True
        stack.extend(classes[b] for b in cur.bases if b in classes)
    return False


def _find_thread_targets(
    pkg: Package, classes: Dict[str, ClassInfo]
) -> List[Finding]:
    """Register thread-entry methods on their classes and run the DC203
    join/daemon-discipline check over every Thread construction."""
    findings: List[Finding] = []
    for src in pkg:
        for node in walk_list(src.tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            # the construction's scope: innermost function, else the module
            scope = _enclosing_function(src, node) or src.tree
            # local variable → class-name map (srv = ElasticShardServer(...))
            var_class: Dict[str, str] = {}
            for sub in walk_list(scope):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name) \
                        and isinstance(sub.value, ast.Call):
                    ctor = call_name(sub.value)
                    if ctor in classes:
                        var_class[sub.targets[0].id] = ctor
            has_join = any(_is_thread_join(n) for n in walk_list(scope))
            target = next(
                (kw.value for kw in node.keywords if kw.arg == "target"),
                None)
            daemon = any(
                kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True for kw in node.keywords)
            if target is not None:
                if isinstance(target, ast.Attribute) and \
                        isinstance(target.value, ast.Name):
                    owner = target.value.id
                    if owner == "self":
                        # .get: function-local classes are not in the
                        # top-level table — their threads still get the
                        # DC203 check below, just no DC205 closure
                        cls = _enclosing_class(src, node)
                        info = classes.get(cls) if cls is not None else None
                        if info is not None:
                            info.thread_entries.add(target.attr)
                    elif owner in var_class:
                        classes[var_class[owner]].thread_entries.add(
                            target.attr)
            if not daemon and not has_join:
                findings.append(Finding(
                    src.path, node.lineno, "DC203",
                    "thread created without daemon=True or a join() in "
                    "the creating scope — it will strand interpreter "
                    "shutdown"))
        # Thread-subclass instantiations: daemon discipline by construction?
        for node in walk_list(src.tree):
            if isinstance(node, ast.Call):
                ctor = call_name(node)
                info = classes.get(ctor)
                if info is not None and _is_thread_subclass(info, classes) \
                        and not _class_daemonic(info, classes):
                    daemon = any(
                        kw.arg == "daemon" and
                        isinstance(kw.value, ast.Constant) and
                        kw.value.value is True for kw in node.keywords)
                    enclosing = _enclosing_function(src, node)
                    has_join = enclosing is not None and any(
                        _is_thread_join(n) for n in walk_list(enclosing))
                    if not daemon and not has_join:
                        findings.append(Finding(
                            src.path, node.lineno, "DC203",
                            f"{ctor} (a Thread subclass that does not set "
                            "daemon=True) created without daemon=True or a "
                            "join() in the creating scope"))
    # Thread subclasses: run() is a thread entry
    for info in classes.values():
        if _is_thread_subclass(info, classes) and "run" in info.methods:
            info.thread_entries.add("run")
    return findings


def _enclosing_class(src: SourceFile, node: ast.AST) -> Optional[str]:
    for cls in walk_list(src.tree):
        if isinstance(cls, ast.ClassDef) and \
                cls.lineno <= node.lineno <= (cls.end_lineno or cls.lineno):
            return cls.name
    return None


def _enclosing_function(src: SourceFile, node: ast.AST) -> Optional[ast.AST]:
    best = None
    for fn in walk_list(src.tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                fn.lineno <= node.lineno <= (fn.end_lineno or fn.lineno):
            if best is None or fn.lineno > best.lineno:
                best = fn
    return best


def _closure(info: ClassInfo, roots: Set[str]) -> Set[str]:
    out = set()
    stack = list(roots)
    while stack:
        m = stack.pop()
        if m in out:
            continue
        out.add(m)
        stack.extend(info.calls.get(m, ()))
    return out


def collect_lock_sites(pkg: Package) -> Set[Tuple[str, int]]:
    """(path, line) of every ``threading.Lock()/RLock()`` creation — the
    runtime witness cross-validates its observed locks against this."""
    sites: Set[Tuple[str, int]] = set()
    for src in pkg:
        for node in walk_list(src.tree):
            if isinstance(node, ast.Call) and call_name(node) in _LOCK_CTORS:
                chain = node.func
                base = chain.value if isinstance(chain, ast.Attribute) else None
                if base is None or (isinstance(base, ast.Name)
                                    and base.id == "threading"):
                    sites.add((src.path, node.lineno))
    return sites


def check(pkg: Package) -> List[Finding]:
    classes: Dict[str, ClassInfo] = {}
    for src in pkg:
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                classes[node.name] = _collect_class(src, node)
    findings = _find_thread_targets(pkg, classes)
    _merge_inherited(classes)

    for info in classes.values():
        findings.extend(_check_guarded_by(info))
        findings.extend(_check_lock_cycles(info))
        if info.thread_entries:
            findings.extend(_check_cross_thread(info))
    return findings


def _check_guarded_by(info: ClassInfo) -> List[Finding]:
    """DC201 (mixed mutations) and DC204 (unguarded reads of owned attrs)."""
    findings: List[Finding] = []
    attrs = {a.attr for a in info.mutations}
    for attr in sorted(attrs):
        if attr in info.lock_attrs or attr in info.safe_attrs:
            continue
        muts = [a for a in info.mutations if a.attr == attr]
        by_lock: Dict[str, List[Access]] = {}
        unguarded = []
        for a in muts:
            if a.locks:
                for lock in a.locks:
                    by_lock.setdefault(lock, []).append(a)
            else:
                unguarded.append(a)
        if not by_lock:
            continue
        owner, owned = max(by_lock.items(), key=lambda kv: len(kv[1]))
        if len(owned) >= 2 and unguarded and len(owned) > len(unguarded):
            for a in unguarded:
                findings.append(Finding(
                    info.path, a.line, "DC201",
                    f"{info.name}.{attr} is mutated here without "
                    f"{info.name}.{owner}, which guards its other "
                    f"{len(owned)} mutation site(s)"))
        if len(owned) >= 2 and not unguarded:
            mut_lines = {(a.line, a.attr) for a in muts}
            for r in info.reads:
                if r.attr != attr or owner in r.locks:
                    continue
                if (r.line, r.attr) in mut_lines:
                    continue  # the read half of a guarded mutation
                findings.append(Finding(
                    info.path, r.line, "DC204",
                    f"{info.name}.{attr} is lock-owned (every mutation "
                    f"holds {info.name}.{owner}) but this read does not "
                    "hold it — torn/stale state can escape here"))
    return findings


def _check_lock_cycles(info: ClassInfo) -> List[Finding]:
    findings: List[Finding] = []
    # locks acquired anywhere inside each method (acquire records don't
    # carry the method name — recover it via the method's line range)
    acquired_in: Dict[str, Set[str]] = {m: set() for m in info.methods}
    for m, fn in info.methods.items():
        lo, hi = fn.lineno, fn.end_lineno or fn.lineno
        for _held, lock, line in info.acquires:
            if lo <= line <= hi:
                acquired_in[m].add(lock)
    # transitive closure through same-class calls
    changed = True
    while changed:
        changed = False
        for m, callees in info.calls.items():
            for c in callees:
                extra = acquired_in.get(c, set()) - acquired_in.get(m, set())
                if extra:
                    acquired_in.setdefault(m, set()).update(extra)
                    changed = True
    edges: Dict[Tuple[str, str], int] = {}
    for held, lock, line in info.acquires:
        for h in held:
            if h != lock:
                edges.setdefault((h, lock), line)
    for held, callee, line in info.held_calls:
        for lock in acquired_in.get(callee, ()):
            for h in held:
                if h != lock:
                    edges.setdefault((h, lock), line)
    # cycle detection over the small per-class graph
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    def reachable(frm: str, to: str) -> bool:
        stack, seen = [frm], set()
        while stack:
            cur = stack.pop()
            if cur == to:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(graph.get(cur, ()))
        return False

    for (a, b), line in sorted(edges.items(), key=lambda kv: kv[1]):
        if reachable(b, a):
            findings.append(Finding(
                info.path, line, "DC202",
                f"lock-order cycle: {info.name}.{a} is held while "
                f"acquiring {info.name}.{b}, and elsewhere {info.name}.{b} "
                f"is held while (transitively) acquiring {info.name}.{a} — "
                "two threads taking the two orders deadlock"))
    return findings


def _check_cross_thread(info: ClassInfo) -> List[Finding]:
    findings: List[Finding] = []
    thread_side = _closure(info, set(info.thread_entries))
    guarded_attrs = {
        a.attr for a in info.mutations + info.reads if a.locks}
    mut_by_method: Dict[str, Set[str]] = {}
    ref_by_method: Dict[str, Set[str]] = {}
    for a in info.mutations:
        mut_by_method.setdefault(a.method, set()).add(a.attr)
    for a in info.reads + info.mutations:
        ref_by_method.setdefault(a.method, set()).add(a.attr)
    other_methods = [
        m for m in info.methods
        if m not in thread_side and m not in ("__init__", "__post_init__")]

    def closure_attrs(table, roots):
        out: Set[str] = set()
        for m in _closure(info, set(roots)):
            out |= table.get(m, set())
        return out

    t_mut = closure_attrs(mut_by_method, thread_side)
    t_ref = closure_attrs(ref_by_method, thread_side)
    flagged: Set[str] = set()
    for m in sorted(other_methods):
        o_mut = closure_attrs(mut_by_method, {m})
        o_ref = closure_attrs(ref_by_method, {m})
        for attr in sorted((t_mut & o_ref) | (o_mut & t_ref)):
            if attr in flagged or attr in guarded_attrs or \
                    attr in info.lock_attrs or attr in info.safe_attrs:
                continue
            if attr in info.bool_attrs and attr not in info.nonbool_assigned:
                continue  # pure boolean flag: GIL-atomic store, monotonic
            flagged.add(attr)
            fn = info.methods[m]
            findings.append(Finding(
                info.path, fn.lineno, "DC205",
                f"{info.name}.{attr} is shared across the thread boundary "
                f"(thread entry {sorted(info.thread_entries)}) and touched "
                f"by {m}() with no lock anywhere — guard it or document "
                "why the race is benign"))
    return findings
